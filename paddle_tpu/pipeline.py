"""Device-staged input pipeline — DEPRECATED shim.

DeviceChunkFeeder's machinery moved into paddle_tpu.datapipe (the
subsystem version adds parallel transfer streams, preallocated staging
buffers, per-stage stats and backpressure); this module keeps the original
class as a thin wrapper over datapipe.AsyncDeviceFeeder so existing call
sites keep working. New code should build a datapipe.DataPipe
(.batch().prefetch_to_device(chunk=K)) or use AsyncDeviceFeeder directly.

NAME COLLISION NOTE: this module is the *input*-pipeline shim and is
unrelated to ``paddle_tpu.parallel.pipeline``, the pipeline-*parallelism*
package (ProgramDesc partitioning over a ``pp`` mesh axis with 1F1B
microbatch scheduling — see docs/pipeline.md).
"""

import warnings

__all__ = ["DeviceChunkFeeder"]

# once per process, not per instantiation: Trainer loops rebuild their
# feeder every pass, and the default "default" warning filter dedupes by
# code location only per-module-registry, which user warning config
# (-W always, pytest filters) routinely defeats
_deprecation_warned = False


class DeviceChunkFeeder:
    """Deprecated: use datapipe.AsyncDeviceFeeder / DataPipe.

    Iterate device-resident [K, ...] feed dicts off a prefetch thread.

    reader():      yields per-step feed dicts {name: ndarray}
    chunk:         K, the number of steps per dispatch (Executor iters=K)
    place:         paddle_tpu Place the chunks are staged to (default: the
                   default jax device)
    capacity:      staged chunks buffered ahead (2 = classic double buffer)
    stage_fn:      optional override for the host->device staging step,
                   called as stage_fn(chunk_index, {name: stacked_ndarray})
                   -> {name: device_array}

    The tail is dropped if fewer than `chunk` batches remain. A single
    transfer thread is kept (the historical behavior: stage_fn sees chunk
    indices strictly in order); pass transfer_threads to
    AsyncDeviceFeeder for parallel transfer streams.
    """

    def __init__(self, reader, chunk, place=None, capacity=2, stage_fn=None):
        global _deprecation_warned
        if not _deprecation_warned:
            _deprecation_warned = True
            warnings.warn(
                "pipeline.DeviceChunkFeeder is deprecated; use "
                "datapipe.AsyncDeviceFeeder (or DataPipe.prefetch_to_device)",
                DeprecationWarning, stacklevel=2)
        from .datapipe import AsyncDeviceFeeder

        if int(chunk) < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        # donate=False: the legacy contract yields plain {name: array}
        # chunk dicts with no transfer-engine metadata riding along
        self._feeder = AsyncDeviceFeeder(
            reader, chunk=chunk, place=place, capacity=max(2, int(capacity)),
            transfer_threads=1, stage_fn=stage_fn, donate=False)

    def __iter__(self):
        return iter(self._feeder)
