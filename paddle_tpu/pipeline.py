"""Device-staged input pipeline.

Reference parity: operators/reader/create_double_buffer_reader_op.cc:34-69 —
a dedicated thread stages upcoming batches into DEVICE memory (the reference
keeps a GPU tensor cache fed by per-buffer CUDADeviceContexts) so the compute
stream never waits on host->device copies.

TPU adaptation: per-step dispatch latency, not link bandwidth, dominates a
naive feed loop on a tunneled chip (measured: ~25 ms for a 19 MB device_put
vs ~600 ms per jit dispatch), so staging happens at CHUNK granularity — K
consecutive batches are stacked into one [K, ...] array per feed name and
device_put once, sized for Executor.run(feed=chunk, iters=K), which runs the
K steps inside a single jit'd lax.scan dispatch. The prefetch thread stacks
and transfers chunk k+1 while chunk k trains.
"""

import threading
from queue import Queue

import numpy as np

__all__ = ["DeviceChunkFeeder"]


class DeviceChunkFeeder:
    """Iterate device-resident [K, ...] feed dicts off a prefetch thread.

    reader():      yields per-step feed dicts {name: ndarray}
    chunk:         K, the number of steps per dispatch (Executor iters=K)
    place:         paddle_tpu Place the chunks are staged to (default: the
                   default jax device)
    capacity:      staged chunks buffered ahead (2 = classic double buffer)
    stage_fn:      optional override for the host->device staging step,
                   called as stage_fn(chunk_index, {name: stacked_ndarray})
                   -> {name: device_array}. Default: jax.device_put per
                   array. Benchmarks use this to measure the pipeline
                   machinery with transfers taken off the critical path.

    The tail is dropped if fewer than `chunk` batches remain (a partial
    chunk would force a second XLA compile for the odd shape).
    """

    _END = object()

    def __init__(self, reader, chunk, place=None, capacity=2, stage_fn=None):
        self._reader = reader
        self._chunk = int(chunk)
        self._place = place
        self._cap = int(capacity)
        self._stage_fn = stage_fn
        if self._chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")

    def _device(self):
        if self._place is None:
            return None
        from .core.places import jax_device_for

        return jax_device_for(self._place)

    def __iter__(self):
        import jax

        q = Queue(maxsize=self._cap)
        stop = threading.Event()
        dev = self._device()

        def put(item):
            # bounded wait so a consumer that stopped iterating (e.g. its
            # train step raised) releases the worker instead of pinning
            # `capacity` chunk-sized device buffers behind a blocked put
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except Exception:  # queue.Full
                    continue
            return False

        def work():
            try:
                batches = []
                chunk_idx = 0
                for batch in self._reader():
                    if stop.is_set():
                        return
                    batches.append(batch)
                    if len(batches) < self._chunk:
                        continue
                    stacked = {
                        n: np.stack([np.asarray(b[n]) for b in batches], 0)
                        for n in batches[0]
                    }
                    if self._stage_fn is not None:
                        staged = self._stage_fn(chunk_idx, stacked)
                    else:
                        staged = {n: jax.device_put(a, dev)
                                  for n, a in stacked.items()}
                    chunk_idx += 1
                    if not put(staged):
                        return
                    batches = []
                put(self._END)
            except BaseException as e:  # surface reader errors to consumer
                put(e)

        t = threading.Thread(target=work, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except Exception:  # queue.Empty — drained
                pass
