"""Convergence detectors over the sampled health records.

Host-side rolling-window logic — it sees only the tiny per-sample
summaries (loss scalar, per-param norms), never tensors. Each fired
event:

  * bumps `health_events_total{kind=...}` in the monitor registry,
  * fires `trace.maybe_dump("health_<kind>")` so the flight recorder
    snapshots the run around the anomaly (cooldown-gated, never raises),
  * lands on a bounded queue that resilience.ResilientRunner drains
    after each step and maps through FLAGS_resilience_health_policy
    (warn | skip | restore) — the generalized form of the NaN-only
    guard, which stays as its own special case.

Kinds: loss_spike, loss_divergence, loss_plateau, loss_nonfinite,
grad_explode, grad_vanish, param_nonfinite.

Tuning notes live in docs/observability.md. The spike z-score uses a
std floor of 5% of |window mean| so a flat-but-noisy curve needs a real
excursion (not timer-grade jitter) to fire, and a cleanly decaying loss
never fires (its new samples sit below the window mean).
"""

import math
import threading

from .. import flags

flags.define("health_window", int, 20,
             "Rolling-window length (in sampled steps) for the loss "
             "spike z-score and the grad-explosion median baseline.")
flags.define("health_spike_z", float, 6.0,
             "Fire loss_spike when the sampled loss sits more than this "
             "many (floored) standard deviations above the window mean.")
flags.define("health_grad_explode", float, 1e4,
             "Absolute global-grad-norm threshold for grad_explode.")
flags.define("health_grad_ratio", float, 100.0,
             "Relative grad_explode threshold: norm > ratio * rolling "
             "median (needs >= 5 samples of history).")
flags.define("health_grad_vanish", float, 1e-9,
             "Fire grad_vanish when the global grad norm drops below "
             "this (0 disables).")
flags.define("health_diverge_factor", float, 10.0,
             "Fire loss_divergence when the loss EMA exceeds this "
             "factor times the best EMA seen so far.")
flags.define("health_plateau_patience", int, 0,
             "Fire loss_plateau after this many sampled steps without "
             "the loss EMA improving by health_plateau_tol "
             "(relative). 0 = plateau detection off.")
flags.define("health_plateau_tol", float, 1e-3,
             "Relative EMA improvement that resets the plateau counter.")
flags.define("health_ema", float, 0.98,
             "Decay of the loss exponential moving average.")

_MIN_HISTORY = 5  # samples before spike/explode baselines are trusted

_events_lock = threading.Lock()
_pending = []  # [(kind, step)], drained by resilience
_PENDING_CAP = 256


def _trace():
    from .. import trace
    return trace


def _registry():
    from ..monitor.step import registry
    return registry()


class DetectorBank:
    """Rolling state for one run's detectors. observe() one sampled
    record at a time; returns the list of event kinds fired."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.losses = []
        self.grad_norms = []
        self.ema = None
        self.best_ema = None
        self.stale_samples = 0

    # -- individual detectors ------------------------------------------

    def _check_loss(self, loss, events):
        window = max(_MIN_HISTORY, int(flags.get("health_window")))
        if not math.isfinite(loss):
            events.append("loss_nonfinite")
            return
        if len(self.losses) >= _MIN_HISTORY:
            hist = self.losses[-window:]
            mean = sum(hist) / len(hist)
            var = sum((x - mean) ** 2 for x in hist) / len(hist)
            std = max(math.sqrt(var), 0.05 * abs(mean), 1e-12)
            if (loss - mean) / std > flags.get("health_spike_z"):
                events.append("loss_spike")
        self.losses.append(loss)
        del self.losses[:-window]

        decay = flags.get("health_ema")
        self.ema = (loss if self.ema is None
                    else decay * self.ema + (1.0 - decay) * loss)
        tol = flags.get("health_plateau_tol")
        if (self.best_ema is None
                or self.ema < self.best_ema - tol * abs(self.best_ema)):
            self.best_ema = self.ema
            self.stale_samples = 0
        else:
            self.stale_samples += 1
        if (self.best_ema is not None
                and self.ema > flags.get("health_diverge_factor")
                * self.best_ema
                and self.ema - self.best_ema > 1e-6):
            events.append("loss_divergence")
        patience = flags.get("health_plateau_patience")
        if patience and self.stale_samples >= patience:
            events.append("loss_plateau")
            self.stale_samples = 0  # re-arm instead of firing every step

    def _check_grad(self, norm, events):
        if not math.isfinite(norm):
            return  # counted via nonfinite_params
        window = max(_MIN_HISTORY, int(flags.get("health_window")))
        fired = False
        if norm > flags.get("health_grad_explode"):
            events.append("grad_explode")
            fired = True
        elif len(self.grad_norms) >= _MIN_HISTORY:
            hist = sorted(self.grad_norms[-window:])
            median = hist[len(hist) // 2]
            if median > 0 and norm > flags.get("health_grad_ratio") * median:
                events.append("grad_explode")
                fired = True
        vanish = flags.get("health_grad_vanish")
        if not fired and vanish and norm < vanish:
            events.append("grad_vanish")
        if not fired:  # keep exploded samples out of the baseline
            self.grad_norms.append(norm)
            del self.grad_norms[:-window]

    # -- entry point ---------------------------------------------------

    def observe(self, record):
        events = []
        loss = record.get("loss")
        if loss is not None:
            self._check_loss(float(loss), events)
        record["loss_ema"] = self.ema
        norm = record.get("global_grad_norm")
        if norm is not None:
            self._check_grad(float(norm), events)
        if record.get("nonfinite_params"):
            events.append("param_nonfinite")
        for kind in events:
            _fire(kind, record.get("step"))
        return events


def _fire(kind, step):
    _registry().counter(
        "health_events_total",
        help="Model-health detector events by kind.", kind=kind).inc()
    _trace().maybe_dump("health_" + kind)
    with _events_lock:
        if len(_pending) < _PENDING_CAP:
            _pending.append((kind, step))


def drain_events():
    """Hand the queued (kind, step) events to the caller (resilience's
    per-step policy hook) and clear the queue."""
    with _events_lock:
        out = list(_pending)
        del _pending[:]
    return out


def pending_events():
    with _events_lock:
        return list(_pending)


def reset():
    with _events_lock:
        del _pending[:]
