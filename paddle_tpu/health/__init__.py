"""paddle_tpu.health — fused on-device model-health telemetry.

The monitor (PR 3) observes the process and trace (PR 6) observes
requests; this package observes the MODEL: per-param grad/weight norms,
update ratios and non-finite counts fused into the compiled step fn
(stats.py), a JSONL run ledger + gauges (ledger.py), convergence
detectors wired into trace dumps and the resilience policy
(detectors.py), and a run-parity comparison engine behind
`python -m paddle_tpu health summary|compare` (compare.py).

Executor integration (executor.py / parallel_executor.py):

    hplan = health.plan_if_enabled(program)     # None when FLAGS_health=0
    ... cache key gains ("health", hplan.digest or None) ...
    step  = executor_core.build_step_fn(
        program, fetch_names + hplan.fetch_names, ...)
    step  = hplan.wrap_step(step, len(fetch_names))   # after wire wrap,
                                                      # before pack/scan
    ... run; stats = fetches.pop() ...
    health.on_step(step0, iters, stats, fetch_names, fetches, mon=mon)

See docs/observability.md ("Model health") for flags and tuning.
"""

import math
import time

import numpy as np

from .. import flags
from . import compare, detectors, ledger, stats
from .compare import compare_ledgers, format_compare, summarize_ledger
from .detectors import DetectorBank, drain_events, pending_events
from .ledger import read_ledger
from .stats import (HealthPlan, STAT_FIELDS, plan_for, plan_if_enabled)

__all__ = [
    "HealthPlan", "STAT_FIELDS", "plan_for", "plan_if_enabled",
    "on_step", "enabled", "last_record", "reset",
    "DetectorBank", "drain_events", "pending_events",
    "read_ledger", "summarize_ledger", "compare_ledgers",
    "format_compare",
    "compare", "detectors", "ledger", "stats",
]

_bank = DetectorBank()
_last = {"record": None}


def enabled():
    return bool(flags.get("health"))


def last_record():
    """The most recent sampled record (tests / notebooks)."""
    return _last["record"]


def _find_loss(fetch_names, fetches, k, multi):
    """First float fetch that is one scalar per step — the documented
    loss heuristic (fetch the loss first to feed the detectors)."""
    for v in fetches or ():
        try:
            arr = np.asarray(v)
        except Exception:
            continue
        if arr.dtype.kind != "f":
            continue
        if multi:
            if arr.ndim >= 1 and arr.shape[0] == k and arr.size == k:
                return arr.reshape(k).astype(np.float64)
        elif arr.size == 1:
            return arr.reshape(1).astype(np.float64)
    return None


def _chaos_scales(step):
    """(loss_scale, grad_scale) from the installed chaos monkey."""
    from ..resilience import chaos  # lazy: resilience imports health

    monkey = chaos.active()
    if monkey is None:
        return 1.0, 1.0
    return monkey.poison_health(step)


def on_step(step0, iters, stats_dev, fetch_names, fetches,
            mon=None, kind="executor"):
    """Host side of the health path: sample, journal, detect.

    Called by the executors after a health-wrapped dispatch with the
    stats pytree popped off the fetch list. `step0` is the program step
    index of the first iteration in the dispatch; `iters` is None for a
    single step or the scan length K. Steps where
    `step % FLAGS_health_interval != 0` cost nothing on the host — the
    device stats leaves are simply dropped without a readback.
    """
    interval = max(1, int(flags.get("health_interval") or 1))
    multi = iters is not None
    k = int(iters) if multi else 1
    sampled = [i for i in range(k) if (step0 + i) % interval == 0]
    if not sampled:
        return
    host = {label: np.asarray(v, dtype=np.float64).reshape(k, len(
        STAT_FIELDS)) for label, v in stats_dev.items()}
    loss_vec = _find_loss(fetch_names, fetches, k, multi)
    last_rec = None
    for i in sampled:
        step = step0 + i
        params, nonfinite, gsq_total = {}, 0, 0.0
        for label, a in sorted(host.items()):
            gsq, wsq, dsq, bad = (float(x) for x in a[i])
            gn = math.sqrt(gsq) if gsq >= 0 else float("nan")
            wn = math.sqrt(wsq) if wsq >= 0 else float("nan")
            dn = math.sqrt(dsq) if dsq >= 0 else float("nan")
            params[label] = {
                "grad_norm": gn,
                "weight_norm": wn,
                "update_ratio": (dn / wn) if wn > 0 else 0.0,
                "nonfinite": int(bad),
            }
            if bad:
                nonfinite += 1
            gsq_total += gsq
        loss = float(loss_vec[i]) if loss_vec is not None else None
        ggn = math.sqrt(gsq_total) if gsq_total >= 0 else float("nan")

        loss_scale, grad_scale = _chaos_scales(step)
        if loss is not None and loss_scale != 1.0:
            loss *= loss_scale
        if grad_scale != 1.0:
            for st in params.values():
                st["grad_norm"] *= grad_scale
            ggn *= grad_scale

        rec = {"ts": time.time(), "step": int(step), "kind": kind,
               "loss": loss, "global_grad_norm": ggn,
               "nonfinite_params": nonfinite, "params": params}
        rec["events"] = _bank.observe(rec)  # also sets rec["loss_ema"]
        ledger.write_record(rec)
        ledger.set_gauges(rec)
        last_rec = rec
    _last["record"] = last_rec
    if mon is not None and last_rec is not None:
        if mon.extra is None:
            mon.extra = {}
        mon.extra["health"] = {
            "step": last_rec["step"],
            "loss": last_rec["loss"],
            "loss_ema": last_rec["loss_ema"],
            "global_grad_norm": last_rec["global_grad_norm"],
            "nonfinite_params": last_rec["nonfinite_params"],
            "events": last_rec["events"],
        }


def reset():
    """Forget plans, detector state, queued events, and the ledger
    writer (tests; also lets one process run independent experiments)."""
    stats.reset()
    ledger.reset()
    detectors.reset()
    _bank.reset()
    _last["record"] = None
