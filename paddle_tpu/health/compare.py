"""Run-comparison engine: convergence parity between two health ledgers.

Two runs "converged equivalently" when, over their step-aligned loss
trajectories:

  * the final loss (at the last common sampled step) differs by at most
    `tol_final`,
  * the max step-aligned deviation stays within `tol_traj`,
  * they agree on divergence: either neither run fired a divergence-class
    detector event, or both did.

This is the standard parity gate bench.py and tools/green_gate.sh use to
assert e.g. FLAGS_zero1 / FLAGS_autoshard on-vs-off equivalence; the CLI
(`python -m paddle_tpu health compare A B`) exits 0 on parity, 1 on a
violated tolerance, 2 on an unreadable ledger.
"""

import math

# Detector kinds that mean "this run left the healthy regime" — used for
# the divergence-step component of parity.
DIVERGENCE_KINDS = ("loss_spike", "loss_divergence", "loss_nonfinite",
                    "grad_explode", "param_nonfinite")


def _loss_curve(records):
    """-> {step: loss} over records that carry a finite-or-not loss."""
    curve = {}
    for r in records:
        step, loss = r.get("step"), r.get("loss")
        if step is None or loss is None:
            continue
        curve[int(step)] = float(loss)
    return curve


def _divergence_step(records):
    for r in records:
        events = r.get("events") or ()
        if any(k in DIVERGENCE_KINDS for k in events):
            return int(r.get("step", -1))
    return None


def summarize_ledger(records):
    """Aggregate a health ledger -> summary dict (cli renders it)."""
    curve = _loss_curve(records)
    steps = sorted(curve)
    finite = [curve[s] for s in steps if math.isfinite(curve[s])]
    events = {}
    for r in records:
        for k in (r.get("events") or ()):
            events[k] = events.get(k, 0) + 1
    norms = [float(r["global_grad_norm"]) for r in records
             if r.get("global_grad_norm") is not None
             and math.isfinite(float(r["global_grad_norm"]))]
    emas = [r["loss_ema"] for r in records
            if r.get("loss_ema") is not None]
    return {
        "records": len(records),
        "steps": len(steps),
        "first_step": steps[0] if steps else None,
        "last_step": steps[-1] if steps else None,
        "final_loss": curve[steps[-1]] if steps else None,
        "min_loss": min(finite) if finite else None,
        "loss_ema_final": emas[-1] if emas else None,
        "max_global_grad_norm": max(norms) if norms else None,
        "nonfinite_steps": sum(
            1 for s in steps if not math.isfinite(curve[s])),
        "events": events,
        "divergence_step": _divergence_step(records),
    }


def compare_ledgers(a, b, tol_final=1e-3, tol_traj=5e-3):
    """Parity report between two ledgers (lists of records)."""
    ca, cb = _loss_curve(a), _loss_curve(b)
    common = sorted(set(ca) & set(cb))
    report = {
        "steps_a": len(ca), "steps_b": len(cb),
        "common_steps": len(common),
        "tol_final": tol_final, "tol_traj": tol_traj,
    }
    if not common:
        report.update(ok=False, reason="no overlapping sampled steps")
        return report

    def dev(s):
        d = abs(ca[s] - cb[s])
        return d if math.isfinite(d) else float("inf")

    worst = max(common, key=dev)
    traj_dev = dev(worst)
    final_step = common[-1]
    final_delta = dev(final_step)
    div_a, div_b = _divergence_step(a), _divergence_step(b)
    div_ok = (div_a is None) == (div_b is None)

    checks = {
        "final_loss": final_delta <= tol_final,
        "trajectory": traj_dev <= tol_traj,
        "divergence": div_ok,
    }
    report.update(
        final_step=final_step,
        final_loss_a=ca[final_step], final_loss_b=cb[final_step],
        final_loss_delta=final_delta,
        traj_max_abs_diff=traj_dev, traj_worst_step=worst,
        divergence_step_a=div_a, divergence_step_b=div_b,
        checks=checks,
        ok=all(checks.values()),
    )
    if not report["ok"]:
        report["reason"] = ", ".join(
            f"{k} check failed" for k, v in checks.items() if not v)
    return report


def format_ledger_summary(s):
    lines = [f"records: {s['records']}  sampled steps: {s['steps']}  "
             f"range: [{s['first_step']}, {s['last_step']}]"]
    if s["final_loss"] is not None:
        ema = (f"  ema={s['loss_ema_final']:.6g}"
               if s["loss_ema_final"] is not None else "")
        lines.append(f"loss: final={s['final_loss']:.6g} "
                     f"min={s['min_loss']:.6g}{ema}")
    if s["max_global_grad_norm"] is not None:
        lines.append(
            f"max global grad norm: {s['max_global_grad_norm']:.6g}")
    if s["nonfinite_steps"]:
        lines.append(f"non-finite loss steps: {s['nonfinite_steps']}")
    if s["events"]:
        lines.append("events: " + ", ".join(
            f"{k} x{n}" for k, n in sorted(s["events"].items())))
    ds = s["divergence_step"]
    lines.append("divergence: none" if ds is None
                 else f"divergence: first at step {ds}")
    return "\n".join(lines)


def format_compare(r):
    lines = [f"common sampled steps: {r['common_steps']} "
             f"(a={r['steps_a']}, b={r['steps_b']})"]
    if r["common_steps"]:
        lines.append(
            f"final loss @step {r['final_step']}: "
            f"a={r['final_loss_a']:.6g} b={r['final_loss_b']:.6g} "
            f"delta={r['final_loss_delta']:.3g} "
            f"(tol {r['tol_final']:.3g})")
        lines.append(
            f"trajectory max |a-b|: {r['traj_max_abs_diff']:.3g} "
            f"@step {r['traj_worst_step']} (tol {r['tol_traj']:.3g})")
        da, db = r["divergence_step_a"], r["divergence_step_b"]
        lines.append(
            f"divergence: a={'none' if da is None else f'step {da}'} "
            f"b={'none' if db is None else f'step {db}'}")
        for k, ok in r["checks"].items():
            lines.append(f"  [{'ok' if ok else 'FAIL'}] {k}")
    lines.append("PARITY: " + ("ok" if r["ok"]
                               else f"FAIL ({r.get('reason', '?')})"))
    return "\n".join(lines)
