"""Fused on-device model-health statistics.

When FLAGS_health > 0 the executors extend the step function they are
about to compile: the per-param gradients (already live in the traced
environment — they feed the optimizer ops) are appended to the fetch
list, and `HealthPlan.wrap_step` folds them into ONE compact stats leaf
per parameter inside the jit:

    stats[param] = [sum(g^2), sum(w^2), sum((w_new - w_old)^2),
                    nonfinite(g)]          (float32, shape [4])

so the per-step cost is one reduction per tensor fused into the
already-compiled function — no extra dispatch, no full-tensor readback.

Sharding-awareness falls out of the layout rather than being re-derived
here: under FLAGS_zero1 the optimizer op reads `grad@zero1_rs`, the
reduce-scattered [N, shard] grad whose zero padding makes the shard-local
sum of squares exactly the full grad's, and under autoshard GSPMD lowers
the jnp reductions shard-locally with a tiny combine — stats are computed
on shards and combined, never regathered.

The off path (FLAGS_health == 0) is a single flag check in
`plan_if_enabled`. Host readback, ledger writes, gauges, and detectors
run only every FLAGS_health_interval steps (health/__init__.on_step).
"""

import hashlib

from .. import flags
from ..core.framework import VarType

flags.define("health", int, 0,
             "Model-health telemetry: 0 = off (one flag check per run "
             "call), >0 = fuse per-param grad/weight/update-ratio/"
             "non-finite stats into the compiled step and journal them "
             "to FLAGS_health_ledger every FLAGS_health_interval steps.")
flags.define("health_interval", int, 1,
             "Sample model-health stats every N steps. The reductions "
             "run fused in-graph each step (keeping one trace); readback "
             "+ ledger + detectors fire only on sampled steps.")

# Fields of each per-param stats leaf, in order.
STAT_FIELDS = ("grad_sq", "weight_sq", "delta_sq", "nonfinite")

# zero1.apply rewrites the optimizer op's Param input to the shard-layout
# alias; the canonical (full, persistable) parameter keeps its plain name.
_PARAM_SUFFIXES = ("@zero1_shard",)

_plan_cache = {}  # (id(program), mutation) -> HealthPlan


class HealthPlan:
    """Which (param, grad) pairs a program's step fn collects stats for."""

    __slots__ = ("pairs", "digest")

    def __init__(self, pairs):
        self.pairs = tuple(pairs)  # (label, grad_env_name)
        self.digest = hashlib.sha1(
            repr(self.pairs).encode()).hexdigest()[:12]

    @property
    def fetch_names(self):
        """Grad env names to append to the step fn's fetch list."""
        return [g for _, g in self.pairs]

    def wrap_step(self, step, n_user):
        """Wrap a built step fn: consume the appended grad fetches,
        emit one {label: [4]f32} stats dict as a single extra fetch.

        Applied after the wire wrapper and before PackPlan/multi-step
        wrapping, so `mut_state`/`new_mut` carry plain var names and the
        scan stacks only the [4]-element leaves, never raw grads.
        """
        import jax.numpy as jnp

        pairs = self.pairs

        def health_step(mut_state, const_state, feeds, rng):
            fetches, new_mut = step(mut_state, const_state, feeds, rng)
            user, grads = fetches[:n_user], fetches[n_user:]
            stats = {}
            for (label, _), g in zip(pairs, grads):
                g32 = jnp.asarray(g).astype(jnp.float32)
                grad_sq = jnp.sum(g32 * g32)
                bad = jnp.sum(
                    (~jnp.isfinite(g32)).astype(jnp.float32))
                w_old = mut_state.get(label)
                if w_old is None:
                    w_old = const_state.get(label)
                w_new = new_mut.get(label)
                if w_new is None:
                    w_new = w_old
                if w_old is not None:
                    wo = jnp.asarray(w_old).astype(jnp.float32)
                    wn = jnp.asarray(w_new).astype(jnp.float32)
                    weight_sq = jnp.sum(wn * wn)
                    d = wn - wo
                    delta_sq = jnp.sum(d * d)
                else:
                    weight_sq = jnp.float32(0.0)
                    delta_sq = jnp.float32(0.0)
                stats[label] = jnp.stack(
                    [grad_sq, weight_sq, delta_sq, bad])
            return list(user) + [stats], new_mut

        return health_step


def plan_for(program):
    """Scan a (resolved) program for optimizer (Param, Grad) pairs.

    Every optimizer op names its inputs through the "Param"/"Grad" slots;
    under FLAGS_zero1 the resolved program carries `p@zero1_shard` /
    `g@zero1_rs` instead — the label strips the shard suffix back to the
    canonical param name (which stays persistable and in mutable state,
    giving the weight-side stats on the full tensor). Sparse
    (SELECTED_ROWS) and ragged grads have no dense norm and are skipped,
    mirroring zero1.build_plan.
    """
    key = (id(program), program._mutation)
    plan = _plan_cache.get(key)
    if plan is not None:
        return plan
    gb = program.global_block()
    pairs, seen = [], set()
    for op in gb.ops:
        pname = (op.inputs.get("Param") or [None])[0]
        gname = (op.inputs.get("Grad") or [None])[0]
        if not pname or not gname:
            continue
        label = pname
        for suf in _PARAM_SUFFIXES:
            if label.endswith(suf):
                label = label[:-len(suf)]
        if label in seen:
            continue
        gvar = gb.vars.get(gname)
        if gvar is not None and (
                gvar.type == VarType.SELECTED_ROWS
                or getattr(gvar, "lod_level", 0)):
            continue
        pvar = gb.vars.get(label)
        if pvar is None or not getattr(pvar, "persistable", False):
            continue
        seen.add(label)
        pairs.append((label, gname))
    plan = HealthPlan(pairs)
    if len(_plan_cache) > 256:
        _plan_cache.clear()
    _plan_cache[key] = plan
    return plan


def plan_if_enabled(program):
    """One flag check when health is off; else the program's plan
    (None when the program has no optimizer ops to watch)."""
    if not flags.get("health"):
        return None
    plan = plan_for(program)
    return plan if plan.pairs else None


def reset():
    _plan_cache.clear()
