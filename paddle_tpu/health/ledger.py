"""Run ledger: the model-health JSONL journal and its monitor gauges.

One record per sampled step, written through the same JournalWriter the
monitor step journal uses (same torn-line tolerance on read, same
FLAGS_monitor_journal_max_mb size-gated rotation):

    {"ts": ..., "step": 12, "kind": "executor", "loss": 0.41,
     "loss_ema": 0.44, "global_grad_norm": 1.7, "nonfinite_params": 0,
     "params": {"fc_0.w_0": {"grad_norm": ..., "weight_norm": ...,
                             "update_ratio": ..., "nonfinite": 0}, ...},
     "events": ["loss_spike", ...]}

The writer is lazy and re-opens when FLAGS_health_ledger changes, so
tests and multi-run processes can retarget it with flag_guard.
"""

import threading

from .. import flags
from ..monitor.journal import JournalWriter, read_journal
from ..monitor.step import registry as _monitor_registry

flags.define("health_ledger", str, "",
             "Path of the model-health JSONL run ledger (empty = no "
             "ledger file; gauges and detectors still run).")

_lock = threading.Lock()
_state = {"path": None, "writer": None}


def _writer():
    path = flags.get("health_ledger")
    if not path:
        return None
    with _lock:
        if _state["path"] != path:
            if _state["writer"] is not None:
                _state["writer"].close()
            _state["writer"] = JournalWriter(path)
            _state["path"] = path
        return _state["writer"]


def write_record(record):
    w = _writer()
    if w is not None:
        w.write(record)


def set_gauges(record):
    """Publish the sampled stats to the monitor registry."""
    reg = _monitor_registry()
    for label, st in record.get("params", {}).items():
        reg.gauge("health_grad_norm",
                  help="Per-parameter gradient L2 norm (sampled).",
                  param=label).set(st["grad_norm"])
    reg.gauge("health_nonfinite_params",
              help="Parameters whose grad held non-finite values at the "
                   "last sampled step.").set(
        float(record.get("nonfinite_params", 0)))
    g = record.get("global_grad_norm")
    if g is not None:
        reg.gauge("health_global_grad_norm",
                  help="Global gradient L2 norm (sampled).").set(g)
    ema = record.get("loss_ema")
    if ema is not None:
        reg.gauge("health_loss_ema",
                  help="Exponential moving average of the training "
                       "loss (sampled).").set(ema)


def read_ledger(path):
    """Parse a health ledger (JSONL, torn lines skipped, `<path>.1`
    rollover segment read first when present)."""
    return read_journal(path)


def reset():
    with _lock:
        if _state["writer"] is not None:
            _state["writer"].close()
        _state["path"] = None
        _state["writer"] = None
