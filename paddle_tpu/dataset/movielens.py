"""MovieLens-1M recommender dataset (reference python/paddle/dataset/movielens.py).

Samples: (user_id, gender_id, age_id, job_id, movie_id, category_ids,
title_ids, rating). Synthetic fallback with consistent entity tables.
"""

import numpy as np

from . import common

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table", "movie_categories"]

NUM_USERS = 1000
NUM_MOVIES = 800
NUM_JOBS = 21
NUM_CATEGORIES = 18
TITLE_VOCAB = 1500
AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]
TRAIN_SIZE = 8192
TEST_SIZE = 1024


def max_user_id():
    return NUM_USERS


def max_movie_id():
    return NUM_MOVIES


def max_job_id():
    return NUM_JOBS - 1


def age_table():
    return list(AGE_TABLE)


def movie_categories():
    return {f"cat{i}": i for i in range(NUM_CATEGORIES)}


def _reader(split, size):
    def reader():
        rs = common.synthetic_rng("movielens", split)
        ers = common.synthetic_rng("movielens", "entities")
        user_bias = ers.randn(NUM_USERS + 1)
        movie_bias = ers.randn(NUM_MOVIES + 1)
        for _ in range(size):
            u = rs.randint(1, NUM_USERS + 1)
            m = rs.randint(1, NUM_MOVIES + 1)
            gender = rs.randint(2)
            age = rs.randint(len(AGE_TABLE))
            job = rs.randint(NUM_JOBS)
            cats = rs.randint(0, NUM_CATEGORIES,
                              rs.randint(1, 4)).tolist()
            title = rs.randint(0, TITLE_VOCAB, rs.randint(2, 6)).tolist()
            score = 3.0 + user_bias[u] + movie_bias[m] + 0.3 * rs.randn()
            rating = float(np.clip(round(score), 1, 5))
            yield u, gender, age, job, m, cats, title, rating

    return reader


def train():
    return _reader("train", TRAIN_SIZE)


def test():
    return _reader("test", TEST_SIZE)
