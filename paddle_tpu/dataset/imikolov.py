"""PTB-style language-model dataset (reference python/paddle/dataset/imikolov.py).

build_dict() -> {word: id}; train/test yield n-gram tuples of word ids
(default n=5, as used by the word2vec book chapter).
"""

import numpy as np

from . import common

__all__ = ["build_dict", "train", "test"]

VOCAB_SIZE = 2074  # reference's min-freq-cutoff dict size ballpark
TRAIN_SIZE = 4096
TEST_SIZE = 512


def build_dict(min_word_freq=50):
    d = {f"w{i}": i for i in range(VOCAB_SIZE - 2)}
    d["<s>"] = VOCAB_SIZE - 2
    d["<e>"] = VOCAB_SIZE - 1
    return d


def _reader(split, size, n):
    def reader():
        rs = common.synthetic_rng("imikolov", split)
        # markov-ish: next word depends on previous (mod structure) so the
        # n-gram model has signal to learn
        for _ in range(size):
            start = rs.randint(VOCAB_SIZE)
            seq = [start]
            for _ in range(n - 1):
                nxt = (seq[-1] * 31 + 7 + rs.randint(5)) % VOCAB_SIZE
                seq.append(int(nxt))
            yield tuple(seq)

    return reader


def train(word_idx=None, n=5):
    return _reader("train", TRAIN_SIZE, n)


def test(word_idx=None, n=5):
    return _reader("test", TEST_SIZE, n)
