"""CoNLL-2005 semantic role labeling dataset (reference
python/paddle/dataset/conll05.py).

Samples are 9-slot tuples of equal-length token sequences:
  (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids, mark, labels)
— the predicate-context windows and IOB label ids the SRL model consumes.
get_dict() -> (word_dict, verb_dict, label_dict); get_embedding() -> path
placeholder (the reference ships pretrained emb; synthetic build returns
a deterministic matrix instead).

Synthetic fallback: labels correlate with distance to the marked predicate
so an SRL model has real signal to fit.
"""

import numpy as np

from . import common

__all__ = ["get_dict", "get_embedding", "test"]

WORD_DICT_LEN = 44068
VERB_DICT_LEN = 3162
LABEL_DICT_LEN = 67  # IOB tags over 33 role types + O
TEST_SIZE = 512


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_DICT_LEN)}
    verb_dict = {f"v{i}": i for i in range(VERB_DICT_LEN)}
    label_dict = {f"l{i}": i for i in range(LABEL_DICT_LEN)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Deterministic [WORD_DICT_LEN, 32] embedding matrix (stand-in for the
    reference's downloaded emb file)."""
    rs = common.synthetic_rng("conll05", "emb")
    return rs.uniform(-0.1, 0.1, (WORD_DICT_LEN, 32)).astype(np.float32)


def _reader(split, size):
    def reader():
        rs = common.synthetic_rng("conll05", split)
        for _ in range(size):
            n = int(rs.randint(5, 40))
            words = rs.randint(0, WORD_DICT_LEN, n)
            pred_pos = int(rs.randint(n))
            verb = int(rs.randint(VERB_DICT_LEN))

            def ctx(off):
                j = min(max(pred_pos + off, 0), n - 1)
                return np.full(n, words[j], dtype=np.int64)

            mark = np.zeros(n, np.int64)
            mark[pred_pos] = 1
            # role labels depend on signed distance to the predicate
            dist = np.arange(n) - pred_pos
            labels = (np.abs(dist) * 2 + (dist < 0)) % LABEL_DICT_LEN
            yield (words.tolist(), ctx(-2).tolist(), ctx(-1).tolist(),
                   ctx(0).tolist(), ctx(1).tolist(), ctx(2).tolist(),
                   np.full(n, verb, np.int64).tolist(), mark.tolist(),
                   labels.astype(np.int64).tolist())

    return reader


def test():
    return _reader("test", TEST_SIZE)
