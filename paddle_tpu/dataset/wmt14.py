"""WMT14 fr-en translation dataset (reference python/paddle/dataset/wmt14.py).

Samples: (src_ids, trg_ids, trg_next_ids) — source sentence, target sentence
with <s> prefix, target shifted with <e> suffix. Synthetic fallback: target
is a deterministic token-wise function of source, so seq2seq models can
genuinely learn the mapping.
"""

import numpy as np

from . import common

__all__ = ["train", "test"]

DICT_SIZE = 30000
START_ID, END_ID, UNK_ID = 0, 1, 2
TRAIN_SIZE = 2048
TEST_SIZE = 256


def _reader(split, size, src_dict_size, trg_dict_size):
    src_v = min(src_dict_size, DICT_SIZE)
    trg_v = min(trg_dict_size, DICT_SIZE)

    def reader():
        rs = common.synthetic_rng("wmt14", split)
        for _ in range(size):
            n = rs.randint(4, 16)
            src = rs.randint(3, src_v, n).tolist()
            trg = [(w * 17 + 3) % (trg_v - 3) + 3 for w in src]
            yield src, [START_ID] + trg, trg + [END_ID]

    return reader


def train(dict_size=DICT_SIZE):
    return _reader("train", TRAIN_SIZE, dict_size, dict_size)


def test(dict_size=DICT_SIZE):
    return _reader("test", TEST_SIZE, dict_size, dict_size)
