"""Dataset cache/location helpers (reference python/paddle/dataset/common.py).

The reference downloads archives with md5 caching into ~/.cache/paddle/dataset.
This build runs with zero network egress: each dataset first looks for files
in the same cache layout (so real data dropped there is used), and otherwise
falls back to a DETERMINISTIC synthetic generator with the exact sample
schema of the real dataset. Training pipelines, shapes, dtypes and LoD
structure are identical either way; only the underlying bits differ.
"""

import hashlib
import os
import pickle

import numpy as np

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle/dataset"))

__all__ = ["DATA_HOME", "md5file", "cached_path", "split", "cluster_files_reader"]


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def cached_path(module, fname):
    """Path to a locally-provided dataset file, or None if absent."""
    p = os.path.join(DATA_HOME, module, fname)
    return p if os.path.exists(p) else None


def download(url, module, md5sum=None, save_name=None):
    """reference common.py:download — zero-egress build: only resolves files
    already present in DATA_HOME; raises otherwise."""
    fname = save_name or url.split("/")[-1]
    p = cached_path(module, fname)
    if p is None:
        raise IOError(
            f"dataset file {module}/{fname} not present under {DATA_HOME} "
            "and network egress is disabled; drop the file there or use the "
            "synthetic reader")
    return p


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """split a reader's samples into chunked pickle files
    (reference common.py:split)."""
    indx_f = 0
    batch = []
    outs = []

    def flush():
        nonlocal indx_f, batch
        if not batch:
            return
        out = suffix % indx_f
        with open(out, "wb") as f:
            dumper(batch, f)
        outs.append(out)
        batch = []
        indx_f += 1

    for sample in reader():
        batch.append(sample)
        if len(batch) == line_count:
            flush()
    flush()
    return outs


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """reader over this trainer's shard of chunked files
    (reference common.py:cluster_files_reader)."""
    import glob

    def reader():
        flist = sorted(glob.glob(files_pattern))
        my = flist[trainer_id::trainer_count]
        for fn in my:
            with open(fn, "rb") as f:
                for sample in loader(f):
                    yield sample

    return reader


# ---------------------------------------------------------------------------
# synthetic fallback machinery
# ---------------------------------------------------------------------------
def synthetic_rng(name, split_name):
    """Deterministic per-(dataset, split) RNG."""
    seed = int.from_bytes(
        hashlib.md5(f"{name}:{split_name}".encode()).digest()[:4], "little")
    return np.random.RandomState(seed)
