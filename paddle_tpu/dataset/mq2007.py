"""LETOR MQ2007 learning-to-rank dataset (reference
python/paddle/dataset/mq2007.py).

Three reader formats selected by `format`:
  pointwise: (feature [46] float32, relevance_score float)
  pairwise:  (relevant_doc [46], irrelevant_doc [46]) per query pair
  listwise:  (label_list, feature_list) per query

Synthetic fallback: each query draws a hidden weight vector; relevance is
a noisy linear score of the 46 LETOR features, so rank models can learn
genuine orderings.
"""

import numpy as np

from . import common

__all__ = ["train", "test"]

FEATURE_DIM = 46
TRAIN_QUERIES = 128
TEST_QUERIES = 32


def _gen_query(rs):
    ndocs = int(rs.randint(5, 20))
    feats = rs.rand(ndocs, FEATURE_DIM).astype(np.float32)
    w = rs.randn(FEATURE_DIM).astype(np.float32)
    score = feats @ w + rs.randn(ndocs).astype(np.float32) * 0.1
    # LETOR relevance grades 0/1/2 by score tercile
    order = np.argsort(score)
    rel = np.zeros(ndocs, np.int64)
    rel[order[ndocs // 3:]] = 1
    rel[order[2 * ndocs // 3:]] = 2
    return feats, rel


def _reader(split, nqueries, format):
    def reader():
        rs = common.synthetic_rng("mq2007", split)
        for _ in range(nqueries):
            feats, rel = _gen_query(rs)
            if format == "pointwise":
                for f, r in zip(feats, rel):
                    yield f, float(r)
            elif format == "pairwise":
                for i in range(len(rel)):
                    for j in range(len(rel)):
                        if rel[i] > rel[j]:
                            yield feats[i], feats[j]
            elif format == "listwise":
                yield rel.tolist(), [f for f in feats]
            else:
                raise ValueError(f"unknown format {format!r}")

    return reader


def train(format="pairwise"):
    return _reader("train", TRAIN_QUERIES, format)


def test(format="pairwise"):
    return _reader("test", TEST_QUERIES, format)
