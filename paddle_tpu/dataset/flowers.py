"""102-category flowers dataset (reference python/paddle/dataset/flowers.py).

Samples: (image: float32[3*224*224] flattened CHW in [0,1], label: int).
Synthetic fallback mirrors cifar's class-structured generator at 224x224.
"""

import numpy as np

from . import common

__all__ = ["train", "test", "valid"]

NUM_CLASSES = 102
TRAIN_SIZE = 512
TEST_SIZE = 128


def _synthetic(split, size):
    def reader():
        rs = common.synthetic_rng("flowers", split)
        protos = common.synthetic_rng("flowers", "protos").rand(
            NUM_CLASSES, 3, 7, 7)
        for _ in range(size):
            y = rs.randint(NUM_CLASSES)
            base = np.kron(protos[y], np.ones((1, 32, 32)))  # 3x224x224
            x = np.clip(base + 0.1 * rs.randn(3, 224, 224), 0, 1)
            yield x.astype("float32").flatten(), int(y)

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return _synthetic("train", TRAIN_SIZE)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return _synthetic("test", TEST_SIZE)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _synthetic("valid", TEST_SIZE)
