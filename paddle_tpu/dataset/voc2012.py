"""PASCAL VOC2012 segmentation dataset (reference
python/paddle/dataset/voc2012.py).

Samples: (image [3, H, W] float32 in [0,1], label_mask [H, W] int32 with
class ids 0..20, 255 = void border). The reference decodes JPEG/PNG pairs;
the synthetic fallback paints class rectangles whose pixel statistics
correlate with their class id, so segmentation models have learnable
signal at identical shapes/dtypes.
"""

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

NUM_CLASSES = 21
H = W = 64  # synthetic resolution (the reference resizes anyway)
TRAIN_SIZE = 256
TEST_SIZE = 64


def _reader(split, size):
    def reader():
        rs = common.synthetic_rng("voc2012", split)
        for _ in range(size):
            img = rs.rand(3, H, W).astype(np.float32) * 0.2
            mask = np.zeros((H, W), np.int32)
            for _obj in range(int(rs.randint(1, 4))):
                c = int(rs.randint(1, NUM_CLASSES))
                y0, x0 = rs.randint(0, H // 2), rs.randint(0, W // 2)
                h, w = rs.randint(8, H // 2), rs.randint(8, W // 2)
                mask[y0:y0 + h, x0:x0 + w] = c
                # class-correlated appearance
                img[:, y0:y0 + h, x0:x0 + w] = (
                    np.asarray([c, (c * 3) % NUM_CLASSES,
                                (c * 7) % NUM_CLASSES], np.float32)
                    .reshape(3, 1, 1) / NUM_CLASSES
                    + rs.rand(3, h, w).astype(np.float32) * 0.1)
            # void border (255) like the real annotations
            mask[0, :] = mask[-1, :] = mask[:, 0] = mask[:, -1] = 255
            yield img, mask

    return reader


def train():
    return _reader("train", TRAIN_SIZE)


def test():
    return _reader("test", TEST_SIZE)


def val():
    return _reader("val", TEST_SIZE)
