"""Dataset package (reference python/paddle/dataset/__init__.py).

Each module provides reader creators with the reference's exact sample
schema; real files under DATA_HOME are used when present, else
deterministic synthetic data with learnable structure (zero-egress build).
"""

from . import common
from . import mnist
from . import cifar
from . import imdb
from . import imikolov
from . import uci_housing
from . import wmt14
from . import wmt16
from . import flowers
from . import movielens
from . import conll05
from . import sentiment
from . import voc2012
from . import mq2007

__all__ = ["common", "mnist", "cifar", "imdb", "imikolov", "uci_housing",
           "wmt14", "wmt16", "flowers", "movielens", "conll05", "sentiment",
           "voc2012", "mq2007"]
