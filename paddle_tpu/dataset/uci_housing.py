"""UCI housing regression dataset (reference python/paddle/dataset/uci_housing.py).

Samples: (features: float32[13], price: float32[1]). Synthetic fallback is an
actual linear model + noise so fit_a_line converges.
"""

import numpy as np

from . import common

__all__ = ["train", "test"]

TRAIN_SIZE = 404
TEST_SIZE = 102
FEATURE_NUM = 13


def _synthetic(split, size):
    def reader():
        rs = common.synthetic_rng("uci_housing", split)
        w = common.synthetic_rng("uci_housing", "w").randn(FEATURE_NUM)
        for _ in range(size):
            x = rs.randn(FEATURE_NUM).astype("float32")
            y = float(x @ w + 0.1 * rs.randn())
            yield x, np.array([y], dtype="float32")

    return reader


def train():
    return _synthetic("train", TRAIN_SIZE)


def test():
    return _synthetic("test", TEST_SIZE)
