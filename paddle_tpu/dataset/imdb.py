"""IMDB sentiment dataset (reference python/paddle/dataset/imdb.py).

Samples: (word_ids: list[int], label: 0/1). word_dict() -> {word: id}.
Synthetic fallback: two vocab regions with class-biased unigram draws so
sentiment models genuinely separate the classes.
"""

import numpy as np

from . import common

__all__ = ["train", "test", "word_dict"]

VOCAB_SIZE = 5148  # matches the reference's aclImdb word_dict cutoff order
TRAIN_SIZE = 2048
TEST_SIZE = 256


def word_dict():
    """{word: id}; synthetic vocabulary w0..wN + <unk>."""
    d = {f"w{i}": i for i in range(VOCAB_SIZE - 1)}
    d["<unk>"] = VOCAB_SIZE - 1
    return d


def _synthetic_reader(split, size):
    def reader():
        rs = common.synthetic_rng("imdb", split)
        half = VOCAB_SIZE // 2
        for _ in range(size):
            y = rs.randint(2)
            n = rs.randint(16, 128)
            # class-biased mixture: 70% from its half, 30% anywhere
            biased = rs.randint(y * half, y * half + half, n)
            noise = rs.randint(0, VOCAB_SIZE - 1, n)
            pick = rs.rand(n) < 0.7
            words = np.where(pick, biased, noise).tolist()
            yield words, int(y)

    return reader


def train(word_idx=None):
    return _synthetic_reader("train", TRAIN_SIZE)


def test(word_idx=None):
    return _synthetic_reader("test", TEST_SIZE)
