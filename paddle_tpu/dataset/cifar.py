"""CIFAR-10/100 (reference python/paddle/dataset/cifar.py).

Samples: (image: float32[3072] in [0,1] flattened CHW, label: int).
Reads python-pickle batches from DATA_HOME/cifar when present, else
deterministic synthetic images with class-dependent color/texture structure.
"""

import os
import pickle
import tarfile

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100"]

TRAIN_SIZE = 4096
TEST_SIZE = 512


def _tar_reader(path, sub_name):
    def reader():
        with tarfile.open(path, mode="r") as f:
            names = [n for n in f.getnames() if sub_name in n]
            for name in names:
                batch = pickle.load(f.extractfile(name), encoding="bytes")
                data = batch[b"data"]
                labels = batch.get(b"labels", batch.get(b"fine_labels"))
                for sample, label in zip(data, labels):
                    yield (sample / 255.0).astype("float32"), int(label)

    return reader


def _synthetic_reader(split, size, num_classes):
    def reader():
        rs = common.synthetic_rng(f"cifar{num_classes}", split)
        protos = common.synthetic_rng(
            f"cifar{num_classes}", "protos").rand(num_classes, 3, 8, 8)
        for _ in range(size):
            y = rs.randint(num_classes)
            base = np.kron(protos[y], np.ones((1, 4, 4)))  # 3x32x32
            x = np.clip(base + 0.15 * rs.randn(3, 32, 32), 0, 1)
            yield x.astype("float32").flatten(), int(y)

    return reader


def _reader(archive, sub_name, split, size, num_classes):
    p = common.cached_path("cifar", archive)
    if p:
        return _tar_reader(p, sub_name)
    return _synthetic_reader(split, size, num_classes)


def train10():
    return _reader("cifar-10-python.tar.gz", "data_batch", "train", TRAIN_SIZE, 10)


def test10():
    return _reader("cifar-10-python.tar.gz", "test_batch", "test", TEST_SIZE, 10)


def train100():
    return _reader("cifar-100-python.tar.gz", "train", "train", TRAIN_SIZE, 100)


def test100():
    return _reader("cifar-100-python.tar.gz", "test", "test", TEST_SIZE, 100)
