"""NLTK movie-review sentiment dataset (reference
python/paddle/dataset/sentiment.py).

Samples: (word_ids: list[int], label: 0/1). get_word_dict() -> {word: id}.
The reference tokenizes nltk's movie_reviews corpus; the synthetic fallback
draws class-biased unigrams (same recipe as dataset/imdb.py, distinct
vocabulary size and corpus stats).
"""

import numpy as np

from . import common

__all__ = ["train", "test", "get_word_dict"]

VOCAB_SIZE = 39768  # nltk movie_reviews vocabulary order
TRAIN_SIZE = 1600
TEST_SIZE = 400


def get_word_dict():
    d = {f"w{i}": i for i in range(VOCAB_SIZE)}
    return d


def _reader(split, size):
    def reader():
        rs = common.synthetic_rng("sentiment", split)
        half = VOCAB_SIZE // 2
        for _ in range(size):
            y = int(rs.randint(2))
            n = int(rs.randint(20, 200))
            biased = rs.randint(y * half, y * half + half, n)
            noise = rs.randint(0, VOCAB_SIZE, n)
            pick = rs.rand(n) < 0.65
            yield np.where(pick, biased, noise).tolist(), y

    return reader


def train():
    return _reader("train", TRAIN_SIZE)


def test():
    return _reader("test", TEST_SIZE)
