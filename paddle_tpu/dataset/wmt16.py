"""WMT16 en<->de translation dataset (reference
python/paddle/dataset/wmt16.py).

Samples: (src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk> conventions —
trg_ids is <s>-prefixed, trg_ids_next is the <e>-suffixed shift.
get_dict(lang, dict_size) -> {word: id}; fetch() is a no-op in the
zero-egress build.

Synthetic fallback mirrors dataset/wmt14.py: the "translation" is a
deterministic affine token map so seq2seq models can genuinely learn it.
"""

import numpy as np

from . import common

__all__ = ["train", "test", "validation", "get_dict", "fetch"]

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220
TRAIN_SIZE = 2048
TEST_SIZE = 256

_START = 0  # <s>
_END = 1    # <e>
_UNK = 2    # <unk>


def get_dict(lang, dict_size, reverse=False):
    dict_size = min(dict_size,
                    TOTAL_EN_WORDS if lang == "en" else TOTAL_DE_WORDS)
    d = {"<s>": _START, "<e>": _END, "<unk>": _UNK}
    for i in range(3, dict_size):
        d[f"{lang}{i}"] = i
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def fetch():
    """Zero-egress build: nothing to download."""
    return None


def _reader(split, size, src_dict_size, trg_dict_size):
    src_dict_size = min(src_dict_size, TOTAL_EN_WORDS)
    trg_dict_size = min(trg_dict_size, TOTAL_DE_WORDS)

    def reader():
        rs = common.synthetic_rng("wmt16", split)
        for _ in range(size):
            n = int(rs.randint(3, 16))
            src = rs.randint(3, src_dict_size, n)
            # learnable mapping: trg token = affine map of src token
            trg = 3 + (src * 7 + 11) % (trg_dict_size - 3)
            trg_in = np.concatenate([[_START], trg])
            trg_next = np.concatenate([trg, [_END]])
            yield (src.tolist(), trg_in.tolist(), trg_next.tolist())

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("train", TRAIN_SIZE, src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("test", TEST_SIZE, src_dict_size, trg_dict_size)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("val", TEST_SIZE, src_dict_size, trg_dict_size)
