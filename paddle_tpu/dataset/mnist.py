"""MNIST dataset (reference python/paddle/dataset/mnist.py).

Samples: (image: float32[784] scaled to [-1,1], label: int64 in [0,10)).
Reads the standard idx-format files from DATA_HOME/mnist when present,
else a deterministic synthetic set with class-dependent pixel structure
(so models genuinely converge on it).
"""

import gzip
import struct

import numpy as np

from . import common

__all__ = ["train", "test"]

TRAIN_SIZE = 8192  # synthetic fallback sizes (real: 60000/10000)
TEST_SIZE = 1024


def _idx_reader(image_path, label_path):
    def reader():
        with gzip.open(image_path, "rb") as imgf, \
                gzip.open(label_path, "rb") as lblf:
            magic, n, rows, cols = struct.unpack(">IIII", imgf.read(16))
            lmagic, ln = struct.unpack(">II", lblf.read(8))
            for _ in range(n):
                img = np.frombuffer(
                    imgf.read(rows * cols), dtype=np.uint8)
                lbl = struct.unpack("B", lblf.read(1))[0]
                img = img.astype("float32") / 255.0 * 2.0 - 1.0
                yield img, int(lbl)

    return reader


def _synthetic_reader(split, size):
    def reader():
        rs = common.synthetic_rng("mnist", split)
        protos = common.synthetic_rng("mnist", "protos").rand(10, 784)
        for _ in range(size):
            y = rs.randint(10)
            x = protos[y] + 0.25 * rs.randn(784)
            x = np.clip(x, 0, 1).astype("float32") * 2.0 - 1.0
            yield x, int(y)

    return reader


def _reader(split, size):
    imgs = common.cached_path(
        "mnist", f"{split}-images-idx3-ubyte.gz")
    lbls = common.cached_path(
        "mnist", f"{split}-labels-idx1-ubyte.gz")
    if imgs and lbls:
        return _idx_reader(imgs, lbls)
    return _synthetic_reader(split, size)


def train():
    return _reader("train", TRAIN_SIZE)


def test():
    return _reader("t10k", TEST_SIZE)
