"""Command-line entry: `python -m paddle_tpu <command>`.

Reference parity: the `paddle` wrapper script (paddle/scripts/
submit_local.sh.in:1 — version/train subcommands that set up the cluster
env and exec the user script) and the flag listing the reference scatters
through gflags --help.

Commands:
  version            print version + backend info
  flags              list registered runtime flags (FLAGS_* env overrides)
  train SCRIPT ...   launch a training script with PADDLE_* cluster env
                     (--role trainer|pserver --trainers N --trainer-id I
                      --pservers host:port,...) — the same variables
                     Trainer()'s cluster bootstrap reads.
  monitor JOURNAL    summarize a FLAGS_monitor_journal step journal
                     (step/phase timings, compile-cache hit rate, replica
                     skew); --json emits the summary as JSON.
  health summary LEDGER
                     summarize a FLAGS_health_ledger run ledger (loss
                     curve, grad norms, detector events, divergence
                     step); --json emits the summary as JSON.
  health compare A B [--tol-final F] [--tol-traj F]
                     assert convergence parity between two run ledgers
                     (final-loss delta, step-aligned trajectory max
                     deviation, divergence-step agreement); rc 0 on
                     parity, 1 on a violated tolerance, 2 on an
                     unreadable ledger — the standard parity gate
                     bench.py and green_gate use.
  checkpoint inspect DIR [--serial N]
                     list a checkpoint directory's serials and their
                     commit status (committed / incomplete / orphaned
                     .tmp) and show the latest (or chosen) manifest,
                     including the ZeRO-1 shard layout (param -> shard
                     owner, shard bytes) when the run had FLAGS_zero1=1;
                     --json emits the report as JSON.
  serve --model-dir DIR [--http PORT | --selftest N]
                     serve a save_inference_model directory with the
                     batching engine (serve.Server): warm every batch
                     bucket, then either expose the stdlib HTTP frontend
                     (POST /v1/infer, GET /healthz /stats /metrics) or
                     fire N synthetic requests and print stats JSON.
  trace ops --model-dir DIR
                     compile the model once with tracing + HLO cost
                     analysis on and print the slowest-ops table (HLO
                     cost attributed back to ProgramDesc ops).
  trace summary DIR  summarize a flight-recorder dump directory (span
                     counts per name, traces, slowest spans).
  trace dump [--out DIR] [--selftest]
                     dump the in-process flight recorder (--selftest
                     records synthetic spans first, proving the
                     record->dump->load path end to end).
  fleet replica --model-dir DIR [--port 0 --port-file F]
                     run one serving replica process for a fleet: the
                     serve engine behind its HTTP frontend, exiting 0
                     after a graceful drain (POST /admin/drain or
                     SIGTERM) with empty queues. --master registers a
                     TTL heartbeat with a parallel.master service;
                     --router registers with a fleet router over HTTP.
                     --chaos-kill-at/--chaos-hang-at N arm a
                     replica_kill/replica_hang fault on the Nth
                     executor dispatch (failover drills).
  elastic status --master HOST:PORT
                     membership snapshot of an elastic training job: the
                     current epoch, live world size and member names
                     (parallel.elastic; --json for machine parsing) —
                     the drill/runbook observability command.
  elastic drain NAME --master HOST:PORT
                     manually scale DOWN: remove worker NAME from the
                     membership so the survivors resize at their next
                     step boundary (the operator-driven twin of the
                     SIGTERM-drain path).
  fleet router [--replicas ep1,ep2,...] [--master HOST:PORT]
                     run the fleet router: health-checked least-queue
                     routing over the replica set with retry-on-other-
                     replica, deadlines, a fleet-wide retry budget and
                     graceful drain orchestration (POST /admin/drain
                     {"replica": name}).
"""

import argparse
import os
import sys


def _cmd_version(args):
    from . import __version__

    print(f"paddle_tpu {__version__}")
    try:
        import jax

        devs = jax.devices()
        print(f"jax {jax.__version__}; {len(devs)} device(s): "
              f"{devs[0].platform}")
    except Exception as e:  # jax may be unusable in a build sandbox
        print(f"jax unavailable: {e}")
    return 0


def _cmd_flags(args):
    from . import flags

    for name, (value, type_, help_) in flags.all_flags().items():
        print(f"FLAGS_{name} ({type_}, current={value}): {help_}")
    return 0


def _cmd_monitor(args):
    import glob as globmod

    from .monitor import format_summary, read_journal, summarize_journal

    paths = []
    for pat in args.journal:
        hits = sorted(globmod.glob(pat))
        paths.extend(hits or [pat])
    journals = {}
    for path in paths:
        if path in journals:
            continue
        try:
            journals[path] = read_journal(path)
        except OSError as e:
            print(f"cannot read journal: {e}", file=sys.stderr)
            return 1
    if len(journals) == 1:
        summary = summarize_journal(next(iter(journals.values())))
        if args.json:
            import json

            print(json.dumps(summary, indent=2))
        else:
            print(format_summary(summary))
        return 0

    # several journals = one per fleet process: per-process summaries
    # plus the obs clock-aligned merge (same-host processes share the
    # epoch clock, so offset 0 per journal) for cross-replica skew
    import json
    import os as osmod

    from .obs import merge_step_timeline

    summaries = {p: summarize_journal(r) for p, r in journals.items()}
    merged = merge_step_timeline(
        [{"name": osmod.path.basename(p) or p, "journal": r,
          "offset_s": 0.0} for p, r in journals.items()])
    if args.json:
        print(json.dumps({"journals": summaries,
                          "fleet": {k: merged[k] for k in
                                    ("steps", "stragglers")}}, indent=2))
        return 0
    hdr = (f"{'journal':<28}{'steps':>7}{'mean_ms':>10}{'p50_ms':>9}"
           f"{'p95_ms':>9}{'cache_hit%':>11}")
    print(hdr)
    print("-" * len(hdr))
    for path, s in summaries.items():
        ms = s.get("step_ms") or {}
        cache = s.get("cache") or {}
        lookups = (cache.get("hit") or 0) + (cache.get("miss") or 0)
        hit = 100.0 * (cache.get("hit") or 0) / lookups if lookups \
            else None
        print(f"{osmod.path.basename(path) or path:<28.27}"
              f"{s.get('steps', 0):>7}"
              f"{_opt_num(ms.get('mean')):>10}"
              f"{_opt_num(ms.get('p50')):>9}"
              f"{_opt_num(ms.get('p95')):>9}"
              f"{_opt_num(hit):>11}")
    steps = merged["steps"]
    if steps:
        worst = max(steps, key=lambda s: s["skew_ms"])
        print(f"fleet: {len(steps)} step(s) aligned across processes; "
              f"max skew {worst['skew_ms']:.1f} ms at step "
              f"{worst['step']} (slowest {worst['slowest']})")
        for name, run in sorted(merged["stragglers"].items()):
            print(f"straggler: {name} slowest on {run} consecutive "
                  f"step(s)")
    else:
        print("fleet: no step overlap between the journals")
    return 0


def _opt_num(v, spec="{:.1f}"):
    return "-" if v is None else spec.format(v)


def _cmd_health(args):
    import json

    from .health import compare as hcompare
    from .health.ledger import read_ledger

    if args.health_action == "summary":
        try:
            records = read_ledger(args.ledger)
        except OSError as e:
            print(f"cannot read ledger: {e}", file=sys.stderr)
            return 2
        summary = hcompare.summarize_ledger(records)
        if args.json:
            print(json.dumps(summary, indent=2))
        else:
            print(hcompare.format_ledger_summary(summary))
        return 0
    if args.health_action == "compare":
        try:
            a = read_ledger(args.a)
            b = read_ledger(args.b)
        except OSError as e:
            print(f"cannot read ledger: {e}", file=sys.stderr)
            return 2
        report = hcompare.compare_ledgers(
            a, b, tol_final=args.tol_final, tol_traj=args.tol_traj)
        if args.json:
            print(json.dumps(report, indent=2))
        else:
            print(hcompare.format_compare(report))
        return 0 if report["ok"] else 1
    return 2


def _fmt_age(seconds):
    s = float(seconds)
    if s < 60:
        return f"{s:.0f}s"
    if s < 3600:
        return f"{s / 60:.0f}m"
    if s < 86400:
        return f"{s / 3600:.1f}h"
    return f"{s / 86400:.1f}d"


def _cmd_cache(args):
    import json

    from . import flags
    from .cache import L2Store

    root = args.dir or flags.get("compile_cache_dir")
    if not root:
        print("no cache dir: pass --dir or set FLAGS_compile_cache_dir",
              file=sys.stderr)
        return 2
    if not os.path.isdir(root):
        print(f"not a directory: {root}", file=sys.stderr)
        return 2
    store = L2Store(root)
    if args.cache_action == "ls":
        ents = store.entries()
        if args.json:
            print(json.dumps({
                "dir": root,
                "total_bytes": sum(e["bytes"] for e in ents),
                "entries": ents,
            }, indent=2))
            return 0
        if not ents:
            print(f"{root}: empty")
            return 0
        print(f"{'digest':<18} {'kind':<20} {'bytes':>10} {'age':>7} "
              f"{'jaxlib':<12} status")
        for e in ents:
            print(f"{e['digest'][:16] + '..':<18} "
                  f"{e.get('kind', '?'):<20} {e['bytes']:>10} "
                  f"{_fmt_age(e['age_s']):>7} {e.get('jaxlib', '?'):<12} "
                  f"{'ok' if e['ok'] else 'CORRUPT'}")
        total = sum(e["bytes"] for e in ents)
        print(f"{len(ents)} entries, {total / 1e6:.1f} MB in {root}")
        return 0
    if args.cache_action == "prune":
        max_mb = args.max_mb if args.max_mb is not None \
            else flags.get("compile_cache_dir_max_mb")
        removed = store.prune(int(max_mb) * (1 << 20))
        print(f"pruned {removed} entries "
              f"({store.total_bytes() / 1e6:.1f} MB resident, "
              f"cap {max_mb} MB)")
        return 0
    if args.cache_action == "clear":
        removed = store.clear()
        print(f"removed {removed} entries from {root}")
        return 0
    return 2


def _cmd_checkpoint(args):
    from .resilience import inspect_dir

    try:
        report = inspect_dir(args.dir, serial=args.serial)
    except (OSError, ValueError) as e:
        print(f"cannot inspect checkpoint dir: {e}", file=sys.stderr)
        return 1
    if args.json:
        import json

        print(json.dumps(report, indent=2))
        return 0
    print(f"checkpoint dir: {report['checkpoint_dir']}")
    if not report["serials"]:
        print("  (no checkpoints)")
        return 0
    for ent in report["serials"]:
        print(f"  {ent['dir']:<24} serial={ent['serial']!s:<6} "
              f"{ent['status']:<12} {ent['bytes']} bytes")
    print(f"latest committed serial: {report['latest']}")
    manifest = report.get("manifest")
    if manifest:
        print(f"manifest (serial {manifest.get('serial')}): "
              f"format={manifest.get('format')} step={manifest.get('step')}")
        var_names = sorted((manifest.get("vars") or {}).keys())
        print(f"  vars ({len(var_names)}): {', '.join(var_names[:8])}"
              + (" ..." if len(var_names) > 8 else ""))
        dp = manifest.get("datapipe")
        if dp:
            print(f"  datapipe: {dp}")
        mesh = manifest.get("mesh")
        if mesh:
            mesh_s = "×".join(f"{k}={v}" for k, v in mesh.items())
            print(f"  mesh geometry: [{mesh_s}] (dp may change across a "
                  f"restore; other axes must match the target mesh)")
        el = (manifest.get("extra") or {}).get("elastic")
        if el:
            print(f"  elastic resize point: epoch={el.get('epoch')} "
                  f"world_size={el.get('world_size')} "
                  f"members={el.get('members')}")
        zero1 = manifest.get("zero1")
        if zero1:
            print(f"  zero1 shard layout ({len(zero1)} sharded params; "
                  f"checkpoint stores canonical full layout):")
            for pname in sorted(zero1):
                ent = zero1[pname]
                owners = ent.get("owners") or {}
                own = ", ".join(
                    f"dp{r}:[{owners[r][0]}:{owners[r][1]})"
                    for r in sorted(owners, key=int)[:4])
                if len(owners) > 4:
                    own += ", ..."
                print(f"    {pname}: shape={ent.get('shape')} "
                      f"shards={ent.get('num_shards')}x"
                      f"{ent.get('shard_numel')} "
                      f"param_shard={ent.get('param_shard_bytes')}B "
                      f"accum_shard={ent.get('accum_shard_bytes')}B")
                print(f"      owners: {own}")
                accs = ent.get("accums") or []
                if accs:
                    print(f"      accums: {', '.join(accs)}")
        ashard = manifest.get("autoshard")
        if ashard:
            mesh = ashard.get("mesh_axes") or {}
            mesh_s = "×".join(f"{k}={v}" for k, v in mesh.items())
            params = ashard.get("params") or {}
            print(f"  autoshard plan digest={ashard.get('digest')} "
                  f"mesh[{mesh_s}] layout={ashard.get('layout', 'full')} "
                  f"({len(params)} sharded params; checkpoint stores "
                  f"canonical full layout):")
            for pname in sorted(params):
                spec = ", ".join(str(a) for a in params[pname])
                print(f"    {pname}: ({spec})")
        pp = manifest.get("pipeline")
        if pp:
            print(f"  pipeline: stages={pp.get('stages')} "
                  f"axis={pp.get('axis', 'pp')} "
                  f"microbatches={pp.get('microbatches')} "
                  f"schedule={pp.get('schedule', '1f1b')} "
                  f"plan digest={pp.get('digest')} (params stored full; "
                  f"restore requires a matching pp axis size)")
    elif report.get("format"):
        print(f"legacy io-format checkpoint (no manifest); files: "
              f"{len(report.get('files', []))}")
    return 0


def _shard_demo_program():
    """Small embedding+fc net with mp seeds on the embedding table and the
    first fc weight — the same shape of model the autoshard dryrun and
    bench A/B use."""
    import paddle_tpu as fluid

    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        emb = fluid.layers.embedding(ids, size=[32, 16])
        h = fluid.layers.fc(emb, 32, act="relu")
        p = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(p, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    gb = main.global_block()
    embw = next(n for n, v in gb.vars.items()
                if getattr(v, "persistable", False) and v.shape == (32, 16))
    w1 = next(n for n, v in gb.vars.items()
              if getattr(v, "persistable", False) and v.shape == (16, 32))
    fluid.parallel.set_sharding(gb.var(embw), ("mp", None))
    fluid.parallel.set_sharding(gb.var(w1), (None, "mp"))
    return main


def _cmd_shard(args):
    import json

    from .parallel import autoshard

    mesh_axes = {}
    for part in (args.mesh or "").split(","):
        if not part.strip():
            continue
        k, _, v = part.partition("=")
        try:
            mesh_axes[k.strip()] = int(v)
        except ValueError:
            print(f"bad --mesh entry {part!r} (want name=size)",
                  file=sys.stderr)
            return 1
    if not mesh_axes:
        print("empty --mesh", file=sys.stderr)
        return 1
    if args.shard_action == "search":
        return _cmd_shard_search(args, mesh_axes)
    seeds = {}
    for s in args.seed or []:
        name, _, spec_s = s.partition("=")
        seeds[name.strip()] = tuple(
            None if e.strip() in ("", "None", "none", "-") else e.strip()
            for e in spec_s.split(","))
    if args.selftest:
        program = _shard_demo_program()
    elif args.model_dir:
        from .core.framework import Program

        with open(os.path.join(args.model_dir, "__model__")) as f:
            payload = json.load(f)
        program = Program.from_dict(payload["program"])
    else:
        print("shard plan needs --model-dir or --selftest", file=sys.stderr)
        return 1
    try:
        plan = autoshard.build_plan(program, mesh_axes,
                                    batch_axis=args.batch_axis,
                                    extra_seeds=seeds or None)
    except (TypeError, ValueError) as e:
        print(f"shard plan error: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(plan.describe(), indent=2))
    else:
        print(plan.render(verbose=not args.quiet))
    ok = plan.is_total() and not plan.unresolved
    if args.selftest:
        ok = ok and len(plan.sharded_names()) > 0
        # stderr so --json stdout stays machine-parseable
        print(f"shard plan selftest: {'OK' if ok else 'FAILED'}",
              file=sys.stderr if args.json else sys.stdout)
    return 0 if ok else 2


def _cmd_shard_search(args, mesh_axes):
    """`shard search`: enumerate seed placements, score whole plans with
    the unified cost model, report the cheapest vs the manual seeds.
    rc 0 search ok, 1 plan/search error, 2 selftest contract violated."""
    import json

    from .parallel import autoshard

    if args.selftest:
        program = _shard_demo_program()
    elif args.model_dir:
        loaded = _load_saved_program(args.model_dir)
        if isinstance(loaded, str):
            print(loaded, file=sys.stderr)
            return 1
        program = loaded[0]
    else:
        print("shard search needs --model-dir or --selftest",
              file=sys.stderr)
        return 1
    try:
        res = autoshard.search_plan(
            program, mesh_axes, batch_axis=args.batch_axis,
            batch_size=args.batch, hbm_budget=args.hbm_budget,
            max_params=args.max_params, rounds=args.rounds)
    except (TypeError, ValueError) as e:
        print(f"shard search error: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(res.to_dict(), indent=2))
    else:
        print(res.render())
        if not args.quiet:
            print(res.plan.render(verbose=False))
    ok = res.plan.is_total() and not res.plan.unresolved \
        and res.cost["score_s"] <= res.manual_cost["score_s"]
    if args.selftest:
        # the searched plan must never lose to the manual seeds, and the
        # demo net must actually end up sharded
        ok = ok and len(res.plan.sharded_names()) > 0
        print(f"shard search selftest: {'OK' if ok else 'FAILED'}",
              file=sys.stderr if args.json else sys.stdout)
    return 0 if ok else 2


def _check_demo_program():
    """Small MLP training program for `check --selftest`."""
    import paddle_tpu as fluid

    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 16, act="relu")
        p = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(p, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, ["x", "y"], [loss.name]


def _cmd_check(args):
    import json

    from . import analysis

    mesh_axes = None
    if args.mesh:
        mesh_axes = {}
        for part in args.mesh.split(","):
            if not part.strip():
                continue
            k, _, v = part.partition("=")
            try:
                mesh_axes[k.strip()] = int(v)
            except ValueError:
                print(f"bad --mesh entry {part!r} (want name=size)",
                      file=sys.stderr)
                return 2

    if args.selftest:
        # 1) a well-formed training program must verify clean ...
        prog, feeds, fetches = _check_demo_program()
        clean = analysis.verify(prog, level="full", feed_names=feeds,
                                fetch_names=fetches, mesh_axes=mesh_axes,
                                context="check --selftest")
        # 2) ... and the SAME program with an op knocked out must not:
        # drop the first fc's matmul, leaving its output undefined
        broken = prog.clone()
        ops = broken.global_block().ops
        del ops[next(i for i, op in enumerate(ops) if op.type == "mul")]
        bad = analysis.verify(broken, level="full", feed_names=feeds,
                              fetch_names=fetches, mesh_axes=mesh_axes,
                              context="check --selftest (broken)")
        ok = clean.ok and not bad.ok and "PTA001" in bad.codes()
        if args.json:
            print(json.dumps({"ok": ok, "clean": clean.to_dict(),
                              "broken": bad.to_dict()}, indent=2))
        else:
            print(clean.render(verbose=not args.quiet))
            print("--- intentionally broken program (must flag PTA001) ---")
            print(bad.render(verbose=not args.quiet))
            print(f"check selftest: {'OK' if ok else 'FAILED'}")
        return 0 if ok else 1

    if not args.model_dir:
        print("check needs --model-dir or --selftest", file=sys.stderr)
        return 2
    from .core.framework import Program

    model_path = os.path.join(args.model_dir, "__model__")
    try:
        with open(model_path) as f:
            payload = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot load {model_path}: {e}", file=sys.stderr)
        return 2
    program = Program.from_dict(payload["program"])
    report = analysis.verify(
        program, level=args.level,
        feed_names=payload.get("feed_var_names"),
        fetch_names=payload.get("fetch_var_names"),
        mesh_axes=mesh_axes, context=f"check {args.model_dir}")
    if args.json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(report.render(verbose=not args.quiet))
    return report.rc


def _load_saved_program(model_dir):
    """(program, feed_names, fetch_names) from a save_inference_model dir,
    or an error string."""
    import json

    from .core.framework import Program

    model_path = os.path.join(model_dir, "__model__")
    try:
        with open(model_path) as f:
            payload = json.load(f)
    except (OSError, ValueError) as e:
        return f"cannot load {model_path}: {e}"
    return (Program.from_dict(payload["program"]),
            payload.get("feed_var_names"),
            payload.get("fetch_var_names"))


def _seed_cycle(program):
    """Clone with a genuine def-use cycle appended (two scale ops reading
    each other's outputs) — the `analyze graph --selftest` mutation."""
    from .core.framework import OP_ROLE_ATTR_NAME, OpRole

    clone = program.clone()
    gb = clone.global_block()
    for nm in ("a_cyc", "b_cyc"):
        gb.create_var(name=nm, shape=[1], dtype="float32")
    role = {OP_ROLE_ATTR_NAME: int(OpRole.Forward), "scale": 1.0}
    gb.append_op(type="scale", inputs={"X": ["b_cyc"]},
                 outputs={"Out": ["a_cyc"]}, attrs=dict(role))
    gb.append_op(type="scale", inputs={"X": ["a_cyc"]},
                 outputs={"Out": ["b_cyc"]}, attrs=dict(role))
    return clone


def _seed_gather_rewire(program):
    """Clone of a zero1-rewritten program whose first zero1_gather is
    rewired to consume the PRE-update param shard — flat index order stays
    valid (PTA012-clean) but the gather no longer consumes the update, the
    dependence-path divergence only PTA033 sees."""
    clone = program.clone()
    gb = clone.global_block()
    gat = next(op for op in gb.ops if op.type == "zero1_gather")
    pupd = gat.input("X")[0]
    gat.rename_input(pupd, pupd.replace("@zero1_upd", "@zero1_shard"))
    clone._mutation += 1
    return clone


def _cmd_analyze(args):
    import json

    from .analysis import (ProgramVerificationError, Report, dataflow,
                           schedule)

    mesh_axes = None
    if getattr(args, "mesh", None):
        mesh_axes = {}
        for part in args.mesh.split(","):
            if not part.strip():
                continue
            k, _, v = part.partition("=")
            try:
                mesh_axes[k.strip()] = int(v)
            except ValueError:
                print(f"bad --mesh entry {part!r} (want name=size)",
                      file=sys.stderr)
                return 2

    def _resolve_program():
        """(program, feeds) for the non-selftest path, honoring --zero1."""
        if not args.model_dir:
            print(f"analyze {args.analyze_action} needs --model-dir or "
                  f"--selftest", file=sys.stderr)
            return None
        loaded = _load_saved_program(args.model_dir)
        if isinstance(loaded, str):
            print(loaded, file=sys.stderr)
            return None
        program, feeds, _ = loaded
        if args.zero1:
            from .parallel import zero1 as _z1
            program, _ = _z1.apply(program, args.zero1)
        return program, feeds

    if args.analyze_action == "pipeline":
        return _cmd_analyze_pipeline(args)

    if args.analyze_action == "graph":
        if args.selftest:
            prog, feeds, _ = _check_demo_program()
            if args.zero1:
                from .parallel import zero1 as _z1
                prog, _ = _z1.apply(prog, args.zero1)
            graph = dataflow.build_graph(prog, feed_names=feeds)
            clean = Report(level="full", context="analyze graph --selftest")
            dataflow.check_hazards(prog, clean, feed_names=feeds,
                                   graph=graph)
            seeded = Report(level="full",
                            context="analyze graph --selftest (cyclic)")
            dataflow.check_hazards(_seed_cycle(prog), seeded,
                                   feed_names=feeds)
            ok = clean.ok and not graph.has_cycle \
                and not seeded.ok and "PTA030" in seeded.codes()
            if args.json:
                print(json.dumps({"ok": ok, "graph": graph.summary(),
                                  "clean": clean.to_dict(),
                                  "seeded": seeded.to_dict()}, indent=2))
            else:
                print(f"graph: {graph.summary()}")
                print(clean.render(verbose=not args.quiet))
                print("--- seeded cyclic clone (must flag PTA030) ---")
                print(seeded.render(verbose=not args.quiet))
                print(f"analyze graph selftest: {'OK' if ok else 'FAILED'}")
            return 0 if ok else 1
        resolved = _resolve_program()
        if resolved is None:
            return 2
        program, feeds = resolved
        report = Report(level="full",
                        context=f"analyze graph {args.model_dir}")
        graph = dataflow.check_hazards(program, report, feed_names=feeds)
        if args.json:
            print(json.dumps({"graph": graph.summary(),
                              "report": report.to_dict()}, indent=2))
        else:
            print(f"graph: {graph.summary()}")
            print(report.render(verbose=not args.quiet))
        return report.rc

    if args.analyze_action == "fusion":
        from . import analysis, fusion

        bucket_bytes = (args.bucket_mb << 20) if args.bucket_mb else None

        def render(plan):
            if plan is None:
                return "fusion: nothing fused"
            lines = [f"fusion: ops {plan.n_ops_before} -> "
                     f"{plan.n_ops_after}  digest={plan.digest()}"]
            for c in plan.chains:
                lines.append(f"  chain  {'+'.join(c['types'])}  "
                             f"{c['vars'][0]} -> {c['vars'][1]}  "
                             f"benefit={c['benefit_us']}us")
            for b in plan.buckets:
                lines.append(f"  bucket fused_{b['opt']}_update x{b['n']} "
                             f"bytes={b['bytes']} "
                             f"shard_rows={b['shard_rows']}")
            if plan.skipped and not args.quiet:
                for base, why in plan.skipped:
                    lines.append(f"  skipped {base}: {why}")
            return "\n".join(lines)

        if args.selftest:
            import paddle_tpu as fluid

            # training demo: 6 params under adam -> one fused bucket,
            # and the fused program must verify clean at level full
            main, start = fluid.Program(), fluid.Program()
            with fluid.unique_name.guard(), \
                    fluid.program_guard(main, start):
                x = fluid.layers.data(name="x", shape=[8],
                                      dtype="float32")
                y = fluid.layers.data(name="y", shape=[1],
                                      dtype="float32")
                h = fluid.layers.fc(x, 16, act="relu")
                h2 = fluid.layers.fc(h, 8, act="relu")
                p = fluid.layers.fc(h2, 1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(p, y))
                fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
            fused, plan = fusion.apply(main, feed_names=["x", "y"],
                                       fetch_names=[loss.name],
                                       bucket_bytes=bucket_bytes)
            rep = analysis.verify(fused, feed_names=["x", "y"],
                                  fetch_names=[loss.name], level="full",
                                  context="analyze fusion --selftest")
            ok = plan is not None and bool(plan.buckets) \
                and max(b["n"] for b in plan.buckets) >= 2 and rep.ok

            # inference demo: an elementwise chain must fuse vertically
            inf = fluid.Program()
            with fluid.unique_name.guard(), \
                    fluid.program_guard(inf, fluid.Program()):
                xi = fluid.layers.data(name="x", shape=[64],
                                       dtype="float32")
                out = fluid.layers.scale(
                    fluid.layers.sigmoid(fluid.layers.tanh(
                        fluid.layers.relu(xi))), scale=2.0)
            _, vplan = fusion.apply(inf, feed_names=["x"],
                                    fetch_names=[out.name])
            ok = ok and vplan is not None and len(vplan.chains) >= 1

            # a hazardous source program must be REFUSED, never fused
            refused, codes = False, []
            try:
                fusion.apply(_seed_cycle(main), feed_names=["x", "y"],
                             fetch_names=[loss.name])
            except ProgramVerificationError as e:
                refused = True
                codes = sorted(e.report.codes())
            ok = ok and refused and "PTA030" in codes
            if args.json:
                print(json.dumps({
                    "ok": bool(ok),
                    "plan": plan.to_dict() if plan else None,
                    "vertical": vplan.to_dict() if vplan else None,
                    "verify_ok": rep.ok,
                    "seeded_refused": refused,
                    "seeded_codes": codes}, indent=2))
            else:
                print(render(plan))
                print(render(vplan))
                print("--- seeded cyclic source: "
                      + ("refused " + str(codes) if refused
                         else "NOT refused") + " ---")
                print("analyze fusion selftest: "
                      + ("OK" if ok else "FAILED"))
            return 0 if ok else 1

        resolved = _resolve_program()
        if resolved is None:
            return 2
        program, feeds = resolved
        try:
            fused, plan = fusion.apply(program, feed_names=feeds,
                                       bucket_bytes=bucket_bytes)
        except ProgramVerificationError as e:
            print(e.report.render(verbose=not args.quiet),
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(plan.to_dict() if plan else None, indent=2))
        else:
            print(render(plan))
        return 0

    # analyze schedule
    if args.selftest:
        from .parallel import zero1 as _z1
        prog, feeds, _ = _check_demo_program()
        parts = args.zero1 or (mesh_axes or {}).get("dp", 8)
        z, _zplan = _z1.apply(prog, parts)
        sched = schedule.analyze(
            z, mesh_axes=mesh_axes or {"dp": parts}, feed_names=feeds,
            batch_size=args.batch, bucket_bytes=args.bucket_bytes)
        reordered, plan = schedule.apply_plan(z, sched.plan,
                                              feed_names=feeds)
        ok = sched.critical_path_ms > 0 and len(plan.buckets) > 0 \
            and len(plan.moves) > 0 and reordered is not z
        # the seeded divergence must be REJECTED, never silently scheduled
        rejected = False
        codes = []
        try:
            schedule.analyze(_seed_gather_rewire(z),
                             mesh_axes=mesh_axes or {"dp": parts},
                             feed_names=feeds)
        except ProgramVerificationError as e:
            rejected = True
            codes = sorted(e.report.codes())
        ok = ok and rejected and "PTA033" in codes
        if args.json:
            print(json.dumps({"ok": ok, "schedule": sched.to_dict(),
                              "seeded_rejected": rejected,
                              "seeded_codes": codes}, indent=2))
        else:
            print(sched.render())
            print(f"--- seeded gather-rewire clone: "
                  f"{'rejected ' + str(codes) if rejected else 'NOT rejected'}"
                  f" ---")
            print(f"analyze schedule selftest: {'OK' if ok else 'FAILED'}")
        return 0 if ok else 1

    resolved = _resolve_program()
    if resolved is None:
        return 2
    program, feeds = resolved
    try:
        sched = schedule.analyze(
            program, mesh_axes=mesh_axes, feed_names=feeds,
            batch_size=args.batch, bucket_bytes=args.bucket_bytes)
    except ProgramVerificationError as e:
        print(e.report.render(verbose=not args.quiet), file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(sched.to_dict(), indent=2))
    else:
        print(sched.render())
    return 0


def _pipeline_demo_program():
    """Fixed-name 3-layer MLP trainer for `analyze pipeline --selftest` —
    explicit layer names so two builds yield identical param names (and
    therefore identical startup init) for the parity comparison."""
    import paddle_tpu as fluid

    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 32, act="relu", name="pls1")
        h = fluid.layers.fc(h, 16, act="relu", name="pls2")
        p = fluid.layers.fc(h, 1, name="pls3")
        loss = fluid.layers.mean(fluid.layers.square_error_cost(p, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, start, ["x", "y"], loss.name


def _cmd_analyze_pipeline(args):
    """`analyze pipeline`: partition a program over the pp axis, verify
    the split (PTA040/041), and report the 1F1B schedule + bubble.

    --selftest additionally 1F1B-executes the demo net at the requested
    stage count, asserts bitwise loss parity against an unpartitioned
    (n_stages=1) replay with identical microbatching, asserts the
    structural bubble equals the analytic (p-1)/(m+p-1) bound, and
    asserts a seeded backwards-edge mutation is REFUSED with PTA040.
    rc 0 ok, 1 contract violated / illegal split, 2 usage error."""
    import json

    import numpy as np

    from .analysis import ProgramVerificationError, Report
    from .parallel import pipeline as pl

    p, m = args.stages, args.microbatches
    if p < 1 or m < 1:
        print("--stages and --microbatches must be >= 1", file=sys.stderr)
        return 2

    if args.selftest:
        from .core.scope import Scope
        from .executor import Executor

        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(4 * m, 16).astype(np.float32),
                "y": rng.randn(4 * m, 1).astype(np.float32)}

        def run(n_stages):
            main, start, feeds, loss_name = _pipeline_demo_program()
            scope = Scope()
            Executor().run(start, scope=scope)
            runner = pl.PipelineRunner(
                main, n_stages, loss_name=loss_name, feed_names=feeds,
                n_microbatches=m, scope=scope, batch_size=4 * m)
            reports = [runner.run(feed) for _ in range(2)]
            return [np.asarray(r["loss"]) for r in reports], reports[-1]

        ref_losses, _ = run(1)
        losses, rep = run(p)
        parity = all((a == b).all() for a, b in zip(ref_losses, losses))
        bound = pl.analytic_bubble(p, m)
        bubble_ok = rep["bubble_fraction"] <= bound + 1e-9

        # a split that sends forward data to an EARLIER stage must be
        # refused, never silently executed (mirrors analyze schedule)
        main, start, feeds, loss_name = _pipeline_demo_program()
        plan = pl.partition(main, max(2, p), feed_names=feeds,
                            batch_size=4 * m)
        # force a forward def-use edge to run BACKWARDS: producer (the
        # first matmul) onto the last stage, its consumer onto stage 0
        ops = main.global_block().ops
        u = min(i for i, op in enumerate(ops)
                if plan.phases[i] == pl.PHASE_FWD and op.type == "mul")
        outs = set(ops[u].output_arg_names())
        v = min(i for i, op in enumerate(ops)
                if i > u and plan.phases[i] == pl.PHASE_FWD
                and outs & set(op.input_arg_names()))
        plan.assignment[u] = plan.n_stages - 1
        plan.assignment[v] = 0
        rejected, codes = False, []
        try:
            pl.build_stage_programs(main, plan, feed_names=feeds,
                                    fetch_names=[loss_name])
        except ProgramVerificationError as e:
            rejected = True
            codes = sorted(e.report.codes())
        ok = parity and bubble_ok and rejected and "PTA040" in codes
        result = {
            "ok": ok,
            "parity_bitwise": parity,
            "bubble_fraction": rep["bubble_fraction"],
            "bubble_analytic": bound,
            "bubble_measured": rep["bubble_measured"],
            "n_stages": p, "n_microbatches": m,
            "seeded_rejected": rejected, "seeded_codes": codes,
            "plan": rep["plan"],
        }
        if args.json:
            print(json.dumps(result, indent=2))
        else:
            print(f"pipeline: {p} stages x {m} microbatches  "
                  f"bubble {rep['bubble_fraction']:.4f} "
                  f"(analytic {bound:.4f}, measured "
                  f"{rep['bubble_measured']:.4f})")
            print(f"  bitwise loss parity vs n_stages=1: {parity}")
            print(f"--- seeded backwards-edge clone: "
                  f"{'rejected ' + str(codes) if rejected else 'NOT rejected'}"
                  f" ---")
            print(f"analyze pipeline selftest: {'OK' if ok else 'FAILED'}")
        return 0 if ok else 1

    if not args.model_dir:
        print("analyze pipeline needs --model-dir or --selftest",
              file=sys.stderr)
        return 2
    loaded = _load_saved_program(args.model_dir)
    if isinstance(loaded, str):
        print(loaded, file=sys.stderr)
        return 2
    program, feeds, _ = loaded
    try:
        plan = pl.partition(program, p, feed_names=feeds,
                            batch_size=args.batch)
    except ValueError as e:
        print(f"analyze pipeline error: {e}", file=sys.stderr)
        return 2
    report = Report(level="full",
                    context=f"analyze pipeline {args.model_dir}")
    pl.check_partition(program, plan, report, feed_names=feeds)
    sim = pl.simulate_schedule(pl.schedule_1f1b(p, m))
    out = {
        "plan": plan.to_dict(),
        "bubble_analytic": pl.analytic_bubble(p, m),
        "bubble_fraction": sim["bubble_fraction"],
        "n_microbatches": m,
        "report": report.to_dict(),
    }
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(plan.describe())
        print(f"  1F1B x {m} microbatches: bubble "
              f"{sim['bubble_fraction']:.4f} "
              f"(analytic {out['bubble_analytic']:.4f})")
        print(report.render(verbose=not args.quiet))
    return report.rc


def _cmd_serve(args):
    import json

    import numpy as np

    from . import flags
    from .core.places import CPUPlace, TPUPlace
    from .serve import ServeConfig, Server
    from .serve.http import serve_http

    if args.cache_dir:
        # persistent compile cache: bucket warmup deserializes executables
        # another process already compiled (sub-second warm start)
        flags.set("compile_cache_dir", args.cache_dir)
    place = CPUPlace() if args.place == "cpu" else TPUPlace(0)
    config = ServeConfig(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        replicas=args.replicas, slo_ms=args.slo_ms,
        max_queue_rows=args.max_queue_rows)
    try:
        server = Server.from_inference_model(
            args.model_dir, place=place, config=config)
    except (OSError, ValueError) as e:
        print(f"cannot load inference model: {e}", file=sys.stderr)
        return 1
    server.start()
    print(f"ready: buckets={list(server.config.buckets)} "
          f"replicas={config.replicas} "
          f"warm_compiles={server._warm_entries}", file=sys.stderr)
    if args.http is not None:
        print(f"http frontend on {args.host}:{args.http}", file=sys.stderr)
        serve_http(server, host=args.host, port=args.http)
        return 0
    # selftest: synthetic single-example requests from the feed shapes,
    # a handful of concurrent clients so the batcher actually batches
    import threading

    n, per = args.selftest, max(1, args.selftest // 8)
    rng = np.random.RandomState(0)

    def fire(k):
        for _ in range(k):
            feed = {name: rng.standard_normal(
                server._example_shape(name)).astype(
                server._feed_dtype(name))
                for name in server.feed_names}
            server.submit(feed).result()

    threads = [threading.Thread(target=fire, args=(per,))
               for _ in range(-(-n // per))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stats = server.stats()
    server.stop()
    print(json.dumps(stats, indent=2))
    return 0 if stats["steady_state_compiles"] == 0 else 1


def _cmd_fleet_replica(args):
    import json
    import signal
    import threading

    from . import flags
    from .core.places import CPUPlace, TPUPlace
    from .serve import ServeConfig, Server
    from .serve.http import make_http_server

    if args.cache_dir:
        # fleet spin-up: every replica shares one persistent compile
        # cache, so only the first one ever compiles each bucket
        flags.set("compile_cache_dir", args.cache_dir)
    if args.compile_service:
        # ... or each replica has its own cache and the first MISSER
        # compiles while the rest fetch the blob by digest
        flags.set("compile_service", args.compile_service)
    if args.chaos_kill_at is not None or args.chaos_hang_at is not None \
            or args.chaos_delay_ms is not None:
        from .resilience import chaos

        monkey = chaos.ChaosMonkey()
        if args.chaos_kill_at is not None:
            monkey.add(chaos.Fault("replica_kill", at=args.chaos_kill_at))
        if args.chaos_hang_at is not None:
            monkey.add(chaos.Fault("replica_hang", at=args.chaos_hang_at,
                                   times=args.chaos_hang_times,
                                   delay_ms=args.chaos_hang_ms))
        if args.chaos_delay_ms is not None:
            # every dispatch: a deterministic per-batch service-time
            # floor -> replica capacity ~= 1000/delay_ms batches/s on
            # any host, which makes autoscale drills reproducible
            monkey.add(chaos.Fault("delay", at=0, times=1 << 62,
                                   delay_ms=args.chaos_delay_ms))
        chaos.install(monkey)
    place = CPUPlace() if args.place == "cpu" else TPUPlace(0)
    config = ServeConfig(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        replicas=args.replicas, max_queue_rows=args.max_queue_rows,
        slo_ms=args.slo_ms)
    try:
        server = Server.from_inference_model(
            args.model_dir, place=place, config=config)
    except (OSError, ValueError) as e:
        print(f"cannot load inference model: {e}", file=sys.stderr)
        return 1
    server.start()
    # a drained replica's frontend shuts itself down -> serve_forever
    # returns -> this process exits 0: the rolling-restart contract
    httpd = make_http_server(server, host=args.host, port=args.port,
                             shutdown_on_drain=True)
    port = httpd.server_address[1]
    endpoint = f"{args.host}:{port}"
    name = args.name or f"replica-{port}"
    if args.port_file:
        with open(args.port_file, "w") as f:
            f.write(f"{port}\n")
    print(f"replica {name} serving on {endpoint}", file=sys.stderr)

    obs_client = None
    if args.obs:
        from . import obs as obs_mod

        obs_client = obs_mod.maybe_start("replica", replica=name,
                                         endpoint=args.obs)

    heartbeater = None
    if args.master:
        from .parallel.master import Heartbeater, MasterClient

        heartbeater = Heartbeater(MasterClient(args.master), "serve",
                                  name, endpoint, ttl=args.ttl)
        heartbeater.start()
    elif args.router:
        import http.client

        def _register_loop():
            body = json.dumps({"name": name, "endpoint": endpoint})
            while not server._stop:
                try:
                    host, rport = args.router.rsplit(":", 1)
                    conn = http.client.HTTPConnection(host, int(rport),
                                                      timeout=2.0)
                    try:
                        conn.request("POST", "/admin/register", body=body)
                        conn.getresponse().read()
                    finally:
                        conn.close()
                except OSError:
                    pass  # router restart: next beat re-registers
                stop_beats.wait(max(0.5, args.ttl / 3.0))

        stop_beats = threading.Event()
        threading.Thread(target=_register_loop, name="fleet-register",
                         daemon=True).start()

    def _sigterm(signum, frame):
        # SIGTERM = drain, not die: finish the backlog, THEN stop the
        # HTTP loop — same ordering as /admin/drain's shutdown_on_drain
        # path, so serve_forever() only returns once the queue is empty
        # (shutting down concurrently would snapshot stats mid-drain and
        # fail still-queued requests in the server.stop() below)
        def _drain_then_exit():
            server.drain()
            httpd.shutdown()

        threading.Thread(target=_drain_then_exit, name="serve-drain-sig",
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        server.drain()
    finally:
        httpd.server_close()
        if heartbeater is not None:
            heartbeater.close()
    stats = server.stats()
    server.stop()
    if obs_client is not None:
        # final push AFTER stop: the collector sees the terminal journal
        # tail and any shutdown trace dump
        obs_client.stop()
    leftover = stats["queue_rows"]
    print(f"replica {name} exiting: drained queue_rows={leftover}",
          file=sys.stderr)
    return 0 if leftover == 0 else 1


def _cmd_fleet_router(args):
    from .serve.fleet import FleetConfig, Router, serve_fleet

    replicas = {}
    if args.replicas:
        for i, ep in enumerate(e for e in args.replicas.split(",") if e):
            replicas[f"r{i}"] = ep
    discover = None
    if args.master:
        from .parallel.master import MasterClient

        client = MasterClient(args.master)
        discover = lambda: client.lookup("serve")  # noqa: E731
    if not replicas and discover is None:
        print("router needs --replicas and/or --master", file=sys.stderr)
        return 1
    config = FleetConfig(
        probe_interval_s=args.probe_interval,
        request_deadline_ms=args.deadline_ms,
        attempt_timeout_ms=args.attempt_timeout_ms,
        max_attempts=args.max_attempts, hedge_ms=args.hedge_ms)
    router = Router(replicas, config=config, discover=discover)
    obs_client = None
    if args.obs:
        from . import obs as obs_mod

        obs_client = obs_mod.maybe_start("router", endpoint=args.obs)
    autoscaler = None
    if args.autoscale_model_dir:
        import tempfile

        from .serve.fleet import (Autoscaler, AutoscalerConfig,
                                  ProcessReplicaSpawner)

        workdir = tempfile.mkdtemp(prefix="fleet_autoscale_")
        argv_base = [sys.executable, "-m", "paddle_tpu", "fleet",
                     "replica", "--model-dir", args.autoscale_model_dir,
                     "--place", "cpu", "--port", "0"]
        if args.compile_service:
            argv_base += ["--compile-service", args.compile_service]
        if args.autoscale_cache_dir:
            argv_base += ["--cache-dir", args.autoscale_cache_dir]
        spawner = ProcessReplicaSpawner(
            argv_base, workdir,
            per_replica_cache=not args.autoscale_cache_dir)
        autoscaler = Autoscaler(router, spawner, AutoscalerConfig(
            target_p99_ms=args.autoscale_target_p99_ms,
            high_queue_rows=args.autoscale_queue_rows,
            min_replicas=args.autoscale_min,
            max_replicas=args.autoscale_max,
            interval_s=args.autoscale_interval,
            cooldown_out_s=args.autoscale_cooldown_out,
            cooldown_in_s=args.autoscale_cooldown_in)).start()
    print(f"fleet router on {args.host}:{args.port} over "
          f"{sorted(replicas.values()) or 'master-discovered replicas'}",
          file=sys.stderr)
    serve_fleet(router, host=args.host, port=args.port)
    if autoscaler is not None:
        autoscaler.stop()
        autoscaler.spawner.stop_all()
    if obs_client is not None:
        obs_client.stop()
    return 0


def _cmd_elastic(args):
    import json

    from .parallel import elastic as elastic_mod

    if args.elastic_action == "status":
        try:
            st = elastic_mod.fetch_status(args.master, timeout=args.timeout)
        except OSError as e:
            print(f"cannot reach master {args.master}: {e}",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(st, indent=2))
        else:
            print(f"elastic job at {st['endpoint']}: epoch={st['epoch']} "
                  f"world_size={st['world_size']}")
            for name, addr in sorted(st["members"].items()):
                print(f"  {name}" + (f"  {addr}" if addr else ""))
        return 0
    if args.elastic_action == "drain":
        from .parallel.master import MasterClient

        client = MasterClient(args.master, connect_timeout=args.timeout)
        try:
            r = client.elastic_leave(args.name)
        except OSError as e:
            print(f"cannot reach master {args.master}: {e}",
                  file=sys.stderr)
            return 1
        finally:
            client.close()
        print(f"drained {args.name}: membership epoch now {r['epoch']} "
              f"(survivors resize at their next step boundary)")
        return 0
    return 1


def _cmd_fleet(args):
    if args.fleet_action == "replica":
        return _cmd_fleet_replica(args)
    if args.fleet_action == "router":
        return _cmd_fleet_router(args)
    return 1


def _cmd_obs(args):
    import json

    from . import obs as obs_mod

    if args.obs_action == "collect":
        import threading

        col = obs_mod.Collector(ttl_s=args.ttl,
                                straggler_ratio=args.straggler_ratio,
                                straggler_steps=args.straggler_steps)
        for target in args.scrape or []:
            name, _, endpoint = target.rpartition("=")
            col.add_scrape_target(name or endpoint, endpoint)
        httpd = obs_mod.make_obs_http(col, host=args.host, port=args.port)
        port = httpd.server_address[1]
        if args.port_file:
            with open(args.port_file, "w") as f:
                f.write(f"{port}\n")
        print(f"obs collector on {args.host}:{port} "
              f"(POST /v1/obs/push, GET /metrics /v1/obs/summary "
              f"/v1/obs/timeline; {len(args.scrape or [])} scrape "
              f"target(s))", file=sys.stderr)
        stop = threading.Event()
        if args.scrape:
            col.scrape_tick()

            def _scrape_loop():
                while not stop.wait(args.scrape_interval):
                    col.scrape_tick()

            threading.Thread(target=_scrape_loop, name="obs-scrape",
                             daemon=True).start()
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            stop.set()
            httpd.server_close()
        return 0

    if args.obs_action == "top":
        return obs_mod.run_top(
            args.collector, interval_s=args.interval, once=args.once,
            json_out=args.json, iterations=args.iterations)

    if args.obs_action == "timeline":
        from .trace import load_dump

        dump_dirs = []        # [(lane name or None, dir)]
        merged_steps = None
        if args.collector:
            import http.client

            try:
                host, port = args.collector.rsplit(":", 1)
                conn = http.client.HTTPConnection(host, int(port),
                                                  timeout=5.0)
                try:
                    conn.request("GET", "/v1/obs/timeline")
                    resp = conn.getresponse()
                    body = resp.read()
                    if resp.status != 200:
                        raise OSError(f"HTTP {resp.status}")
                finally:
                    conn.close()
                tl = json.loads(body)
            except (OSError, ValueError) as e:
                print(f"cannot reach collector {args.collector}: {e}",
                      file=sys.stderr)
                return 2
            merged_steps = tl.get("timeline")
            dump_dirs.extend((d.get("replica"), d["dir"])
                             for d in tl.get("dumps", []))
        for d in args.dump or []:
            dump_dirs.append((None, d))
        if not dump_dirs and merged_steps is None:
            print("obs timeline needs --collector and/or --dump",
                  file=sys.stderr)
            return 2
        dumps, names = [], []
        for lane, d in dump_dirs:
            try:
                dumps.append(load_dump(d))
            except (OSError, ValueError) as e:
                print(f"skipping dump {d}: {e}", file=sys.stderr)
                continue
            names.append(lane or os.path.basename(d.rstrip("/")))
        if merged_steps is not None:
            print(obs_mod.format_timeline(merged_steps))
        if dumps:
            trace = obs_mod.merge_chrome_traces(dumps, names=names)
            lanes = {e['pid'] for e in trace['traceEvents']}
            print(f"merged trace: {len(dumps)} dump(s), "
                  f"{len(lanes)} pid lane(s), "
                  f"{sum(1 for e in trace['traceEvents'] if e['ph'] == 'X')}"
                  f" span event(s)")
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(trace, f)
                print(f"wrote {args.out}")
        elif args.out:
            print("no trace dumps to merge (nothing written)",
                  file=sys.stderr)
            return 1
        return 0
    return 1


def _cmd_trace(args):
    import json

    from . import trace

    if args.trace_action == "ops":
        import numpy as np

        from . import flags
        from .core.places import CPUPlace, TPUPlace
        from .core.scope import Scope, scope_guard
        from .executor import Executor
        from .io import load_inference_model

        flags.set("monitor", True)
        flags.set("monitor_hlo_cost", True)
        flags.set("trace", True)
        place = CPUPlace() if args.place == "cpu" else TPUPlace(0)
        exe = Executor(place)
        scope = Scope()
        try:
            with scope_guard(scope):
                program, feed_names, fetch_targets = load_inference_model(
                    args.model_dir, exe)
        except (OSError, ValueError) as e:
            print(f"cannot load inference model: {e}", file=sys.stderr)
            return 1
        feed = {}
        for name in feed_names:
            var = program.global_block().var(name)
            shape = [args.batch if d is None or d < 0 else d
                     for d in var.shape]
            feed[name] = np.zeros(shape, dtype=var.dtype)
        with scope_guard(scope):
            exe.run(program, feed=feed, fetch_list=fetch_targets)
        report = trace.slowest_ops(batch_size=args.batch, top=args.top)
        if report is None:
            print("no compile recorded — nothing to attribute",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(report, indent=2))
        else:
            print(trace.format_ops_table(report))
        return 0

    if args.trace_action == "summary":
        try:
            loaded = trace.load_dump(args.dir)
        except (OSError, ValueError) as e:
            print(f"cannot load dump: {e}", file=sys.stderr)
            return 1
        man, spans = loaded["manifest"], loaded["spans"]
        print(f"dump: {args.dir}")
        print(f"  reason={man.get('reason')} format={man.get('format')} "
              f"spans={len(spans)} dropped={man.get('dropped')} "
              f"traces={man.get('traces')}")
        by_name = {}
        for sp in spans:
            agg = by_name.setdefault(sp["name"], [0, 0.0])
            agg[0] += 1
            agg[1] += sp["t1"] - sp["t0"]
        print(f"  {'span':<24} {'count':>6} {'total_ms':>10} {'avg_ms':>9}")
        for name, (n, tot) in sorted(by_name.items(),
                                     key=lambda kv: -kv[1][1]):
            print(f"  {name:<24} {n:>6} {tot * 1e3:>10.2f} "
                  f"{tot * 1e3 / n:>9.3f}")
        slow = sorted(spans, key=lambda s: s["t0"] - s["t1"])[:5]
        print("  slowest spans:")
        for sp in slow:
            print(f"    {(sp['t1'] - sp['t0']) * 1e3:>9.2f} ms  "
                  f"{sp['name']}  trace={sp['trace'][:8]} "
                  f"thread={sp.get('thread')}")
        return 0

    if args.trace_action == "dump":
        from . import flags

        if args.selftest:
            import time

            flags.set("trace", True)
            with trace.span("selftest.root", kind="selftest"):
                t0 = time.perf_counter()
                with trace.span("selftest.child", n=1):
                    pass
                trace.record("selftest.retro", t0, time.perf_counter())
        if not trace.enabled():
            print("tracing is off (FLAGS_trace=0) — nothing recorded",
                  file=sys.stderr)
            return 1
        path = trace.dump(reason="manual", out_dir=args.out)
        spans, dropped = trace.snapshot()
        print(f"dump written: {path} ({len(spans)} spans, "
              f"{dropped} dropped)")
        if args.selftest:
            loaded = trace.load_dump(path)
            names = {sp["name"] for sp in loaded["spans"]}
            want = {"selftest.root", "selftest.child", "selftest.retro"}
            if not want <= names:
                print(f"selftest FAILED: missing {want - names}",
                      file=sys.stderr)
                return 1
            print("selftest ok: record -> dump -> load round-trip")
        return 0
    return 1


def _cmd_train(args):
    env = dict(os.environ)
    env["PADDLE_TRAINING_ROLE"] = args.role.upper()
    env["PADDLE_TRAINERS"] = str(args.trainers)
    env["PADDLE_TRAINER_ID"] = str(args.trainer_id)
    if args.pservers:
        env["PADDLE_PSERVERS"] = args.pservers
    if args.current_endpoint:
        env["PADDLE_CURRENT_ENDPOINT"] = args.current_endpoint
    cmd = [sys.executable, args.script] + args.script_args
    os.execve(sys.executable, cmd, env)


def main(argv=None):
    parser = argparse.ArgumentParser(prog="paddle_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("version", help="print version and backend info")
    sub.add_parser("flags", help="list runtime flags")

    m = sub.add_parser("monitor", help="summarize step-journal files "
                                       "(FLAGS_monitor_journal)")
    m.add_argument("journal", nargs="+",
                   help="JSONL step journal path(s); globs OK. Several "
                        "journals render a per-process comparison table "
                        "plus the clock-aligned cross-replica skew/"
                        "straggler merge")
    m.add_argument("--json", action="store_true",
                   help="emit the summary as JSON instead of a table")

    h = sub.add_parser("health", help="model-health run ledgers: "
                                      "summarize and assert convergence "
                                      "parity")
    hsub = h.add_subparsers(dest="health_action", required=True)
    hs = hsub.add_parser("summary", help="summarize a FLAGS_health_ledger "
                                         "run ledger")
    hs.add_argument("ledger", help="path of the JSONL health ledger")
    hs.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    hc = hsub.add_parser("compare", help="convergence parity between two "
                                         "run ledgers (rc 0 parity / "
                                         "1 fail / 2 unreadable)")
    hc.add_argument("a", help="reference run ledger")
    hc.add_argument("b", help="candidate run ledger")
    hc.add_argument("--tol-final", type=float, default=1e-3,
                    help="max |final loss A - final loss B| at the last "
                         "common sampled step")
    hc.add_argument("--tol-traj", type=float, default=5e-3,
                    help="max step-aligned |loss A - loss B| over all "
                         "common sampled steps")
    hc.add_argument("--json", action="store_true",
                    help="emit the parity report as JSON")

    ca = sub.add_parser("cache", help="persistent compile-cache store "
                                      "(FLAGS_compile_cache_dir)")
    casub = ca.add_subparsers(dest="cache_action", required=True)
    cal = casub.add_parser("ls", help="list entries: digest, kind, bytes, "
                                      "age, jaxlib version")
    cal.add_argument("--dir", default=None,
                     help="store directory (default "
                          "FLAGS_compile_cache_dir)")
    cal.add_argument("--json", action="store_true",
                     help="emit the listing as JSON")
    cap_ = casub.add_parser("prune", help="delete oldest-used entries "
                                          "until the store fits the cap")
    cap_.add_argument("--dir", default=None,
                      help="store directory (default "
                           "FLAGS_compile_cache_dir)")
    cap_.add_argument("--max-mb", type=int, default=None,
                      help="size cap in MiB (default "
                           "FLAGS_compile_cache_dir_max_mb)")
    cac = casub.add_parser("clear", help="delete every entry")
    cac.add_argument("--dir", default=None,
                     help="store directory (default "
                          "FLAGS_compile_cache_dir)")

    c = sub.add_parser("checkpoint", help="inspect checkpoint directories")
    csub = c.add_subparsers(dest="checkpoint_action", required=True)
    ci = csub.add_parser("inspect", help="list serials, commit status and "
                                         "the manifest of a checkpoint dir")
    ci.add_argument("dir", help="checkpoint directory "
                                "(holds checkpoint_<N> subdirs)")
    ci.add_argument("--serial", type=int, default=None,
                    help="show this serial's manifest instead of the latest")
    ci.add_argument("--json", action="store_true",
                    help="emit the report as JSON")

    sh = sub.add_parser("shard", help="autoshard: GSPMD-style sharding "
                                      "plans over a program")
    shsub = sh.add_subparsers(dest="shard_action", required=True)
    shp = shsub.add_parser("plan", help="propagate seeds and render the "
                                        "total ShardingPlan with per-edge "
                                        "estimated reshard bytes")
    shp.add_argument("--model-dir", default=None,
                     help="save_inference_model directory to plan")
    shp.add_argument("--selftest", action="store_true",
                     help="build a small embedding+fc demo net, plan it, "
                          "and verify the plan is total")
    shp.add_argument("--mesh", default="dp=4,mp=2",
                     help="mesh axes as name=size pairs (plan construction "
                          "is analytic — no devices needed)")
    shp.add_argument("--seed", action="append", metavar="NAME=SPEC",
                     help="extra seed annotation, e.g. fc_0.w_0=None,mp "
                          "(repeatable; entries are axis names or None)")
    shp.add_argument("--batch-axis", default="dp",
                     help="mesh axis seeded onto data vars' dim 0")
    shp.add_argument("--json", action="store_true",
                     help="emit plan.describe() as JSON")
    shp.add_argument("--quiet", action="store_true",
                     help="summary and edges only, no per-var table")
    shse = shsub.add_parser(
        "search", help="search candidate seed placements across the mesh "
                       "axes and keep the whole-plan cheapest (unified "
                       "compute + collective-bytes + peak-HBM cost model)")
    shse.add_argument("--model-dir", default=None,
                      help="save_inference_model directory to search")
    shse.add_argument("--selftest", action="store_true",
                      help="search the embedding+fc demo net and verify "
                           "the searched plan never costs more than the "
                           "manual seeds")
    shse.add_argument("--mesh", default="dp=4,mp=2",
                      help="mesh axes as name=size pairs")
    shse.add_argument("--batch-axis", default="dp",
                      help="mesh axis seeded onto data vars' dim 0")
    shse.add_argument("--batch", type=int, default=8,
                      help="batch size substituted for dynamic dims in "
                           "the cost model")
    shse.add_argument("--hbm-budget", type=int, default=None,
                      metavar="BYTES",
                      help="per-replica peak-HBM feasibility budget; "
                           "plans over it are penalized out")
    shse.add_argument("--max-params", type=int, default=8,
                      help="search seed placements for the N largest "
                           "params")
    shse.add_argument("--rounds", type=int, default=2,
                      help="greedy coordinate-descent passes")
    shse.add_argument("--json", action="store_true",
                      help="emit the search result as JSON")
    shse.add_argument("--quiet", action="store_true",
                      help="skip the winning plan's summary render")

    ck = sub.add_parser("check", help="static program verification: graph/"
                                      "safety/sharding checks and the "
                                      "peak-HBM estimate (docs/analysis.md)")
    ck.add_argument("--model-dir", default=None,
                    help="save_inference_model directory to verify")
    ck.add_argument("--level", default="full", choices=["basic", "full"],
                    help="basic: structure + shape contracts; full: adds "
                         "safety/sharding checks and the HBM table")
    ck.add_argument("--mesh", default=None, metavar="NAME=SIZE,...",
                    help="mesh axes for the sharding checks and per-replica "
                         "HBM accounting, e.g. dp=4,mp=2")
    ck.add_argument("--selftest", action="store_true",
                    help="verify a clean demo program AND an intentionally "
                         "broken clone (must flag PTA001); rc 0 when both "
                         "behave")
    ck.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    ck.add_argument("--quiet", action="store_true",
                    help="show errors only, not warnings")

    an = sub.add_parser("analyze", help="SSA dataflow graph, PTA03x hazard "
                                        "detection, and the static overlap "
                                        "schedule (docs/analysis.md)")
    ansub = an.add_subparsers(dest="analyze_action", required=True)
    ag = ansub.add_parser("graph", help="build the SSA def-use dependency "
                                        "graph and run the dataflow hazard "
                                        "detector (PTA030-PTA034)")
    ag.add_argument("--model-dir", default=None,
                    help="save_inference_model directory to analyze")
    ag.add_argument("--zero1", type=int, default=0, metavar="N",
                    help="apply the ZeRO-1 rewrite with N shards before "
                         "analyzing")
    ag.add_argument("--selftest", action="store_true",
                    help="analyze a clean demo training program AND a "
                         "seeded cyclic clone (must flag PTA030); rc 0 "
                         "when both behave")
    ag.add_argument("--json", action="store_true",
                    help="emit the graph summary and report as JSON")
    ag.add_argument("--quiet", action="store_true",
                    help="show errors only, not warnings")
    asch = ansub.add_parser(
        "schedule", help="critical path over the analytic cost models and "
                         "the bucketed reduce-scatter overlap plan")
    asch.add_argument("--model-dir", default=None,
                      help="save_inference_model directory to schedule")
    asch.add_argument("--mesh", default="dp=8", metavar="NAME=SIZE,...",
                      help="mesh axes for the ring collective-bytes model")
    asch.add_argument("--zero1", type=int, default=0, metavar="N",
                      help="apply the ZeRO-1 rewrite with N shards before "
                           "scheduling")
    asch.add_argument("--batch", type=int, default=1,
                      help="batch size substituted for dynamic dims in the "
                           "FLOPs model")
    asch.add_argument("--bucket-bytes", type=int, default=None,
                      help="override FLAGS_overlap_bucket_bytes for the "
                           "gradient-bucketing plan")
    asch.add_argument("--selftest", action="store_true",
                      help="schedule a zero1-rewritten demo program (must "
                           "hoist a non-empty bucket plan) AND verify a "
                           "seeded collective-order divergence is rejected "
                           "with PTA033; rc 0 when both behave")
    asch.add_argument("--json", action="store_true",
                      help="emit the schedule report as JSON")
    asch.add_argument("--quiet", action="store_true",
                      help="show errors only, not warnings")
    afu = ansub.add_parser(
        "fusion", help="cost-guided operator fusion plan: vertical "
                       "elementwise chains and the bucketed fused weight "
                       "update (docs/fusion.md)")
    afu.add_argument("--model-dir", default=None,
                     help="save_inference_model directory to plan fusion "
                          "for")
    afu.add_argument("--zero1", type=int, default=0, metavar="N",
                     help="apply the ZeRO-1 rewrite with N shards before "
                          "fusing (exercises shard-aware bucketing)")
    afu.add_argument("--bucket-mb", type=int, default=None,
                     help="override FLAGS_fuse_bucket_mb for the update "
                          "bucketing")
    afu.add_argument("--selftest", action="store_true",
                     help="fuse a demo trainer (must bucket >= 2 adam "
                          "updates and verify clean at level full), fuse "
                          "a demo elementwise chain, AND verify a seeded "
                          "cyclic source is refused with PTA030; rc 0 "
                          "when all behave")
    afu.add_argument("--json", action="store_true",
                     help="emit the fusion plan as JSON")
    afu.add_argument("--quiet", action="store_true",
                     help="hide skipped-candidate reasons")
    apl = ansub.add_parser(
        "pipeline", help="pp-axis stage partition (parallel.pipeline): "
                         "min-cut plan, PTA040/041 legality, and the 1F1B "
                         "schedule's bubble fraction")
    apl.add_argument("--model-dir", default=None,
                     help="save_inference_model directory to partition")
    apl.add_argument("--stages", type=int, default=2,
                     help="pipeline stage count (pp axis size)")
    apl.add_argument("--microbatches", type=int, default=4,
                     help="1F1B microbatches per step")
    apl.add_argument("--batch", type=int, default=1,
                     help="batch size substituted for dynamic dims in the "
                          "FLOPs/bytes models")
    apl.add_argument("--selftest", action="store_true",
                     help="1F1B-execute the demo net (bitwise loss parity "
                          "vs unpartitioned, bubble <= analytic bound) AND "
                          "verify a seeded backwards-edge split is refused "
                          "with PTA040; rc 0 when all hold")
    apl.add_argument("--json", action="store_true",
                     help="emit the report as JSON")
    apl.add_argument("--quiet", action="store_true",
                     help="show errors only, not warnings")

    s = sub.add_parser("serve", help="serve a saved inference model with "
                                     "the batching engine")
    s.add_argument("--model-dir", required=True,
                   help="save_inference_model directory")
    s.add_argument("--place", default="tpu", choices=["tpu", "cpu"])
    s.add_argument("--max-batch", type=int, default=8)
    s.add_argument("--max-wait-ms", type=float, default=2.0)
    s.add_argument("--replicas", type=int, default=1)
    s.add_argument("--slo-ms", type=float, default=None)
    s.add_argument("--max-queue-rows", type=int, default=None)
    s.add_argument("--http", type=int, default=None, metavar="PORT",
                   help="expose the HTTP frontend on PORT (blocking)")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--selftest", type=int, default=64, metavar="N",
                   help="without --http: fire N synthetic requests from "
                        "concurrent clients and print stats JSON")
    s.add_argument("--cache-dir", default=None,
                   help="persistent compile-cache directory "
                        "(FLAGS_compile_cache_dir): warmup loads "
                        "executables compiled by earlier processes")

    tr = sub.add_parser("trace", help="flight-recorder dumps and per-op "
                                      "cost attribution")
    trsub = tr.add_subparsers(dest="trace_action", required=True)
    tro = trsub.add_parser("ops", help="compile a saved model once and "
                                       "print the slowest-ops table")
    tro.add_argument("--model-dir", required=True,
                     help="save_inference_model directory")
    tro.add_argument("--place", default="cpu", choices=["tpu", "cpu"])
    tro.add_argument("--batch", type=int, default=1,
                     help="batch size substituted for dynamic dims")
    tro.add_argument("--top", type=int, default=10,
                     help="rows in the table")
    tro.add_argument("--json", action="store_true",
                     help="emit the report as JSON")
    trs = trsub.add_parser("summary", help="summarize a flight-recorder "
                                           "dump directory")
    trs.add_argument("dir", help="dump directory (holds manifest.json)")
    trd = trsub.add_parser("dump", help="dump the in-process flight "
                                        "recorder")
    trd.add_argument("--out", default=None,
                     help="output base dir (default FLAGS_trace_dump_dir "
                          "or cwd)")
    trd.add_argument("--selftest", action="store_true",
                     help="record synthetic spans first and verify the "
                          "dump loads back")

    f = sub.add_parser("fleet", help="multi-replica serving: replica and "
                                     "router processes")
    fsub = f.add_subparsers(dest="fleet_action", required=True)
    fr = fsub.add_parser("replica", help="run one serving replica process "
                                         "(drains clean on /admin/drain "
                                         "or SIGTERM)")
    fr.add_argument("--model-dir", required=True,
                    help="save_inference_model directory")
    fr.add_argument("--place", default="cpu", choices=["tpu", "cpu"])
    fr.add_argument("--host", default="127.0.0.1")
    fr.add_argument("--port", type=int, default=0,
                    help="HTTP port (0 = ephemeral; see --port-file)")
    fr.add_argument("--port-file", default=None,
                    help="write the bound port here once listening")
    fr.add_argument("--name", default=None,
                    help="replica name (default replica-<port>)")
    fr.add_argument("--max-batch", type=int, default=8)
    fr.add_argument("--max-wait-ms", type=float, default=2.0)
    fr.add_argument("--replicas", type=int, default=1,
                    help="engine executor replicas inside this process")
    fr.add_argument("--max-queue-rows", type=int, default=None)
    fr.add_argument("--slo-ms", type=float, default=None,
                    help="latency SLO; violations count "
                         "serve_slo_violations_total and trigger "
                         "flight-recorder dumps under FLAGS_trace")
    fr.add_argument("--router", default=None, metavar="HOST:PORT",
                    help="register with this fleet router over HTTP")
    fr.add_argument("--master", default=None, metavar="HOST:PORT",
                    help="heartbeat a parallel.master TTL registration")
    fr.add_argument("--ttl", type=float, default=10.0,
                    help="registration TTL seconds")
    fr.add_argument("--chaos-kill-at", type=int, default=None, metavar="N",
                    help="SIGKILL this replica on its Nth executor "
                         "dispatch (failover drill)")
    fr.add_argument("--chaos-hang-at", type=int, default=None, metavar="N",
                    help="hang this replica on its Nth executor dispatch")
    fr.add_argument("--chaos-hang-ms", type=float, default=None,
                    help="hang duration (default: effectively forever)")
    fr.add_argument("--chaos-hang-times", type=int, default=1,
                    metavar="K",
                    help="hang on K consecutive dispatches from "
                         "--chaos-hang-at (straggler drills)")
    fr.add_argument("--chaos-delay-ms", type=float, default=None,
                    help="sleep this long on EVERY executor dispatch: a "
                         "deterministic service-time floor, so capacity "
                         "drills (the green_gate autoscale drill) see "
                         "the same queueing on any host")
    fr.add_argument("--obs", default=None, metavar="HOST:PORT",
                    help="push metrics/journal/trace snapshots to this "
                         "obs collector (see `paddle_tpu obs collect`)")
    fr.add_argument("--cache-dir", default=None,
                    help="persistent compile-cache directory shared by "
                         "the fleet (FLAGS_compile_cache_dir): only the "
                         "first replica compiles, the rest deserialize")
    fr.add_argument("--compile-service", default=None, metavar="HOST:PORT",
                    help="distributed compile service (a parallel.master "
                         "with compiled_* ops, FLAGS_compile_service): on "
                         "an L2 miss, fetch the serialized executable by "
                         "digest instead of compiling — scale-out warm "
                         "start with compile_cache_misses == 0. Needs "
                         "--cache-dir")
    fo = fsub.add_parser("router", help="run the fleet router over a "
                                        "replica set")
    fo.add_argument("--replicas", default="",
                    help="comma-separated replica host:port list")
    fo.add_argument("--master", default=None, metavar="HOST:PORT",
                    help="discover replicas from a parallel.master "
                         "registry (kind=serve)")
    fo.add_argument("--host", default="127.0.0.1")
    fo.add_argument("--port", type=int, default=8100)
    fo.add_argument("--probe-interval", type=float, default=0.5)
    fo.add_argument("--deadline-ms", type=float, default=30000.0,
                    help="per-request routing deadline")
    fo.add_argument("--attempt-timeout-ms", type=float, default=None,
                    help="per-attempt transport timeout")
    fo.add_argument("--max-attempts", type=int, default=3)
    fo.add_argument("--hedge-ms", type=float, default=None,
                    help="hedge a silent first attempt after this long")
    fo.add_argument("--obs", default=None, metavar="HOST:PORT",
                    help="push router metrics to this obs collector")
    fo.add_argument("--autoscale-model-dir", default=None, metavar="DIR",
                    help="enable the autoscaler: spawn `fleet replica` "
                         "processes serving this save_inference_model "
                         "dir when the latency target breaches, drain "
                         "them away when load calms")
    fo.add_argument("--autoscale-min", type=int, default=1,
                    help="autoscaler floor (replicas)")
    fo.add_argument("--autoscale-max", type=int, default=4,
                    help="autoscaler ceiling (replicas)")
    fo.add_argument("--autoscale-target-p99-ms", type=float, default=500.0,
                    help="windowed router p99 the autoscaler holds")
    fo.add_argument("--autoscale-queue-rows", type=float, default=None,
                    help="queued rows across the fleet that also arm "
                         "scale-out")
    fo.add_argument("--autoscale-interval", type=float, default=1.0,
                    help="control-loop tick seconds")
    fo.add_argument("--autoscale-cooldown-out", type=float, default=5.0,
                    help="seconds between scale-outs")
    fo.add_argument("--autoscale-cooldown-in", type=float, default=30.0,
                    help="seconds between scale-ins")
    fo.add_argument("--autoscale-cache-dir", default=None,
                    help="shared --cache-dir for spawned replicas "
                         "(default: per-replica dirs under a temp "
                         "workdir — with --compile-service, warm start "
                         "then rides fetch_compiled, not the filesystem)")
    fo.add_argument("--compile-service", default=None, metavar="HOST:PORT",
                    help="pass through to spawned replicas so scale-out "
                         "warm-starts from peers' compiles")

    ob = sub.add_parser("obs", help="fleet-wide observability: collector "
                                    "sink, live top table, merged "
                                    "timeline")
    obsub = ob.add_subparsers(dest="obs_action", required=True)
    obc = obsub.add_parser("collect", help="run the fleet collector "
                                           "(push sink + scrape poller + "
                                           "aggregated /metrics)")
    obc.add_argument("--host", default="127.0.0.1")
    obc.add_argument("--port", type=int, default=9200,
                     help="HTTP port (0 = ephemeral; see --port-file)")
    obc.add_argument("--port-file", default=None,
                     help="write the bound port here once listening")
    obc.add_argument("--ttl", type=float, default=None,
                     help="stale-process expiry seconds "
                          "(default FLAGS_obs_ttl_s)")
    obc.add_argument("--scrape", action="append", default=None,
                     metavar="[NAME=]HOST:PORT",
                     help="poll this /metrics exposition as a fleet "
                          "member (repeatable)")
    obc.add_argument("--scrape-interval", type=float, default=2.0)
    obc.add_argument("--straggler-ratio", type=float, default=1.2,
                     help="slowest/median step-time ratio that counts "
                          "toward straggler attribution")
    obc.add_argument("--straggler-steps", type=int, default=3,
                     help="consecutive slowest steps before "
                          "fleet_straggler{replica=} fires")
    obt = obsub.add_parser("top", help="live fleet table over the "
                                       "collector summary (redraws in "
                                       "place on a TTY)")
    obt.add_argument("--collector", required=True, metavar="HOST:PORT")
    obt.add_argument("--interval", type=float, default=2.0)
    obt.add_argument("--once", action="store_true",
                     help="print one frame and exit")
    obt.add_argument("--json", action="store_true",
                     help="emit raw summary JSON frames")
    obt.add_argument("--iterations", type=int, default=None,
                     help=argparse.SUPPRESS)
    obl = obsub.add_parser("timeline", help="merged fleet timeline: "
                                            "cross-replica skew table + "
                                            "one chrome trace with a pid "
                                            "lane per process")
    obl.add_argument("--collector", default=None, metavar="HOST:PORT",
                     help="pull the step timeline and known dumps from "
                          "this collector")
    obl.add_argument("--dump", action="append", default=None,
                     metavar="DIR",
                     help="merge this flight-recorder dump directory "
                          "(repeatable)")
    obl.add_argument("--out", default=None,
                     help="write the merged chrome trace JSON here")

    e = sub.add_parser("elastic", help="elastic training membership: "
                                       "status snapshot and manual drain")
    esub = e.add_subparsers(dest="elastic_action", required=True)
    es = esub.add_parser("status", help="epoch, world size and members of "
                                        "a running elastic job")
    es.add_argument("--master", required=True, metavar="HOST:PORT",
                    help="the job's parallel.master endpoint")
    es.add_argument("--timeout", type=float, default=10.0,
                    help="master connect timeout seconds")
    es.add_argument("--json", action="store_true",
                    help="emit the snapshot as JSON")
    ed = esub.add_parser("drain", help="remove a worker from the "
                                       "membership (manual scale-down)")
    ed.add_argument("name", help="worker membership name to remove")
    ed.add_argument("--master", required=True, metavar="HOST:PORT",
                    help="the job's parallel.master endpoint")
    ed.add_argument("--timeout", type=float, default=10.0,
                    help="master connect timeout seconds")

    t = sub.add_parser("train", help="launch a training script with "
                                     "cluster environment")
    t.add_argument("--role", default="trainer",
                   choices=["trainer", "pserver"])
    t.add_argument("--trainers", type=int, default=1)
    t.add_argument("--trainer-id", type=int, default=0)
    t.add_argument("--pservers", default="",
                   help="comma-separated host:port list")
    t.add_argument("--current-endpoint", default="",
                   help="this pserver's host:port")
    t.add_argument("script")
    t.add_argument("script_args", nargs=argparse.REMAINDER)

    args = parser.parse_args(argv)
    try:
        if args.command == "version":
            return _cmd_version(args)
        if args.command == "flags":
            return _cmd_flags(args)
        if args.command == "monitor":
            return _cmd_monitor(args)
        if args.command == "health":
            return _cmd_health(args)
        if args.command == "cache":
            return _cmd_cache(args)
        if args.command == "checkpoint":
            return _cmd_checkpoint(args)
        if args.command == "shard":
            return _cmd_shard(args)
        if args.command == "check":
            return _cmd_check(args)
        if args.command == "analyze":
            return _cmd_analyze(args)
        if args.command == "serve":
            return _cmd_serve(args)
        if args.command == "trace":
            return _cmd_trace(args)
        if args.command == "fleet":
            return _cmd_fleet(args)
        if args.command == "obs":
            return _cmd_obs(args)
        if args.command == "elastic":
            return _cmd_elastic(args)
        if args.command == "train":
            return _cmd_train(args)
    except BrokenPipeError:
        # downstream pager/head closed the pipe; exit quietly
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    parser.error(f"unknown command {args.command}")


if __name__ == "__main__":
    sys.exit(main())
