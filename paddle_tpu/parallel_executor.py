"""ParallelExecutor: single-process data parallelism over the TPU mesh.

Reference parity: paddle/fluid/framework/parallel_executor.cc:54 +
python/paddle/fluid/parallel_executor.py. The reference builds an SSA graph
with one NCCL all-reduce per gradient and a threaded dataflow executor
(threaded_ssa_graph_executor.cc:33). TPU-native equivalent: the SAME traced
step function as Executor, jit-compiled over a jax.sharding.Mesh with
  - feeds sharded on the batch axis (P("dp"))
  - parameters/optimizer state replicated (BuildStrategy.AllReduce) or
    sharded on dim0 (BuildStrategy.Reduce — ZeRO-1-style, the analogue of
    the reference's kReduce balancing strategy, multi_devices_graph_builder
    .cc:221)
XLA inserts the gradient all-reduce/reduce-scatter collectives over ICI and
overlaps them with compute — the role the ThreadedSSAGraphExecutor +
allow_op_delay flags played on GPU.

ZeRO-1 sharded weight update (BuildStrategy.sharded_weight_update /
FLAGS_zero1, arXiv 2004.13336): the program is rewritten by
parallel.zero1.apply before compilation — gradients reduce-scatter over
the dp axis, each replica updates a 1/N param shard with shard-sized
optimizer accumulators, updated shards all-gather back into the
replicated param. The all-gather sits at the tail of the traced step with
no same-step consumers, so XLA overlaps it with the next scan iteration's
forward (iters=K) and, on the per-step path, it completes under async
dispatch while the host preps the next feed.

Multi-node ("NCCL2 mode", num_trainers/trainer_id) maps to jax.distributed
with a mesh spanning hosts; see parallel/distributed.py.
"""

import time

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import amp
from . import analysis
from . import flags
from . import monitor
from .cache import CompileCache
from .core import executor_core
from .core.framework import Parameter, Variable, default_main_program
from .core.lod_tensor import LoDTensor
from .core.registry import SeqTensor
from .core.scope import global_scope
from .executor import as_numpy, _apply_debug_nans
from . import health as _health
from .parallel import autoshard as _autoshard
from .parallel import zero1 as _zero1
from .resilience import chaos as _chaos
from .resilience import watchdog as _watchdog
from .trace import costs as _trace_costs

__all__ = ["ParallelExecutor", "ExecutionStrategy", "BuildStrategy"]


class ExecutionStrategy:
    """reference framework/details/execution_strategy.h. On TPU these are
    advisory: XLA owns scheduling. Kept for API parity + cache control."""

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100
        self.use_event = True


class BuildStrategy:
    """reference framework/details/build_strategy.h:22-31."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1  # -> shard optimizer state over the mesh (ZeRO-1 analogue)

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        # ZeRO-1 sharded weight update (arXiv 2004.13336): None defers to
        # FLAGS_zero1; True/False overrides the flag for this executor
        self.sharded_weight_update = None
        # GSPMD-style autoshard (parallel.autoshard): propagate set_sharding
        # seeds over the whole program and lower the plan as
        # with_sharding_constraint. None defers to FLAGS_autoshard.
        self.auto_sharding = None
        self.debug_graphviz_path = ""


class ParallelExecutor:
    def __init__(
        self,
        use_cuda=True,
        loss_name=None,
        main_program=None,
        share_vars_from=None,
        exec_strategy=None,
        build_strategy=None,
        num_trainers=1,
        trainer_id=0,
        use_tpu=None,
        mesh_shape=None,
        devices=None,
        **kwargs,
    ):
        self._program = main_program or default_main_program()
        self._loss_name = loss_name
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._build_strategy = build_strategy or BuildStrategy()
        self._scope = (
            share_vars_from._scope if share_vars_from is not None else global_scope()
        )
        accel = use_tpu if use_tpu is not None else use_cuda
        if devices is not None:
            # explicit device subset — the elastic resize path re-forms a
            # smaller mesh over the survivors' device slots
            accel_devs = list(devices)
        else:
            devs = jax.devices()
            if accel:
                accel_devs = [d for d in devs if d.platform != "cpu"] or devs
            else:
                accel_devs = devs
        self._devices = accel_devs
        if mesh_shape:
            # user-declared multi-axis mesh ({"dp": 2, "mp": 4}); variables
            # annotated via parallel.set_sharding place onto these axes
            axes = list(mesh_shape.items())
            total = int(np.prod([s for _, s in axes]))
            if total != len(self._devices):
                raise ValueError(
                    f"mesh_shape {mesh_shape} needs {total} devices, have "
                    f"{len(self._devices)}")
            self._mesh = Mesh(
                np.array(self._devices).reshape([s for _, s in axes]),
                tuple(n for n, _ in axes))
        else:
            self._mesh = Mesh(np.array(self._devices), ("dp",))
        self._compile_cache = CompileCache("parallel_executor")
        # zero1/grad-scale rewritten program clones, keyed on the source
        # program identity + mutation counter; strong refs keep id() stable
        # for the compile cache
        self._rewrite_cache = {}
        # autoshard ShardingPlans, keyed on (program identity, mutation)
        self._autoshard_cache = {}
        # overlap-scheduled (reordered) clones of the resolved program +
        # their ScheduleReport, keyed on (program identity, mutation,
        # bucket bytes); strong refs keep id() stable for the compile cache
        self._overlap_cache = {}
        # fused clones of the resolved program + their FusionPlan, keyed
        # on (program identity, mutation, bucket budget, fetches); strong
        # refs keep id() stable for the compile cache
        self._fusion_cache = {}
        self._step = 0
        self.num_trainers = num_trainers
        self.trainer_id = trainer_id

    @property
    def device_count(self):
        return len(self._devices)

    def compile_cache_info(self):
        """Compile-cache stats: entries plus hit/miss/eviction counters and
        the persistent-L2 counter family (cache.CompileCache.info). The
        "entries" key is load-bearing — the serving engine diffs it across
        warmup to assert zero steady-state compiles."""
        return self._compile_cache.info()

    def _l2_extra(self):
        """Mesh/device context folded into the persistent-cache digest: a
        serialized executable is bound to its device assignment, so an
        elastic resize (different mesh geometry or device set) takes a
        clean miss instead of a deserialize-time failure."""
        return (
            ("mesh", tuple((str(k), int(v))
                           for k, v in self._mesh.shape.items())),
            ("devices", tuple(
                (getattr(d, "platform", "?"), int(getattr(d, "id", -1)))
                for d in self._devices)),
            ("procs", int(jax.process_count()), int(jax.process_index())),
        )

    def _cache_store(self, cache_key, entry, mon=None):
        """Insert a compile-cache entry; cache.CompileCache owns the
        FLAGS_compile_cache_cap true-LRU eviction and its counters."""
        self._compile_cache.put(cache_key, entry, mon=mon)

    # ------------------------------------------------------------------
    def _prepare_program(self, program, use_zero1, gss, dp_n):
        """Resolve the program actually compiled this run.

        zero1: parallel.zero1.apply clones the program and sandwiches every
        shardable optimizer op between a gradient reduce-scatter and a param
        all-gather, with GradientScaleStrategy folded into the scatter (One
        = sum semantics -> x dp_n; CoeffNumDevice = mean, and the traced
        loss already averages over the GLOBAL batch, so the folded scale is
        1.0). all-reduce path: GradientScaleStrategy.One inserts the
        equivalent full-size per-grad scale ops so the two paths stay
        numerically comparable. Clones are cached per (program identity,
        mutation, zero1, scale strategy, dp size) so recompiles only track
        real program mutations."""
        key = (id(program), program._mutation, use_zero1, gss, dp_n)
        hit = self._rewrite_cache.get(key)
        if hit is not None:
            return hit
        one = BuildStrategy.GradientScaleStrategy.One
        if use_zero1:
            run_program, plan = _zero1.apply(
                program, dp_n,
                grad_scale=float(dp_n) if gss == one else 1.0)
            if not plan.entries:
                # nothing shardable: keep the original so the compile cache
                # is shared with the plain all-reduce path
                run_program = program
        else:
            plan = _zero1.build_plan(program, dp_n)
            run_program = program
            if gss == one and plan.entries:
                run_program = _zero1.apply_grad_scale(
                    program, plan, float(dp_n))
        self._rewrite_cache[key] = (run_program, plan)
        return run_program, plan

    def _overlap_program(self, program, feed_names=None):
        """Apply the static overlap schedule (analysis.schedule) to the
        RESOLVED program: hoist the legal zero1_scatter reduce-scatters
        into the backward section, bucketed under
        FLAGS_overlap_bucket_bytes. Returns (program', ScheduleReport);
        cached per (program identity, mutation, bucket bytes). A program
        carrying any PTA03x dataflow hazard raises
        ProgramVerificationError — it is never silently reordered."""
        key = (id(program), program._mutation,
               int(flags.get("overlap_bucket_bytes")))
        hit = self._overlap_cache.get(key)
        if hit is None:
            sched = analysis.schedule.analyze(
                program,
                mesh_axes={str(k): int(v)
                           for k, v in self._mesh.shape.items()},
                feed_names=feed_names)
            reordered, _ = analysis.schedule.apply_plan(
                program, sched.plan, feed_names=feed_names)
            hit = (reordered, sched)
            self._overlap_cache[key] = hit
        return hit

    def _fuse_program(self, program, feed_names, fetch_names):
        """Apply cost-guided fusion (paddle_tpu.fusion) to the RESOLVED
        program — after zero1 and overlap, so optimizer buckets see the
        final shard-layout wiring, and before autoshard, so the fused
        ops' operands inherit the plan like any other op. Returns
        (program', FusionPlan or None); cached per (program identity,
        mutation, bucket budget, feeds, fetches)."""
        from . import fusion

        key = (id(program), program._mutation,
               int(flags.get("fuse_bucket_mb")),
               tuple(sorted(feed_names or [])), tuple(fetch_names))
        hit = self._fusion_cache.get(key)
        if hit is None:
            hit = fusion.apply(program, feed_names=feed_names,
                               fetch_names=fetch_names)
            self._fusion_cache[key] = hit
        return hit

    def _autoshard_plan(self, program):
        """Total ShardingPlan for the RESOLVED program (zero1-rewritten when
        that pass is on, so its shard-layout accumulator annotations become
        locked seeds). Cached per (program identity, mutation, mesh)."""
        mesh_axes = {str(k): int(v) for k, v in self._mesh.shape.items()}
        key = (id(program), program._mutation,
               tuple(sorted(mesh_axes.items())))
        plan = self._autoshard_cache.get(key)
        if plan is None:
            plan = _autoshard.build_plan(program, mesh_axes)
            self._autoshard_cache[key] = plan
        return plan

    def _state_sharding(self, name, value, program=None, plan=None):
        """User set_sharding() rules win; then the autoshard plan's spec
        when a plan is active; else replicated by default, with
        BuildStrategy.Reduce sharding optimizer accumulators (non-Parameter
        persistables) on dim 0 when divisible (ZeRO-1 analogue)."""
        program = program if program is not None else self._program
        var = program.global_block().vars.get(name)
        spec = getattr(var, "sharding", None) if var is not None else None
        if spec is not None:
            ndim = len(value.shape) if hasattr(value, "shape") else 0
            if len(spec) > ndim:
                raise ValueError(
                    f"{name}: sharding spec {spec} longer than the runtime "
                    f"rank {ndim}")
            for d, ax in enumerate(spec):
                if ax is None:
                    continue
                if ax not in self._mesh.shape:
                    raise ValueError(
                        f"{name}: sharding axis {ax!r} not in the mesh "
                        f"{dict(self._mesh.shape)} — pass mesh_shape= to "
                        f"ParallelExecutor")
                if value.shape[d] % self._mesh.shape[ax] != 0:
                    raise ValueError(
                        f"{name} dim {d} ({value.shape[d]}) not divisible "
                        f"by mesh axis {ax!r} ({self._mesh.shape[ax]})")
            return NamedSharding(self._mesh, P(*spec))
        if plan is not None:
            pspec = plan.spec_of(name)
            if pspec and hasattr(value, "shape") \
                    and len(pspec) <= len(value.shape):
                # plan specs are derived from static shapes; skip any that
                # don't divide the runtime shape rather than erroring
                ok = all(
                    ax is None or value.shape[d] % self._mesh.shape[ax] == 0
                    for d, ax in enumerate(pspec))
                if ok:
                    return NamedSharding(self._mesh, P(*pspec))
        n = len(self._devices)
        if (
            self._build_strategy.reduce_strategy == BuildStrategy.ReduceStrategy.Reduce
            and not isinstance(var, Parameter)
            and hasattr(value, "shape")
            and value.ndim >= 1
            and value.shape[0] % n == 0
            and value.shape[0] >= n
        ):
            return NamedSharding(self._mesh, P("dp"))
        return NamedSharding(self._mesh, P())

    def _feed_sharding(self, value, leading_steps=False):
        if isinstance(value, SeqTensor):
            return SeqTensor(
                jax.device_put(value.data, NamedSharding(self._mesh, P("dp"))),
                jax.device_put(value.lengths, NamedSharding(self._mesh, P("dp"))),
            )
        # iters=K feeds carry a leading [K] step axis; the batch axis to
        # shard over dp is axis 1 there
        spec = P(None, "dp") if leading_steps else P("dp")
        return jax.device_put(value, NamedSharding(self._mesh, spec))

    # ------------------------------------------------------------------
    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True,
            iters=None, async_fetch=False, donate_feeds=None):
        """One data-parallel step over the mesh — or, with `iters=K`, K
        steps inside ONE jit'd lax.scan dispatch (feeds carry a leading
        [K] axis, batch sharded over "dp" on axis 1; fetches come back
        stacked [K, ...]). Same contract as Executor.run(iters=K).

        `feed` may be a datapipe.DataPipe: the next prefetched chunk is
        pulled here (the step's `feed_wait` phase; DataPipeError from a
        dead decode worker propagates) and iters defaults to the pipe's
        chunk size. Transfer-engine markers (WIRE_KEY/DONATE_KEY) riding
        a staged chunk are honoured the same way as Executor.run: wire
        decode fused into the compiled step, single-use chunks donated —
        including chunks staged zero-copy from the process-pool shm ring.
        `async_fetch=True`
        returns FetchFuture handles instead of host arrays."""
        _apply_debug_nans()
        # single flag check when monitoring is off (same contract as
        # Executor.run); every site below gates on `mon is not None`
        mon = monitor.step_begin("parallel_executor") \
            if monitor.enabled() else None
        feed = feed if feed is not None else feed_dict
        pipe = feed if hasattr(feed, "next_feed") else None
        if pipe is not None:  # datapipe.DataPipe (duck-typed)
            if iters is None:
                iters = getattr(pipe, "feed_iters", None)
            if mon is not None:
                with mon.timed("feed_wait"):
                    feed = pipe.next_feed()
            else:
                feed = pipe.next_feed()
        from .datapipe.transfer import pop_markers
        feed, wire, chunk_donate = pop_markers(feed)
        if donate_feeds is None:
            donate_feeds = chunk_donate
        donate_feeds = bool(donate_feeds) \
            and bool(flags.get("donate_feed_buffers")) \
            and not flags.get("debug_nans")
        if isinstance(feed, list) and iters is None:
            # per-device feed list (reference feed_parallel): concatenate
            merged = {}
            for d in feed:
                for k, v in d.items():
                    arr = np.asarray(v.numpy() if isinstance(v, LoDTensor) else v)
                    merged.setdefault(k, []).append(arr)
            feed = {k: np.concatenate(vs, axis=0) for k, vs in merged.items()}
        fetch_names = [v.name if isinstance(v, Variable) else str(v) for v in fetch_list]

        program, scope = self._program, self._scope
        bs = self._build_strategy
        use_zero1 = bs.sharded_weight_update
        if use_zero1 is None:
            use_zero1 = bool(flags.get("zero1"))
        dp_n = int(dict(self._mesh.shape).get("dp", 1))
        use_zero1 = bool(use_zero1) and dp_n >= 2
        gss = bs.gradient_scale_strategy
        # everything below (feed staging, state collection, trace, state
        # placement) runs against the resolved program — the zero1 rewrite
        # when sharding is on, else the original (plus One-scale ops)
        program, zplan = self._prepare_program(program, use_zero1, gss, dp_n)
        # static overlap schedule (FLAGS_overlap_plan): reorder the zero1-
        # rewritten program so grad reduce-scatters overlap the backward
        # pass. Hazard-checked, cached, and compile-cache-keyed below.
        use_overlap = bool(flags.get("overlap_plan")) and use_zero1 \
            and bool(zplan.entries)
        osched = None
        if use_overlap:
            program, osched = self._overlap_program(
                program,
                feed_names=list(feed) if isinstance(feed, dict) else None)
        # cost-guided fusion (FLAGS_fuse): after zero1/overlap so buckets
        # see the final wiring, before autoshard so fused operands get
        # plan layouts like any other op. Digest joins the cache key.
        fplan = None
        if flags.get("fuse"):
            program, fplan = self._fuse_program(
                program,
                feed_names=list(feed) if isinstance(feed, dict) else [],
                fetch_names=fetch_names)
        use_autoshard = bs.auto_sharding
        if use_autoshard is None:
            use_autoshard = bool(flags.get("autoshard"))
        use_autoshard = bool(use_autoshard) and len(self._devices) > 1
        aplan = None
        if use_autoshard:
            # built on the RESOLVED program so zero1's accumulator layouts
            # compose as locked seeds; raises the clear compile-time error
            # for bad seeds (unknown axis / non-divisible static dim)
            aplan = self._autoshard_plan(program)
            _autoshard.register_plan(aplan)
        else:
            # same compile-time seed validation even when the pass is off —
            # a bad annotation should never surface mid-placement
            _autoshard.validate_seeds(program, dict(self._mesh.shape))
        if use_zero1 and zplan.entries:
            # accumulators live permanently in [dp_n, shard] layout; a
            # full-layout scope (startup init, or a checkpoint restore)
            # converts once here
            zplan.ensure_scope_sharded(scope)
        else:
            # restore onto zero1=0 after a sharded run: fold any shard-
            # layout accumulators back to their canonical full layout
            _zero1.ensure_scope_unsharded(scope, program)
        if mon is not None and zplan.entries:
            # analytic ring-collective accounting for the dp gradient path
            # (bytes, not time — XLA owns the schedule); journal extras ride
            # into the JSONL record for `python -m paddle_tpu monitor`
            cb = zplan.collective_bytes(sharded=use_zero1)
            osb = zplan.optimizer_state_bytes(sharded=use_zero1)
            reg = monitor.registry()
            for op_name, nbytes in sorted(cb.items()):
                reg.gauge(
                    "collective_bytes_per_step",
                    help="analytic per-step dp-collective traffic (ring)",
                    op=op_name).set(float(nbytes))
            reg.gauge(
                "optimizer_state_bytes_per_replica",
                help="optimizer accumulator bytes resident per replica",
            ).set(float(osb))
            if mon.extra is None:
                mon.extra = {}
            mon.extra["collective_bytes"] = {
                k: int(v) for k, v in cb.items()}
            mon.extra["optimizer_state_bytes"] = int(osb)
            mon.extra["zero1"] = bool(use_zero1)
        if mon is not None and osched is not None:
            analysis.schedule.record_gauges(
                osched, context="parallel_executor")
            if mon.extra is None:
                mon.extra = {}
            mon.extra["overlap"] = {
                "critical_path_ms": float(osched.critical_path_ms),
                "hoistable_bytes": int(osched.plan.hoistable_bytes),
                "buckets": len(osched.plan.buckets),
                "moves": len(osched.plan.moves),
                "digest": osched.plan.digest(),
            }
        if mon is not None and aplan is not None:
            reg = monitor.registry()
            reg.gauge(
                "autoshard_reshard_bytes_per_step",
                help="analytic per-step reshard traffic forced by plan "
                     "conflicts and locked-seed boundaries",
            ).set(float(aplan.reshard_bytes_per_step()))
            reg.gauge(
                "autoshard_plan_vars",
                help="variables covered by the active autoshard plan",
            ).set(float(len(aplan.specs)))
            reg.gauge(
                "autoshard_plan_sharded_vars",
                help="plan variables with at least one sharded dim",
            ).set(float(len(aplan.sharded_names())))
            reg.gauge(
                "autoshard_conflicts_resolved",
                help="propagation conflicts arbitrated by the cost model",
            ).set(float(len(aplan.conflicts)))
            reg.gauge(
                "autoshard_unresolved_vars",
                help="plan variables with no resolvable layout (should be 0)",
            ).set(float(len(aplan.unresolved)))
            if mon.extra is None:
                mon.extra = {}
            mon.extra["autoshard"] = {
                "digest": aplan.digest(),
                "sharded_vars": len(aplan.sharded_names()),
                "conflicts": len(aplan.conflicts),
                "reshard_bytes": int(aplan.reshard_bytes_per_step()),
            }
        t_enc = time.perf_counter() if mon is not None else None
        feed_vals = {}
        if iters is not None:
            # shared stacking helper: list-length and leading-axis checks,
            # LoD rejection, dtype cast — the same contract as
            # Executor.run(iters=K); an empty feed list fails there too
            from .executor import stack_multi_step_feeds

            for name, value in stack_multi_step_feeds(
                    program, feed if feed is not None else {},
                    iters, wire=wire).items():
                feed_vals[name] = self._feed_sharding(
                    value, leading_steps=True)
        else:
            feed = feed or {}
            for name, value in feed.items():
                tv = executor_core.feed_to_tracevalue(value)
                feed_vals[name] = self._feed_sharding(tv)
        if mon is not None:
            # stacking + device_put onto the mesh (the h2d link for feeds)
            mon.phase("feed_encode", time.perf_counter() - t_enc)

        state_names, state_out_names = executor_core.collect_state_names(program, scope)
        # health sees the RESOLVED program, so under zero1 the plan pairs
        # the canonical param with its reduce-scattered [N, shard] grad —
        # shard-local reductions, no regather (health/stats.py)
        hplan = _health.plan_if_enabled(program)
        cache_key = (
            id(program),
            program._mutation,
            tuple(sorted((n, executor_core.spec_of(v)) for n, v in feed_vals.items())),
            tuple(fetch_names),
            tuple(state_names),
            amp.fingerprint(),
            flags.get("fuse_optimizer_ops"),  # trace-affecting, like amp
            flags.get("debug_nans"),  # changes donation, like Executor
            ("iters", iters),
            ("wire", wire.fingerprint() if wire is not None else None),
            ("donate_feeds", donate_feeds),
            ("zero1", use_zero1, gss, dp_n),
            ("overlap",
             osched.plan.digest() if osched is not None else None),
            ("autoshard", aplan.digest() if aplan is not None else None),
            ("fuse", fplan.digest() if fplan is not None else None),
            ("health", hplan.digest if hplan is not None else None),
            # stage programs from parallel.pipeline share var names with
            # each other and the source program; the (plan digest, stage,
            # phase) tag keeps their executables from colliding
            ("pipeline", getattr(program, "_pipeline_stage", None)),
        )
        entry = self._compile_cache.get(cache_key)
        fp = monitor.fingerprint_of(cache_key) if mon is not None else None
        build_s = 0.0
        was_miss = entry is None
        level = "l1" if entry is not None else None
        if entry is None:
            # FLAGS_verify on the MISS path only, with the mesh and the
            # zero1/autoshard plans in scope so the `full` level can run
            # the sharding checks and the per-replica peak-HBM estimate
            analysis.ensure_verified(
                program, feed_names=list(feed_vals),
                fetch_names=list(fetch_names),
                mesh_axes=dict(self._mesh.shape),
                zplan=zplan if use_zero1 and zplan.entries else None,
                aplan=aplan,
                donate_state=not flags.get("debug_nans"),
                context="parallel_executor")
            tb = time.perf_counter()
            if iters is not None:
                missing = [n for n in state_out_names
                           if not scope.has_var(n)]
                if missing:
                    raise ValueError(
                        f"iters > 1 needs every written persistable var in "
                        f"scope before the scan; missing: {missing}. Run "
                        f"the startup program first.")
            cache_obj = self._compile_cache
            digest = cache_obj.l2_digest(
                program, cache_key[2:], extra=self._l2_extra()) \
                if cache_obj.l2_enabled() else None

            def _fresh(export_digest=None):
                constraints = None
                if aplan is not None:
                    constraints = {
                        n: NamedSharding(self._mesh, P(*s))
                        for n, s in aplan.boundary_specs().items()}
                built_fetch = (list(fetch_names) + hplan.fetch_names
                               if hplan is not None else fetch_names)
                step = executor_core.build_step_fn(
                    program, built_fetch, state_out_names,
                    constraints=constraints)
                if wire is not None:
                    # decode in the PER-STEP fn (before the scan wrapper),
                    # so each iteration widens only its own [batch] slice
                    gb = program.global_block()
                    var_dtypes = {
                        n: gb.vars[n].dtype for n in wire
                        if n in gb.vars and gb.vars[n].dtype is not None}
                    step = wire.wrap_step(step, var_dtypes=var_dtypes)
                if hplan is not None:
                    # per-step stats reduction before any scan wrapper, so
                    # a K-step scan stacks [4]-stat leaves, not raw grads;
                    # GSPMD lowers the reductions shard-locally on the mesh
                    step = hplan.wrap_step(step, len(fetch_names))
                if iters is not None:
                    step = executor_core.build_multi_step_fn(step, iters)
                probe = monitor.compile_probe(fp) \
                    if mon is not None and flags.get("monitor_hlo_cost") \
                    else None
                return executor_core.compile_step_fn(
                    step, donate_state=not flags.get("debug_nans"),
                    donate_feeds=donate_feeds, probe=probe,
                    aot=cache_obj.aot_sink(export_digest))

            loaded = cache_obj.l2_load(digest, mon=mon) \
                if digest is not None else None
            if loaded is not None:
                # warm start (fleet replica spin-up, resilience restore,
                # elastic re-join): deserialized from the shared
                # FLAGS_compile_cache_dir instead of compiling; a
                # first-call signature mismatch rebuilds fresh (guard_l2)
                compiled = cache_obj.guard_l2(loaded, _fresh, mon=mon)
                was_miss = False
                level = "l2"
            else:
                compiled = _fresh(digest)
            build_s = time.perf_counter() - tb
            entry = (compiled, state_names, state_out_names)
            self._cache_store(cache_key, entry, mon=mon)
        if mon is not None:
            mon.mark_cache(not was_miss, fingerprint=fp, level=level)
        compiled, state_names, state_out_names = entry

        multiproc = any(
            d.process_index != jax.process_index()
            for d in self._mesh.devices.flat)

        def place(v, desired):
            arr = jax.numpy.asarray(v)
            if multiproc:
                # a committed single-device array cannot be resharded onto a
                # cross-process mesh directly; round-trip through the host —
                # every process holds the identical global value (same-seed
                # startup), so device_put scatters consistent local shards
                arr = np.asarray(arr)
            return jax.device_put(arr, desired)

        mut_state, const_state = {}, {}
        out_set = set(state_out_names)
        for n in state_names:
            v = scope.find_var(n)
            if isinstance(v, LoDTensor):
                v = executor_core.feed_to_tracevalue(v)
            var = program.global_block().vars.get(n)
            annotated = getattr(var, "sharding", None) is not None
            planned = aplan is not None and bool(aplan.spec_of(n))
            cur = getattr(v, "sharding", None)
            on_mesh = isinstance(cur, NamedSharding) and cur.mesh == self._mesh
            if annotated or planned:
                # the rule (user seed or plan spec) must win over whatever
                # placement startup left behind — but once the array already
                # carries the desired NamedSharding (every step after the
                # first), re-placing would all-gather the shards to host
                desired = self._state_sharding(n, v, program=program,
                                               plan=aplan)
                if cur != desired:
                    v = place(v, desired)
            elif not on_mesh or not getattr(v, "committed", True):
                # startup leaves single-device committed arrays; a jit over
                # the mesh auto-transfers those in-process but REJECTS them
                # when the mesh spans processes — re-place onto this mesh
                v = place(v, self._state_sharding(n, v, program=program,
                                                  plan=aplan))
            (mut_state if n in out_set else const_state)[n] = v

        base_key = jax.random.PRNGKey(program.random_seed)
        step0 = self._step
        if iters is not None:
            # multi-step scan folds base at step0+i internally — same rng
            # stream as iters sequential run() calls (executor_core
            # build_multi_step_fn); step0 traced to keep the cache hot
            rng = (base_key, jax.numpy.asarray(self._step, jax.numpy.int32))
            self._step += iters
        else:
            rng = jax.random.fold_in(base_key, self._step)
            self._step += 1
        # fault-injection hook (no-op without an installed ChaosMonkey),
        # before the dispatch so donated buffers are intact on a raise
        _chaos.on_run("parallel_executor")
        tc = time.perf_counter() if mon is not None else None
        with _watchdog.armed("parallel_executor"), self._mesh:
            fetches, new_mut = compiled(mut_state, const_state, feed_vals, rng)
        hstats = None
        if hplan is not None:
            hstats = fetches[-1]
            fetches = fetches[:-1]
        replica_ms = replica_ids = None
        if mon is not None:
            if flags.get("monitor_replica_skew"):
                # fence each replica's shard of a step output in device
                # order — stamps per-replica completion. Synchronizes the
                # dispatch queue, hence the separate opt-in flag.
                leaf = fetches[0] if fetches else \
                    next(iter(new_mut.values()), None)
                if leaf is not None:
                    res = monitor.measure_replica_ms(leaf, tc)
                    if res is not None:
                        replica_ms, replica_ids = res
            call_s = time.perf_counter() - tc
            if was_miss:  # first call compiles under async dispatch
                mon.phase("compile", build_s + call_s)
                monitor.record_compile(fp, wall_s=build_s + call_s)
                _trace_costs.register_program(fp, program)
            elif level == "l2":
                # warm start: deserialize wall time, no XLA compile
                mon.phase("cache_load", build_s)
                mon.phase("dispatch", call_s)
            else:
                mon.phase("dispatch", call_s)
        for n, v in new_mut.items():
            scope.set_var(n, v)
        if hstats is not None:
            _health.on_step(step0, iters, hstats, fetch_names, fetches,
                            mon=mon, kind="parallel_executor")
        if was_miss and flags.get("verify") == "full":
            # measured counterpart of the analysis_peak_hbm gauge: bytes
            # actually resident on one device for this step's state (the
            # estimate is gated against this within 2x in the tests)
            live = analysis.measured_live_bytes(
                list(new_mut.values()) + list(const_state.values())
                + list(fetches))
            monitor.registry().gauge(
                "hbm_live_bytes_per_replica",
                help="measured per-device resident bytes of the step "
                     "state + fetches",
            ).set(float(live))
        outs = [
            executor_core.value_to_lod_tensor(f) if isinstance(f, SeqTensor) else f
            for f in fetches
        ]
        if async_fetch:
            from .executor import FetchFuture

            outs = [FetchFuture(o) for o in outs]
        elif return_numpy:
            if mon is not None:
                with mon.timed("fetch_readback"):
                    outs = [as_numpy(o) for o in outs]
            else:
                outs = [as_numpy(o) for o in outs]
        if mon is not None:
            monitor.step_end(mon, iters=iters, datapipe=pipe,
                             replica_ms=replica_ms, replica_ids=replica_ids)
        return outs

    def bcast_params(self):
        """reference parallel_executor.py:242 — under SPMD params live as
        replicated jax.Arrays, so broadcast is placement, done in run()."""
        return None
