"""Pallas TPU kernels for the horizontal fused weight update.

One bucket = one flat f32 lane holding every member parameter (or
zero1 shard) back to back. The kernels view that lane as (rows, 128)
with rows a multiple of 8 — the Mosaic (8, 128) register tile — and walk
it with a 1-D parallel grid, one (8, 128) block per step: parameter,
gradient and moment blocks stream VMEM-resident through a single
read-modify-write pass instead of XLA's generic loop fusion. Scalars
(learning rate, bias-corrected step size, betas) ride along as (1, 1)
blocks mapped to every grid step.

The bucket is zero-padded up to a whole number of (8, 128) blocks;
padded lanes compute garbage that the caller slices away (the ops layer
unpacks by exact member widths). Bitwise parity with the scalar op
kernels holds because each block evaluates the same expression tree in
the same dtype — `interpret=True` keeps that true off-TPU, where the
interpreter executes the identical jax primitives.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["momentum_bucket", "adam_bucket"]

_LANES = 128
_SUBLANES = 8
_BLOCK = _LANES * _SUBLANES

# jax renamed TPUCompilerParams -> CompilerParams across versions
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def _pad2d(x):
    """Flat [n] -> (rows, 128) with rows a multiple of 8, zero-padded."""
    n = int(x.shape[0])
    rows = max(_SUBLANES, (n + _BLOCK - 1) // _BLOCK * _SUBLANES)
    pad = rows * _LANES - n
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
    return x.reshape(rows, _LANES)


def _tile_spec():
    return pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0))


def _scalar_spec():
    return pl.BlockSpec((1, 1), lambda i: (0, 0))


def _scalar(v):
    return jnp.asarray(v, jnp.float32).reshape(1, 1)


def _momentum_kernel(nesterov, p_ref, g_ref, v_ref, lr_ref, mu_ref,
                     po_ref, vo_ref):
    p, g, v = p_ref[...], g_ref[...], v_ref[...]
    lr, mu = lr_ref[0, 0], mu_ref[0, 0]
    v_out = mu * v + g
    if nesterov:
        po_ref[...] = p - (g + mu * v_out) * lr
    else:
        po_ref[...] = p - lr * v_out
    vo_ref[...] = v_out


def momentum_bucket(p, g, v, lr, mu, nesterov):
    """Fused momentum over one flat f32 bucket. p/g/v: [n] f32; lr: f32
    scalar; mu: python float; nesterov: static bool. Returns
    (param_out[n], velocity_out[n])."""
    n = int(p.shape[0])
    p2, g2, v2 = _pad2d(p), _pad2d(g), _pad2d(v)
    rows = int(p2.shape[0])
    po, vo = pl.pallas_call(
        functools.partial(_momentum_kernel, bool(nesterov)),
        grid=(rows // _SUBLANES,),
        in_specs=[_tile_spec(), _tile_spec(), _tile_spec(),
                  _scalar_spec(), _scalar_spec()],
        out_specs=[_tile_spec(), _tile_spec()],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=jax.devices()[0].platform != "tpu",
    )(p2, g2, v2, _scalar(lr), _scalar(mu))
    return po.reshape(-1)[:n], vo.reshape(-1)[:n]


def _adam_kernel(p_ref, g_ref, m1_ref, m2_ref, lrt_ref, b1_ref, omb1_ref,
                 b2_ref, omb2_ref, eps_ref, po_ref, m1o_ref, m2o_ref):
    p, g = p_ref[...], g_ref[...]
    m1, m2 = m1_ref[...], m2_ref[...]
    lr_t, eps = lrt_ref[0, 0], eps_ref[0, 0]
    b1, omb1 = b1_ref[0, 0], omb1_ref[0, 0]
    b2, omb2 = b2_ref[0, 0], omb2_ref[0, 0]
    m1o = b1 * m1 + omb1 * g
    m2o = b2 * m2 + omb2 * jnp.square(g)
    m1o_ref[...] = m1o
    m2o_ref[...] = m2o
    po_ref[...] = p - lr_t * m1o / (jnp.sqrt(m2o) + eps)


def adam_bucket(p, g, m1, m2, lr_t, b1, b2, eps):
    """Fused adam over one flat f32 bucket. p/g/m1/m2: [n] f32; lr_t: f32
    scalar (bias-corrected step size, computed by the caller with the
    scalar op's exact expression); b1/b2/eps: python floats. (1 - b1) and
    (1 - b2) are evaluated in python doubles here — exactly where the
    scalar kernel evaluates them — and only then rounded to f32, so the
    coefficients match the unfused op to the bit. Returns
    (param_out[n], m1_out[n], m2_out[n])."""
    n = int(p.shape[0])
    p2, g2, m12, m22 = _pad2d(p), _pad2d(g), _pad2d(m1), _pad2d(m2)
    rows = int(p2.shape[0])
    po, m1o, m2o = pl.pallas_call(
        _adam_kernel,
        grid=(rows // _SUBLANES,),
        in_specs=[_tile_spec(), _tile_spec(), _tile_spec(), _tile_spec(),
                  _scalar_spec(), _scalar_spec(), _scalar_spec(),
                  _scalar_spec(), _scalar_spec(), _scalar_spec()],
        out_specs=[_tile_spec(), _tile_spec(), _tile_spec()],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=jax.devices()[0].platform != "tpu",
    )(p2, g2, m12, m22, _scalar(lr_t), _scalar(b1), _scalar(1 - b1),
      _scalar(b2), _scalar(1 - b2), _scalar(eps))
    return po.reshape(-1)[:n], m1o.reshape(-1)[:n], m2o.reshape(-1)[:n]
