"""Cost-guided operator fusion over the Program dataflow graph.

Reference parity: the reference rewrites its SSAGraph with
framework/ir/fuse_elewise_add_act_pass (vertical: collapse elementwise
chains) and framework/details/fuse_optimizer_op_pass + its
fuse_{adam,momentum,sgd}_op_pass subclasses (horizontal: one update
kernel over contiguous gradient/parameter buffers, cf.
alloc_continuous_space_op). This package is the TPU-native equivalent,
applied on both executors' compile-miss paths behind FLAGS_fuse and
composed with the other IR rewrites (zero1 -> overlap -> FUSION ->
autoshard); the fusion digest folds into the compile-cache key.

Two pass families:

* VERTICAL — maximal single-consumer chains of elementwise ops
  (activations / scale / cast) collapse into one `fused_elementwise` op
  whose kernel replays the recorded sub-op chain through the real
  registered kernels (ops/fused_ops.py), so amp policy and dtype casts
  apply per sub-op exactly as unfused. A chain only fuses when the cost
  model says the saved HBM round-trips plus kernel-launch floors beat
  the minimum benefit: every eliminated intermediate op saves one
  write+read of its tensor at HBM bandwidth plus one launch floor.

* HORIZONTAL — all (param, grad, slot) triples of one optimizer family
  with one hyperparameter signature (same attrs, LR var, beta-pow vars,
  dtypes, shard layout) flatten into contiguous f32 buckets of at most
  FLAGS_fuse_bucket_mb, each updated by ONE `fused_<opt>_update` op.
  zero1-aware: shard-layout members ((parts, shard) tensors produced by
  parallel.zero1) bucket along the shard axis — `shard_rows` — keeping
  dim 0 pinned to the dp axis with no regather; the members' trailing
  zero1_gather ops move with the fused op (fused update first, then the
  gathers, at the LAST member's position, where every scatter has
  already run). Unpacking is exact, so checkpoints keep their canonical
  layout.

Safety: apply() refuses (ProgramVerificationError) when the SOURCE
program carries any PTA03x hazard, re-verifies the rewritten clone
before returning it, and every bucket passes an interleave check (no
foreign op between the members reads/writes a name the rewrite moves
across it). Loss parity vs. the unfused program is bitwise — gated in
tools/green_gate.sh and tests/test_fusion.py.
"""

import hashlib

import numpy as np

from .. import flags
from ..analysis.dataflow import check_hazards, DATAFLOW_CODES
from ..analysis.diagnostics import ProgramVerificationError, Report

__all__ = ["FusionPlan", "apply", "ELEMENTWISE_OPS", "FUSABLE_OPT",
           "LAUNCH_FLOOR_S", "HBM_BYTES_PER_S", "MIN_BENEFIT_S"]

flags.define(
    "fuse", bool, False,
    "Apply cost-guided operator fusion (paddle_tpu.fusion) to the "
    "resolved program on the compile-miss path of both executors: "
    "vertical elementwise-chain fusion plus the horizontal fused "
    "bucketed weight update (one fused_<opt>_update kernel per "
    "FLAGS_fuse_bucket_mb bucket of same-family parameters). "
    "Bitwise-parity-preserving by construction; composes with zero1, "
    "overlap and autoshard. Distinct from the older trace-time "
    "FLAGS_fuse_optimizer_ops concat path.")
flags.define(
    "fuse_bucket_mb", int, 32,
    "Horizontal fusion bucket budget in MB of f32 parameter payload: "
    "one fused_<opt>_update op covers at most this much. Smaller "
    "buckets bound the concat working set; larger ones cut more "
    "per-parameter kernels.")
flags.define(
    "fuse_pallas", bool, True,
    "Dispatch all-f32 fused adam/momentum buckets (no ambient mesh) to "
    "the Pallas TPU kernel in paddle_tpu.fusion.kernels — one "
    "(8,128)-blocked VMEM pass per bucket. Interpret mode keeps CPU "
    "semantics identical; 0 falls back to the packed jnp expression.")

# cost model: an eliminated intermediate op saves ~one kernel-launch
# floor plus one HBM write+read of its tensor. Like analysis.schedule's
# chip constants these are parameters of a *relative* instrument — the
# same floor applies to every candidate, so the fuse/skip decision is
# robust to the absolute scale being off.
LAUNCH_FLOOR_S = 2e-6
HBM_BYTES_PER_S = 8.2e11
MIN_BENEFIT_S = 4e-6

# unary X -> Out elementwise ops legal inside a fused_elementwise chain
# (ops/activation_ops.py's _act family + scale + cast)
ELEMENTWISE_OPS = frozenset({
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink",
    "softshrink", "hard_shrink", "sqrt", "abs", "ceil", "floor", "round",
    "cos", "sin", "reciprocal", "log", "square", "softplus", "softsign",
    "brelu", "leaky_relu", "soft_relu", "elu", "relu6", "pow", "stanh",
    "hard_sigmoid", "thresholded_relu", "swish", "gelu",
    "scale", "cast",
})

# optimizer families the horizontal pass buckets: accumulator slot pairs
# (in-place contract: input name == output name) and extra scalar inputs
# shared bucket-wide (adam's global beta-pow accumulators)
FUSABLE_OPT = {
    "sgd": {"accums": (), "extra": ()},
    "momentum": {"accums": (("Velocity", "VelocityOut"),), "extra": ()},
    "adam": {"accums": (("Moment1", "Moment1Out"),
                        ("Moment2", "Moment2Out")),
             "extra": ("Beta1Pow", "Beta2Pow")},
}

_ZERO1_UPD = "@zero1_upd"


class FusionPlan:
    """What one apply() did: the fused chains and buckets, op-count
    deltas, and a digest for the executors' compile-cache keys."""

    def __init__(self, chains, buckets, skipped, n_ops_before, n_ops_after,
                 bucket_bytes):
        self.chains = list(chains)
        self.buckets = list(buckets)
        self.skipped = list(skipped)
        self.n_ops_before = int(n_ops_before)
        self.n_ops_after = int(n_ops_after)
        self.bucket_bytes = int(bucket_bytes)

    @property
    def n_fused(self):
        return len(self.chains) + len(self.buckets)

    def digest(self):
        h = hashlib.sha1()
        h.update(repr((
            [(c["types"], c["vars"]) for c in self.chains],
            [(b["opt"], b["params"], b["shard_rows"]) for b in self.buckets],
            self.bucket_bytes, self.n_ops_before, self.n_ops_after,
        )).encode())
        return h.hexdigest()[:16]

    def to_dict(self):
        return {
            "n_chains": len(self.chains),
            "n_buckets": len(self.buckets),
            "n_ops_before": self.n_ops_before,
            "n_ops_after": self.n_ops_after,
            "bucket_bytes": self.bucket_bytes,
            "chains": [dict(c) for c in self.chains],
            "buckets": [dict(b) for b in self.buckets],
            "skipped": list(self.skipped),
            "digest": self.digest(),
        }


def _require_hazard_free(program, feed_names, what):
    report = Report(level="full", context=f"fusion-{what}")
    check_hazards(program, report, feed_names=feed_names)
    if any(d.code in DATAFLOW_CODES for d in report.errors()):
        raise ProgramVerificationError(report)


def _nominal_numel(shape):
    """Static element count with -1 (dynamic batch) dims taken at a
    nominal 128 — the cost model needs a magnitude, not an exact count."""
    if not shape:
        return 0
    n = 1
    for d in shape:
        n *= 128 if d in (-1, None) else int(d)
    return n


def _chain_benefit_s(length, numel, itemsize):
    """Seconds saved by collapsing a `length`-op chain: each eliminated
    boundary saves one launch floor + one HBM write+read round-trip."""
    saved_bytes = (length - 1) * numel * itemsize * 2
    return (length - 1) * LAUNCH_FLOOR_S + saved_bytes / HBM_BYTES_PER_S


# ---------------------------------------------------------------------------
# vertical pass: elementwise chains
# ---------------------------------------------------------------------------
def _fuse_elementwise(clone, feed_names, fetch_names):
    from ..core.framework import Operator

    gb = clone.global_block()
    ops = gb.ops
    op_types = {op.type for b in clone.blocks for op in b.ops}
    pinned = set(feed_names) | set(fetch_names)

    def eligible(i):
        op = ops[i]
        if op.type not in ELEMENTWISE_OPS:
            return False
        if op.type + "_grad" in op_types:
            # consuming the last forward op of a type that still has
            # grad ops would break PTA007 type-level grad pairing (and
            # the chain's backward); vertical fusion targets inference
            return False
        ins = {s for s, n in op.inputs.items() if n}
        outs = {s for s, n in op.outputs.items() if n}
        return (ins == {"X"} and outs == {"Out"}
                and len(op.inputs["X"]) == 1 and len(op.outputs["Out"]) == 1)

    reads = {}
    for b in clone.blocks:
        for op in b.ops:
            for names in op.inputs.values():
                for nm in names:
                    reads[nm] = reads.get(nm, 0) + 1
    gb_reader = {}   # var -> unique global-block reader idx (if any)
    for i, op in enumerate(ops):
        for names in op.inputs.values():
            for nm in names:
                gb_reader[nm] = i if nm not in gb_reader else None
    produced = {}
    multi_prod = set()
    for b in clone.blocks:
        for op in b.ops:
            for names in op.outputs.values():
                for nm in names:
                    if nm in produced:
                        multi_prod.add(nm)
                    produced[nm] = True

    def fusable_edge(out_name):
        """Can the chain continue THROUGH out_name (kill it)?"""
        v = gb.vars.get(out_name)
        if v is None or getattr(v, "persistable", False) \
                or getattr(v, "is_data", False):
            return None
        if out_name in pinned or out_name in multi_prod:
            return None
        if reads.get(out_name, 0) != 1:
            return None
        return gb_reader.get(out_name)

    chains, used, dead_vars = [], set(), []
    for i in range(len(ops)):
        if i in used or not eligible(i):
            continue
        chain = [i]
        cur = i
        while True:
            nxt = fusable_edge(ops[cur].outputs["Out"][0])
            if nxt is None or nxt in used or nxt <= cur \
                    or not eligible(nxt):
                break
            chain.append(nxt)
            cur = nxt
        if len(chain) < 2:
            continue
        mid = gb.vars.get(ops[chain[0]].outputs["Out"][0])
        numel = _nominal_numel(getattr(mid, "shape", None))
        itemsize = np.dtype(getattr(mid, "dtype", "float32")).itemsize
        benefit = _chain_benefit_s(len(chain), numel, itemsize)
        if benefit < MIN_BENEFIT_S:
            continue
        used.update(chain)
        head, tail = ops[chain[0]], ops[chain[-1]]
        fused = Operator(
            gb, "fused_elementwise",
            {"X": [head.inputs["X"][0]]},
            {"Out": [tail.outputs["Out"][0]]},
            {"sub_types": [ops[j].type for j in chain],
             "sub_attrs": [{k: v for k, v in ops[j].attrs.items()
                            if not k.startswith("op_")} for j in chain],
             "op_role": head.attrs.get("op_role", 0)})
        dead_vars.extend(ops[j].outputs["Out"][0] for j in chain[:-1])
        chains.append({
            "op": fused, "first": chain[0], "drop": chain[1:],
            "types": [ops[j].type for j in chain],
            "vars": [head.inputs["X"][0], tail.outputs["Out"][0]],
            "benefit_us": round(benefit * 1e6, 3),
        })
    if chains:
        replace = {c["first"]: c["op"] for c in chains}
        drop = {j for c in chains for j in c["drop"]}
        gb.ops = [replace.get(i, op) for i, op in enumerate(ops)
                  if i not in drop]
        for nm in dead_vars:
            gb.vars.pop(nm, None)
    for c in chains:  # the Operator handle was only needed for the rewrite
        del c["op"], c["first"], c["drop"]
    return chains


# ---------------------------------------------------------------------------
# horizontal pass: fused bucketed weight update
# ---------------------------------------------------------------------------
def _member_of(gb, i, op, fam):
    """Member descriptor for optimizer op `op`, or None if ineligible."""
    from ..core.framework import VarType

    def one(slots, name):
        v = slots.get(name) or []
        return v[0] if len(v) == 1 and v[0] else None

    pname, gname = one(op.inputs, "Param"), one(op.inputs, "Grad")
    lr, pout = one(op.inputs, "LearningRate"), one(op.outputs, "ParamOut")
    if not (pname and gname and lr and pout):
        return None
    pvar, gvar = gb.vars.get(pname), gb.vars.get(gname)
    if pvar is None or pvar.shape is None or any(
            d is None or d < 0 for d in pvar.shape or ()):
        return None
    if getattr(pvar, "type", None) == VarType.SELECTED_ROWS:
        return None
    if gvar is not None and (getattr(gvar, "type", None)
                             == VarType.SELECTED_ROWS
                             or getattr(gvar, "lod_level", 0)):
        return None
    sharded = pout.endswith(_ZERO1_UPD)
    if sharded:
        if len(pvar.shape) != 2:
            return None
        rows = int(pvar.shape[0])
    else:
        if pout != pname:  # not the in-place update wiring we replay
            return None
        rows = 0
    accums = []
    for in_slot, out_slot in fam["accums"]:
        a_in = one(op.inputs, in_slot)
        a_out = one(op.outputs, out_slot)
        avar = gb.vars.get(a_in) if a_in else None
        if not a_in or a_in != a_out or avar is None \
                or tuple(avar.shape or ()) != tuple(pvar.shape):
            return None
        accums.append((in_slot, a_in, str(avar.dtype)))
    extra = []
    for slot in fam["extra"]:
        nm = one(op.inputs, slot)
        if not nm:
            return None
        extra.append((slot, nm))
    sig = tuple(sorted((k, repr(v)) for k, v in op.attrs.items()
                       if not k.startswith("op_")))
    key = (op.type, sig, lr, tuple(nm for _, nm in extra),
           str(pvar.dtype), tuple(dt for _, _, dt in accums), rows)
    return {
        "idx": i, "op": op, "key": key, "pname": pname, "pout": pout,
        "gname": gname, "lr": lr, "accums": accums, "extra": extra,
        "rows": rows, "numel": int(np.prod(pvar.shape)),
        "base": pout[:-len(_ZERO1_UPD)] if sharded else pname,
    }


def _find_gather(ops, m):
    """Index of the zero1_gather consuming this member's @zero1_upd."""
    for k in range(m["idx"] + 1, len(ops)):
        op = ops[k]
        if op.type == "zero1_gather" \
                and (op.inputs.get("X") or [None])[0] == m["pout"]:
            return k
    return None


def _interleave_safe(ops, members, gather_idxs):
    """No foreign op between the bucket's members may interact with a
    name the rewrite moves across it: the fused update runs at the LAST
    member's position and the gathers move right behind it."""
    member_idxs = [m["idx"] for m in members]
    last = max(member_idxs)
    span_end = max(gather_idxs) if gather_idxs else last
    moved = set(member_idxs) | set(gather_idxs)
    in_pos = {}
    for m in members:
        for nm in ([m["pname"], m["gname"], m["lr"]]
                   + [nm for _, nm, _ in m["accums"]]
                   + [nm for _, nm in m["extra"]]):
            in_pos[nm] = min(in_pos.get(nm, m["idx"]), m["idx"])
    pouts = {m["pout"] for m in members}
    gather_outs = {(ops[k].outputs.get("Out") or [None])[0]
                   for k in gather_idxs}
    for k in range(min(member_idxs), span_end + 1):
        if k in moved:
            continue
        op = ops[k]
        w = {nm for names in op.outputs.values() for nm in names}
        r = {nm for names in op.inputs.values() for nm in names}
        if k < last:
            # writes to a member input would now be seen by the fused op
            if any(in_pos.get(nm, k + 1) < k for nm in w):
                return False
            # the member outputs don't exist yet at this position
            if (r | w) & pouts:
                return False
        else:
            # the moved gathers now run BEFORE this op
            if (r | w) & gather_outs:
                return False
    return True


def _fuse_optimizers(clone, bucket_bytes):
    from ..core.framework import Operator

    gb = clone.global_block()
    ops = gb.ops
    groups, seen, skipped = {}, set(), []
    for i, op in enumerate(ops):
        fam = FUSABLE_OPT.get(op.type)
        if fam is None:
            continue
        m = _member_of(gb, i, op, fam)
        if m is None:
            skipped.append(((op.inputs.get("Param") or ["?"])[0],
                            "wiring outside the fusable contract"))
            continue
        if m["pname"] in seen or m["pout"] in seen:
            skipped.append((m["base"], "param updated more than once"))
            continue
        seen.update((m["pname"], m["pout"]))
        groups.setdefault(m["key"], []).append(m)

    inserts, drops, buckets = {}, set(), []
    for key, members in groups.items():
        opt_type, rows = key[0], key[-1]
        fam = FUSABLE_OPT[opt_type]
        # split into buckets by cumulative f32 payload, in program order
        cur, size = [], 0
        parts = []
        for m in members:
            if cur and size + m["numel"] * 4 > bucket_bytes:
                parts.append(cur)
                cur, size = [], 0
            cur.append(m)
            size += m["numel"] * 4
        if cur:
            parts.append(cur)
        for bucket in parts:
            if len(bucket) < 2:
                continue
            gather_idxs = []
            if rows:
                gs = [_find_gather(ops, m) for m in bucket]
                if any(g is None for g in gs):
                    skipped.append((bucket[0]["base"],
                                    "zero1 member without its gather"))
                    continue
                gather_idxs = gs
            if not _interleave_safe(ops, bucket, gather_idxs):
                skipped.append((bucket[0]["base"],
                                "unsafe op interleave inside the bucket"))
                continue
            first = bucket[0]["op"]
            ins = {"Param": [m["pname"] for m in bucket],
                   "Grad": [m["gname"] for m in bucket],
                   "LearningRate": [bucket[0]["lr"]]}
            outs = {"ParamOut": [m["pout"] for m in bucket]}
            for s_i, (in_slot, out_slot) in enumerate(fam["accums"]):
                ins[in_slot] = [m["accums"][s_i][1] for m in bucket]
                outs[out_slot] = [m["accums"][s_i][1] for m in bucket]
            for s_i, slot in enumerate(fam["extra"]):
                ins[slot] = [bucket[0]["extra"][s_i][1]]
            attrs = {k: v for k, v in first.attrs.items()
                     if not k.startswith("op_")}
            attrs["shard_rows"] = int(rows)
            attrs["op_role"] = first.attrs.get("op_role", 0)
            role_vars = []
            for m in bucket:
                role_vars.extend(m["op"].attrs.get("op_role_var", []))
            if role_vars:
                attrs["op_role_var"] = role_vars
            fused = Operator(gb, f"fused_{opt_type}_update",
                             ins, outs, attrs)
            last = max(m["idx"] for m in bucket)
            inserts[last] = [fused] + [ops[k] for k in sorted(gather_idxs)]
            drops.update(m["idx"] for m in bucket)
            drops.update(gather_idxs)
            buckets.append({
                "opt": opt_type, "n": len(bucket),
                "params": [m["base"] for m in bucket],
                "numel": sum(m["numel"] for m in bucket),
                "bytes": sum(m["numel"] for m in bucket) * 4,
                "shard_rows": int(rows),
            })
    if inserts:
        new_ops = []
        for i, op in enumerate(ops):
            if i in inserts:
                new_ops.extend(inserts[i])
            if i not in drops:
                new_ops.append(op)
        gb.ops = new_ops
    return buckets, skipped


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------
def apply(program, feed_names=None, fetch_names=None, bucket_bytes=None):
    """Fuse `program` on a clone. Returns (program, None) when nothing
    fuses, else (fused_clone, FusionPlan). Refuses hazardous source
    programs and re-verifies the rewritten clone — a rewrite that
    introduces any PTA03x hazard raises instead of shipping."""
    if bucket_bytes is None:
        bucket_bytes = flags.get("fuse_bucket_mb") << 20
    feed_names = list(feed_names or [])
    _require_hazard_free(program, feed_names, "source")
    clone = program.clone()
    n_before = len(clone.global_block().ops)
    chains = _fuse_elementwise(clone, feed_names, list(fetch_names or []))
    buckets, skipped = _fuse_optimizers(clone, int(bucket_bytes))
    if not chains and not buckets:
        return program, None
    clone._mutation += 1
    plan = FusionPlan(chains, buckets, skipped, n_before,
                      len(clone.global_block().ops), bucket_bytes)
    _require_hazard_free(clone, feed_names, "fused")
    return clone, plan
