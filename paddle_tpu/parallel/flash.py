"""Flash attention as a Pallas TPU kernel.

The hot-op kernel path the reference implements with cuDNN/hand-written
CUDA: exact attention computed block-by-block in VMEM with the streaming
softmax (running max + normalizer), never materializing the [S, S] score
matrix in HBM. Complements parallel/ring.py: ring attention shards the
sequence ACROSS chips and streams K/V around the ICI ring; flash_attention
is the WITHIN-chip kernel.

Layout [B, H, S, D]. The kernel runs a (batch*heads, q-blocks, k-blocks)
grid with the k dimension innermost ("arbitrary" semantics — sequential
per core) carrying the running (m, l, acc) in VMEM scratch. The backward
pass is a blockwise lax.scan in plain JAX using the saved logsumexp —
O(S * block) live memory — wired through jax.custom_vjp.

Off-TPU (CPU tests) the kernel runs in Pallas interpret mode.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
            scale, causal, block_q, block_k, nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _accumulate():
        q = q_ref[0]                   # [bq, D]
        k = k_ref[0]                   # [bk, D]
        v = v_ref[0]                   # [bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)

        m_prev = m_scr[:]              # [bq]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)  # masked rows
        p = jnp.where(jnp.isneginf(s), 0.0, jnp.exp(s - m_safe[:, None]))
        corr = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_safe))
        l_scr[:] = corr * l_scr[:] + jnp.sum(p, axis=1)
        acc_scr[:] = acc_scr[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    if causal:
        # skip k-blocks entirely above the causal frontier (half the grid)
        pl.when(qi * block_q + block_q - 1 >= ki * block_k)(_accumulate)
    else:
        _accumulate()

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)
        lse = jnp.where(
            jnp.isneginf(m_scr[:]), -jnp.inf, m_scr[:] + jnp.log(l))
        # lse rides in an [8, block_q] tile: Mosaic requires the last two
        # block dims to be (8, 128)-aligned, so broadcast over 8 sublanes
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    """q [BH, Sq, D] (Sq % block_q == 0), k/v [BH, Sk, D] (Sk % block_k
    == 0) -> (out [BH, Sq, D], lse [BH, Sq])."""
    BH, Sq, Dq = q.shape  # Dq may carry the +1 padding-mask channel
    Sk = k.shape[1]
    Dv = v.shape[-1]
    nq, nk = Sq // block_q, Sk // block_k
    kernel = functools.partial(
        _kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, Dq), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, Dq), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, Dv), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, Dv), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, Dv), q.dtype),
            jax.ShapeDtypeStruct((BH, 8, Sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, Dv), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        # device platform, not backend name: the tunneled TPU platform
        # registers as backend "axon" with devices of platform "tpu"
        interpret=jax.devices()[0].platform != "tpu",
    )(q, k, v)


def _fwd_padded(q, k, v, scale, causal, block_q, block_k):
    """Pad S to block multiples; padded KEYS are neutralized by extending D
    with a bias channel (q gains a 1, real keys a 0, padded keys -BIG), so
    their scores vanish under exp without any in-kernel mask plumbing."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    pad_q = (-Sq) % block_q
    pad_k = (-Sk) % block_k
    qw, kw, vw = q, k, v
    if pad_q:
        qw = jnp.pad(qw, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kw = jnp.pad(kw, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vw = jnp.pad(vw, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        BIG = jnp.asarray(3e4 / max(scale, 1e-6), jnp.float32).astype(q.dtype)
        qw = jnp.concatenate([qw, jnp.ones_like(qw[..., :1])], axis=-1)
        maskch = jnp.where(
            (jnp.arange(kw.shape[2]) < Sk)[None, None, :, None],
            jnp.zeros((), q.dtype), -BIG)
        kw = jnp.concatenate(
            [kw, jnp.broadcast_to(maskch, kw.shape[:3] + (1,))], axis=-1)
    BH = B * H
    Dk = qw.shape[-1]
    out, lse = _flash_fwd(
        qw.reshape(BH, Sq + pad_q, Dk), kw.reshape(BH, Sk + pad_k, Dk),
        vw.reshape(BH, Sk + pad_k, D), scale, causal, block_q, block_k)
    out = out.reshape(B, H, Sq + pad_q, D)[:, :, :Sq]
    lse = lse[:, 0, :].reshape(B, H, Sq + pad_q)[:, :, :Sq]
    return out, lse


def normalize_blocks(block_q, block_k, Sq, Sk):
    """Mosaic block-alignment rule: every block dim must be (8, 128)-aligned
    in its (sublane, lane) position OR equal to the (padded) array dim. So a
    block is legal when it is a multiple of 128 (the lse tile's lane dim) or
    when it covers the whole padded sequence (n=1). Auto-shrink short
    sequences to a single 8-rounded block; round user blocks up to 128 when
    compiling for real TPU (interpret mode has no constraint). Callers that
    reach _fwd_padded directly (ring_flash_attention) must use this too."""
    on_tpu = jax.devices()[0].platform == "tpu"

    def _pick(block, S):
        S8 = -(-max(S, 1) // 8) * 8
        block = int(block)
        if on_tpu and block % 128:
            block = -(-block // 128) * 128
        return S8 if block >= S8 else block

    return _pick(block_q, Sq), _pick(block_k, Sk)


def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=256, block_k=256):
    """Exact attention [B, H, S, D] -> [B, H, S, D]; differentiable.

    Defaults (256, 256) measured fastest on a v5e chip at S=1024 D=128 —
    faster than XLA's fused dense attention there, with O(S * block) memory
    instead of the dense [S, S] score matrix (S >= 16k runs comfortably).
    Blocks auto-shrink for short sequences."""
    block_q, block_k = normalize_blocks(block_q, block_k,
                                        q.shape[2], k.shape[2])
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash(q, k, v, float(scale), bool(causal),
                  int(block_q), int(block_k))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    out, _ = _fwd_padded(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _fwd_padded(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, out, lse = res
    B, H, S, D = q.shape
    Sk = k.shape[2]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), -1)

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)

    nk = (Sk + block_k - 1) // block_k
    pad = nk * block_k - Sk
    if pad:
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = jnp.moveaxis(kf.reshape(B, H, nk, block_k, D), 2, 0)
    vb = jnp.moveaxis(vf.reshape(B, H, nk, block_k, D), 2, 0)

    def body(dq, blk):
        kblk, vblk, j = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kblk) * scale
        pos = j * block_k + jnp.arange(block_k)
        valid = pos < Sk
        if causal:
            mask = valid[None, :] & (pos[None, :] <= jnp.arange(S)[:, None])
        else:
            mask = jnp.broadcast_to(valid[None, :], (S, block_k))
        p = jnp.where(mask[None, None], jnp.exp(s - lse[..., None]), 0.0)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vblk)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kblk)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq, (dk, dv)

    dq, (dks, dvs) = jax.lax.scan(
        body, jnp.zeros_like(qf), (kb, vb, jnp.arange(nk)))
    dk = jnp.moveaxis(dks, 0, 2).reshape(B, H, nk * block_k, D)[:, :, :Sk]
    dv = jnp.moveaxis(dvs, 0, 2).reshape(B, H, nk * block_k, D)[:, :, :Sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)
