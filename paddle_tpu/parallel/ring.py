"""Ring attention: exact attention over sequences sharded across the mesh.

The reference has NO sequence parallelism (SURVEY.md §5 long-context: LoD
bucketing only); this is the TPU build's first-class long-context capability.

Algorithm (blockwise-stable ring): each device holds one sequence shard of
Q, K, V. K/V blocks rotate around the ring via lax.ppermute; each hop every
device accumulates its Q-block's attention against the visiting K/V block
with the numerically-stable streaming-softmax update (running max m and
normalizer l), so the result is EXACT full attention with O(S/n) memory per
chip and compute/communication overlapped hop by hop over ICI.

Usage: inside shard_map over a mesh with a sequence axis, or via
ring_attention() which wraps the shard_map. Causal masking uses global
position offsets per shard.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention_sharded", "ring_attention",
           "ring_flash_attention_sharded", "ring_flash_attention"]


def _block_attn(q, k, v, bias, m_prev, l_prev, o_prev, scale):
    """One streaming-softmax accumulation step.
    q: [B,H,Sq,D]; k,v: [B,H,Sk,D]; bias: [B,H,Sq,Sk] additive (-inf mask).
    Returns updated (m, l, o)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    m_cur = jnp.max(s, axis=-1)                        # [B,H,Sq]
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (all -inf)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])                 # [B,H,Sq,Sk]
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l_cur = jnp.sum(p, axis=-1)                        # [B,H,Sq]
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
    l_new = alpha * l_prev + l_cur
    o_new = alpha[..., None] * o_prev + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(p.dtype),
        preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def ring_attention_sharded(q, k, v, axis_name, causal=False, scale=None):
    """Per-shard body: call inside shard_map/pmap over `axis_name`.

    q,k,v: [B, H, S_local, D] — this device's sequence shard.
    Returns [B, H, S_local, D] exact attention output."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(D)

    qf = q.astype(jnp.float32)
    m = jnp.full((B, H, S), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, S), jnp.float32)
    o = jnp.zeros((B, H, S, D), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def accumulate(hop_idx, k_blk, v_blk, m, l, o):
        # global block index the visiting K/V block came from
        src = (idx - hop_idx) % n
        if causal:
            q_pos = idx * S + jnp.arange(S)            # [S]
            k_pos = src * S + jnp.arange(S)            # [S]
            mask = q_pos[:, None] >= k_pos[None, :]    # [S,S]
            bias = jnp.where(mask, 0.0, -jnp.inf)[None, None]
        else:
            bias = None
        return _block_attn(qf, k_blk.astype(jnp.float32),
                           v_blk.astype(jnp.float32), bias, m, l, o, scale)

    def hop(carry, hop_idx):
        k_blk, v_blk, m, l, o = carry
        m, l, o = accumulate(hop_idx, k_blk, v_blk, m, l, o)
        # rotate K/V to the next device (overlaps with next hop's compute)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, l, o), None

    if n > 1:
        # scan the first n-1 hops (each ends in a rotation); the final hop
        # accumulates only — no wasted trailing K/V rotation over ICI
        (k_blk, v_blk, m, l, o), _ = lax.scan(
            hop, (k, v, m, l, o), jnp.arange(n - 1))
        m, l, o = accumulate(n - 1, k_blk, v_blk, m, l, o)
    else:
        m, l, o = accumulate(0, k, v, m, l, o)
    l_safe = jnp.maximum(l, 1e-20)
    return (o / l_safe[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False, scale=None):
    """Full-tensor entry: q,k,v [B,H,S,D] sharded (or shardable) on S over
    mesh axis `axis_name`. Returns attention output with the same sharding.
    """
    spec = P(None, None, axis_name, None)
    fn = jax.shard_map(
        functools.partial(ring_attention_sharded, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ring_flash_attention_sharded(q, k, v, axis_name, causal=False,
                                 scale=None, block_q=256, block_k=256):
    """Ring attention with the Pallas flash kernel as the per-hop block
    compute: K/V shards rotate over ICI while each hop's local attention
    runs block-streaming in VMEM, so neither the global [S, S] scores nor a
    per-hop [S_local, S_local] matrix ever exists in HBM. Exact (per-hop
    (out, lse) pairs merge in log space).

    Forward/serving path: the flash kernel's custom VJP does not propagate
    through the log-space hop merge, so for training use ring_attention
    (pure-jnp streaming, fully differentiable). Call inside shard_map over
    `axis_name`; q,k,v: [B, H, S_local, D].
    """
    from .flash import _fwd_padded

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    scale = float(scale) if scale is not None else 1.0 / np.sqrt(D)

    def hop_flash(k_blk, v_blk, case):
        # case 0: fully visible hop (full attention)
        # case 1: diagonal hop (causal within the shard)
        # case 2: fully masked hop (skip)
        def full(_):
            return _fwd_padded(q, k_blk, v_blk, scale, False,
                               block_q, block_k)

        def diag(_):
            return _fwd_padded(q, k_blk, v_blk, scale, True,
                               block_q, block_k)

        def skip(_):
            return (jnp.zeros((B, H, S, D), q.dtype),
                    jnp.full((B, H, S), -jnp.inf, jnp.float32))

        if causal:
            return lax.switch(case, [full, diag, skip], 0)
        return full(0)

    def merge(o_p, lse_p, o_h, lse_h):
        lse_new = jnp.logaddexp(lse_p, lse_h)
        safe = jnp.where(jnp.isneginf(lse_new), 0.0, lse_new)
        w_p = jnp.where(jnp.isneginf(lse_p), 0.0, jnp.exp(lse_p - safe))
        w_h = jnp.where(jnp.isneginf(lse_h), 0.0, jnp.exp(lse_h - safe))
        o_new = w_p[..., None] * o_p.astype(jnp.float32) \
            + w_h[..., None] * o_h.astype(jnp.float32)
        return o_new, lse_new

    perm = [(i, (i + 1) % n) for i in range(n)]
    o = jnp.zeros((B, H, S, D), jnp.float32)
    lse = jnp.full((B, H, S), -jnp.inf, jnp.float32)

    def hop(carry, hop_idx):
        k_blk, v_blk, o, lse = carry
        src = (idx - hop_idx) % n
        case = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
        o_h, lse_h = hop_flash(k_blk, v_blk, case)
        o, lse = merge(o, lse, o_h, lse_h)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, o, lse), None

    if n > 1:
        (k_blk, v_blk, o, lse), _ = lax.scan(
            hop, (k, v, o, lse), jnp.arange(n - 1))
        src = (idx - (n - 1)) % n
        case = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
        o_h, lse_h = hop_flash(k_blk, v_blk, case)
        o, lse = merge(o, lse, o_h, lse_h)
    else:
        o_h, lse_h = hop_flash(k, v, jnp.asarray(1 if causal else 0))
        o, lse = merge(o, lse, o_h, lse_h)
    return o.astype(q.dtype)


def ring_flash_attention(q, k, v, mesh, axis_name="sp", causal=False,
                         scale=None, block_q=256, block_k=256):
    """Full-tensor entry for ring_flash_attention_sharded (see its
    docstring; forward/serving path)."""
    from .flash import normalize_blocks

    # normalize against the PER-SHARD sequence length (what each hop's
    # kernel actually sees), keeping Mosaic alignment + auto-shrink
    s_local = q.shape[2] // mesh.shape[axis_name]
    block_q, block_k = normalize_blocks(block_q, block_k, s_local, s_local)
    spec = P(None, None, axis_name, None)
    fn = jax.shard_map(
        functools.partial(ring_flash_attention_sharded, axis_name=axis_name,
                          causal=causal, scale=scale, block_q=block_q,
                          block_k=block_k),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
