"""ZeRO-1 cross-replica sharded weight update for the dp mesh.

Reference: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv 2004.13336). Plain data parallelism
all-reduces every gradient and then redundantly runs the identical weight
update on every replica, holding N full copies of the optimizer state.
ZeRO-1 splits the update: each gradient is reduce-scattered over the dp
axis, each replica updates only its 1/N shard of the parameter (with
shard-sized optimizer accumulators), and the updated shards are
all-gathered back into the replicated parameter.

This module is a PROGRAM-REWRITE pass. ParallelExecutor traces the whole
Program into one pjit'd step over the mesh, so the collectives are not
emitted explicitly — instead each optimizer op is rewritten to run in a
shard layout:

    grad  -> zero1_scatter -> [N, shard]   (reduce-scatter; scale folded)
    param -> zero1_scatter -> [N, shard]   (local slice of the replicated
                                            param — no communication)
    opt_op(param_shard, grad_shard, accum_shard, ...) -> param_shard_out
    param_shard_out -> zero1_gather -> param (all-gather, full shape)

The accumulators named in optimizer.ZERO1_SHARDABLE_SLOTS permanently live
in the shard layout [N, ceil(numel/N)] with dim 0 sharded over dp — that is
the N-times optimizer-state memory cut. Padding lanes are zero and stay
zero (the supported update rules are elementwise and inert on zero input).

Checkpoint contract: resilience.CheckpointManager.save converts
shard-layout accumulators back to the canonical FULL layout (an exact
pad/unpad round trip, bitwise stable), so a checkpoint written at dp=N
restores onto any dp size — including FLAGS_zero1=0 — without conversion
tooling. The manifest records the shard layout under "zero1".
"""

import numpy as np

from .. import flags
from ..core.framework import VarType
from ..optimizer import ZERO1_SHARDABLE_SLOTS

__all__ = ["Zero1Plan", "build_plan", "apply", "apply_grad_scale",
           "to_shard_layout", "from_shard_layout", "registered_entry",
           "canonicalize_snapshot", "ensure_scope_unsharded",
           "reset_registry"]

flags.define(
    "zero1", bool, False,
    "ZeRO-1 sharded weight update on the ParallelExecutor dp mesh "
    "(BuildStrategy.sharded_weight_update): reduce-scatter gradients, "
    "update a 1/N parameter shard per replica with shard-sized optimizer "
    "accumulators, all-gather the updated shards. Cuts optimizer-state "
    "memory ~Nx at dp=N and halves gradient collective bytes.")

DP_AXIS = "dp"


# ---------------------------------------------------------------------------
# layout conversion (the single definition of the shard layout)
# ---------------------------------------------------------------------------
def to_shard_layout(arr, parts):
    """Full-layout host array -> [parts, shard] zero-padded shard layout.
    Exact inverse of from_shard_layout for any input (pure pad/reshape)."""
    arr = np.asarray(arr)
    flat = arr.reshape(-1)
    pad = (-flat.shape[0]) % parts
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, dtype=arr.dtype)])
    return flat.reshape(parts, -1)


def from_shard_layout(arr, numel, shape):
    """[parts, shard] shard layout -> original full layout (drops pad)."""
    arr = np.asarray(arr)
    return arr.reshape(-1)[:numel].reshape(shape)


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------
class _Entry:
    """One optimizer op's shard layout."""

    __slots__ = ("param", "grad", "op_type", "shape", "numel", "padded",
                 "shard", "dtype", "accums")

    def __init__(self, param, grad, op_type, shape, numel, parts, dtype,
                 accums):
        self.param = param
        self.grad = grad
        self.op_type = op_type
        self.shape = tuple(shape)
        self.numel = int(numel)
        self.padded = -(-self.numel // parts) * parts
        self.shard = self.padded // parts
        self.dtype = dtype
        # [(in_slot, out_slot, var_name, dtype)]
        self.accums = accums

    def describe(self, parts):
        itemsize = np.dtype(self.dtype).itemsize
        acc_itemsize = sum(np.dtype(d).itemsize for _, _, _, d in
                           self.accums) or 0
        return {
            "shape": list(self.shape),
            "numel": self.numel,
            "padded_numel": self.padded,
            "num_shards": parts,
            "shard_numel": self.shard,
            "param_shard_bytes": self.shard * itemsize,
            "accum_shard_bytes": self.shard * acc_itemsize,
            "accums": [name for _, _, name, _ in self.accums],
            # shard i of the flattened (padded) param is owned by dp rank i
            "owners": {str(i): [i * self.shard, (i + 1) * self.shard]
                       for i in range(parts)},
        }


class Zero1Plan:
    """Shard layout + byte accounting for a program's optimizer ops.

    Built for BOTH paths: the all-reduce path uses it only for the
    collective/optimizer-state byte gauges; the zero1 path also drives the
    rewrite and the scope layout conversion."""

    def __init__(self, parts, axis=DP_AXIS):
        self.parts = int(parts)
        self.axis = axis
        self.entries = []          # [_Entry]
        self.skipped = []          # [(param, reason)] — not sharded
        self._by_accum = {}        # accum var name -> _Entry

    # -- accounting ---------------------------------------------------------
    def optimizer_state_bytes(self, sharded):
        """Per-replica bytes of the plan's param-shaped accumulators."""
        total = 0
        for e in self.entries:
            for _, _, _, dtype in e.accums:
                item = np.dtype(dtype).itemsize
                total += (e.shard if sharded else e.numel) * item
        return total

    def collective_bytes(self, sharded):
        """Analytic per-replica per-step collective bytes on a ring of N
        replicas: all_reduce = 2(N-1)/N * B, reduce_scatter = all_gather =
        (N-1)/N * B. Returns {op: bytes} for the path in effect."""
        n = self.parts
        if n < 2:
            return {}
        grad_b = sum(e.padded * np.dtype(e.dtype).itemsize
                     for e in self.entries)
        if not sharded:
            return {"all_reduce": int(2 * (n - 1) / n * grad_b)}
        param_b = grad_b  # regathered params have the padded grad footprint
        return {
            "reduce_scatter": int((n - 1) / n * grad_b),
            "all_gather": int((n - 1) / n * param_b),
        }

    def describe(self):
        """Manifest / CLI rendering: param -> shard layout."""
        return {e.param: e.describe(self.parts) for e in self.entries}

    # -- scope layout -------------------------------------------------------
    def ensure_scope_sharded(self, scope):
        """Convert any full-layout accumulator value in `scope` to the
        shard layout (startup programs and checkpoint restores always leave
        the canonical full layout). No-op for values already converted."""
        for e in self.entries:
            for _, _, name, _ in e.accums:
                v = scope.find_var(name)
                if v is None or not hasattr(v, "shape"):
                    continue
                if tuple(v.shape) == (self.parts, e.shard):
                    continue
                if int(np.prod(v.shape or (1,))) != e.numel:
                    continue  # stale var from another program; leave it
                scope.set_var(name, to_shard_layout(_host(v), self.parts))


def _host(v):
    """Scope value -> host numpy (LoDTensor or jax array)."""
    if hasattr(v, "numpy") and not hasattr(v, "sharding"):
        v = v.numpy()
    return np.asarray(v)


# ---------------------------------------------------------------------------
# plan construction
# ---------------------------------------------------------------------------
def build_plan(program, parts, axis=DP_AXIS):
    """Scan a program's optimizer ops into a Zero1Plan. Pure analysis — no
    rewrite. Unsupported updates land in plan.skipped with a reason and
    stay on the replicated path."""
    plan = Zero1Plan(parts, axis)
    gb = program.global_block()
    seen_params = set()
    for op in gb.ops:
        slots = ZERO1_SHARDABLE_SLOTS.get(op.type)
        if slots is None:
            continue
        pname = (op.inputs.get("Param") or [None])[0]
        gname = (op.inputs.get("Grad") or [None])[0]
        if not pname or not gname:
            continue
        pvar = gb.vars.get(pname)
        gvar = gb.vars.get(gname)

        def skip(reason):
            plan.skipped.append((pname, reason))

        if pvar is None or pvar.shape is None or any(
                d is None or d < 0 for d in pvar.shape or ()):
            skip("dynamic or unknown param shape")
            continue
        if pname in seen_params:
            skip("param updated by more than one optimizer op")
            continue
        if getattr(pvar, "sharding", None) is not None:
            skip("param carries a user set_sharding rule (mp-parallel)")
            continue
        if gvar is not None and (
                gvar.type == VarType.SELECTED_ROWS
                or getattr(gvar, "lod_level", 0)):
            skip("sparse/ragged gradient")
            continue
        accums = []
        ok = True
        for in_slot, out_slot in slots:
            names = op.inputs.get(in_slot) or []
            outs = op.outputs.get(out_slot) or []
            if not names or not outs or names[0] != outs[0]:
                ok = False
                break
            avar = gb.vars.get(names[0])
            if avar is None or tuple(avar.shape or ()) != tuple(pvar.shape):
                ok = False
                break
            accums.append((in_slot, out_slot, names[0], avar.dtype))
        if not ok:
            skip("accumulator wiring does not match the shardable contract")
            continue
        numel = int(np.prod(pvar.shape)) if pvar.shape else 1
        if numel <= 0:
            skip("empty param")
            continue
        seen_params.add(pname)
        e = _Entry(pname, gname, op.type, pvar.shape or (1,), numel, parts,
                   pvar.dtype, accums)
        plan.entries.append(e)
        for _, _, name, _ in accums:
            plan._by_accum[name] = e
    return plan


# ---------------------------------------------------------------------------
# the rewrite pass
# ---------------------------------------------------------------------------
def apply(program, parts, axis=DP_AXIS, grad_scale=1.0):
    """Clone `program` and rewrite every plannable optimizer op onto the
    shard layout. Returns (rewritten_program, plan). The original program
    is untouched (ParallelExecutor keeps it as the user-visible IR and the
    checkpoint/manifest source of full shapes).

    grad_scale is folded into the gradient reduce-scatter (the
    GradientScaleStrategy satellite): 1.0 for CoeffNumDevice/Customized
    (the traced loss is already a global-batch mean, so gradients are
    already the cross-replica mean), dp_size for One (sum semantics)."""
    from ..core.framework import Operator

    clone = program.clone()
    plan = build_plan(clone, parts, axis)
    if not plan.entries:
        return clone, plan
    gb = clone.global_block()
    emap = {(e.op_type, e.param): e for e in plan.entries}
    new_ops = []
    for op in gb.ops:
        e = None
        if op.type in ZERO1_SHARDABLE_SLOTS:
            e = emap.get((op.type, (op.inputs.get("Param") or [None])[0]))
        if e is None:
            new_ops.append(op)
            continue
        gshard = e.grad + "@zero1_rs"
        pshard = e.param + "@zero1_shard"
        pupd = e.param + "@zero1_upd"
        for n, dt in ((gshard, (gb.vars.get(e.grad).dtype
                                if e.grad in gb.vars else e.dtype)),
                      (pshard, e.dtype), (pupd, e.dtype)):
            gb.create_var(name=n, shape=(parts, e.shard), dtype=dt,
                          persistable=False)
        new_ops.append(Operator(
            gb, "zero1_scatter", {"X": [e.grad]}, {"Out": [gshard]},
            {"parts": parts, "axis_name": axis,
             "scale": float(grad_scale)}))
        # the param-side scatter carries no pending reduction: under GSPMD
        # it lowers to each replica slicing its shard of the replicated
        # param — layout change only, no collective
        new_ops.append(Operator(
            gb, "zero1_scatter", {"X": [e.param]}, {"Out": [pshard]},
            {"parts": parts, "axis_name": axis}))
        op.rename_input(e.param, pshard)
        op.rename_input(e.grad, gshard)
        op.rename_output(e.param, pupd)
        new_ops.append(op)
        new_ops.append(Operator(
            gb, "zero1_gather", {"X": [pupd]}, {"Out": [e.param]},
            {"numel": e.numel, "shape": list(e.shape),
             "axis_name": axis}))
        # accumulators live permanently in the shard layout: rewrite the
        # var shape and pin dim 0 onto the dp axis so _state_sharding
        # places each replica's shard locally (the Nx memory cut)
        for _, _, name, _ in e.accums:
            avar = gb.vars[name]
            avar.shape = (parts, e.shard)
            avar.sharding = (axis, None)
    gb.ops = new_ops
    clone._mutation += 1
    _register(plan)
    return clone, plan


def apply_grad_scale(program, plan, scale):
    """All-reduce-path GradientScaleStrategy: clone `program` and insert a
    full-size per-gradient `scale` op before each optimizer op — the cost
    zero1 folds into its reduce-scatter. Kept for numeric parity tests and
    for BuildStrategy.GradientScaleStrategy.One without zero1."""
    from ..core.framework import Operator

    clone = program.clone()
    gb = clone.global_block()
    targets = {(e.op_type, e.param): e for e in plan.entries}
    new_ops = []
    for op in gb.ops:
        e = None
        if op.type in ZERO1_SHARDABLE_SLOTS:
            e = targets.get((op.type, (op.inputs.get("Param") or [None])[0]))
        if e is None:
            new_ops.append(op)
            continue
        scaled = e.grad + "@scaled"
        gb.create_var(name=scaled, shape=e.shape, dtype=e.dtype,
                      persistable=False)
        new_ops.append(Operator(
            gb, "scale", {"X": [e.grad]}, {"Out": [scaled]},
            {"scale": float(scale)}))
        op.rename_input(e.grad, scaled)
        new_ops.append(op)
    gb.ops = new_ops
    clone._mutation += 1
    return clone


# ---------------------------------------------------------------------------
# process-wide registry: checkpointing needs to recognize shard-layout
# accumulator values without a handle on the ParallelExecutor
# ---------------------------------------------------------------------------
_REGISTRY = {}  # accum var name -> (Zero1Plan, _Entry)


def _register(plan):
    for e in plan.entries:
        for _, _, name, _ in e.accums:
            _REGISTRY[name] = (plan, e)


def registered_entry(name):
    """(plan, entry) for an accumulator var sharded by an applied zero1
    pass in this process, or None."""
    return _REGISTRY.get(name)


def reset_registry():
    _REGISTRY.clear()


def canonicalize_snapshot(snap):
    """Convert shard-layout accumulator arrays in a checkpoint snapshot to
    the canonical full layout. Returns (snap, zero1_manifest_section) where
    the section is None when nothing in the snapshot was shard-laid-out.
    The conversion is an exact unpad (bitwise stable), so checkpoints are
    portable across dp sizes and restore onto FLAGS_zero1=0 unchanged."""
    zinfo = {}
    out = dict(snap)
    for name, arr in snap.items():
        reg = _REGISTRY.get(name)
        if reg is None:
            continue
        plan, e = reg
        if tuple(arr.shape) != (plan.parts, e.shard):
            continue
        out[name] = from_shard_layout(arr, e.numel, e.shape)
        zinfo.setdefault(e.param, e.describe(plan.parts))
    return out, (zinfo or None)


def ensure_scope_unsharded(scope, program):
    """Undo the shard layout for accumulators in `scope` that belong to
    `program` — the FLAGS_zero1=0 (or BuildStrategy flip) path after a
    sharded run in the same process. Cheap no-op when zero1 never ran."""
    if not _REGISTRY:
        return
    gb = program.global_block()
    for name, (plan, e) in _REGISTRY.items():
        if name not in gb.vars:
            continue
        v = scope.find_var(name)
        if v is None or not hasattr(v, "shape"):
            continue
        if tuple(v.shape) == (plan.parts, e.shard) \
                and tuple(v.shape) != tuple(e.shape):
            scope.set_var(name, from_shard_layout(_host(v), e.numel,
                                                  e.shape))

