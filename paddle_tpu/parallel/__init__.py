"""Parallelism package: mesh management, multi-host bootstrap, the RPC
variable runtime (pserver transport), and sequence-parallel ring attention.

Reference mapping (SURVEY.md §2.4):
  NCCL collectives      -> mesh + XLA collectives (mesh.py; pjit shardings)
  gen_nccl_id bootstrap -> distributed.py (jax.distributed over DCN)
  gRPC send/recv        -> rpc.py (TCP variable transport) + ops/rpc_ops.py
  (absent in reference) -> ring_attention.py sequence/context parallelism
  kReduce strategy      -> zero1.py ZeRO-1 sharded weight update
                           (FLAGS_zero1 / BuildStrategy.sharded_weight_update)
  (absent in reference) -> autoshard/ GSPMD-style sharding propagation
                           (FLAGS_autoshard / BuildStrategy.auto_sharding)
                           + search.py whole-plan seed search
  (absent in reference) -> pipeline/ inter-op pipeline parallelism over
                           the pp mesh axis (1F1B; NOT the input-feeder
                           shim in paddle_tpu/pipeline.py)
"""

from . import mesh
from . import zero1
from . import autoshard
from . import pipeline
from . import distributed
from . import rpc
from . import ring
from . import master
from . import elastic
from . import sharded_embedding
from . import flash
from . import api
from .mesh import (make_mesh, data_parallel_mesh, mesh_scope,
                   mesh_geometry, MeshSpec)
from .elastic import (ElasticController, ElasticConfig, ElasticError,
                      Resized, RescalePolicy, LinearRescale,
                      ConstantRescale)
from .ring import (ring_attention, ring_attention_sharded,
                   ring_flash_attention,
                   ring_flash_attention_sharded)
from .sharded_embedding import shard_table, sharded_embedding_lookup
from .api import set_sharding, get_sharding, sharding_scope
from .flash import flash_attention

__all__ = [
    "mesh", "distributed", "rpc", "ring", "sharded_embedding", "api",
    "flash", "zero1", "autoshard", "pipeline", "elastic",
    "make_mesh", "data_parallel_mesh", "mesh_scope",
    "mesh_geometry", "MeshSpec",
    "ElasticController", "ElasticConfig", "ElasticError", "Resized",
    "RescalePolicy", "LinearRescale", "ConstantRescale",
    "ring_attention", "ring_attention_sharded",
    "ring_flash_attention", "ring_flash_attention_sharded",
    "shard_table", "sharded_embedding_lookup",
    "set_sharding", "get_sharding", "sharding_scope", "flash_attention",
]
