"""Elastic data-parallel training: resize the dp mesh mid-job.

The Fluid lineage's Go/etcd fault-tolerant master (PAPER.md §0) exists so
a training job outlives its workers. This module closes that loop for the
TPU runtime: every trainer runs an ElasticController around its step
loop, the master (parallel/master.py) tracks a TTL'd membership set with
a monotonically increasing *membership epoch*, and any join/leave —
heartbeat lapse, connection close, or the explicit SIGTERM-drain from
resilience/preempt.py — bumps the epoch. On an epoch change every
surviving trainer hits the resize barrier at its next step boundary:

    1. barrier("resize", epoch)   all survivors of the new epoch meet;
                                  the release assigns dense ranks
    2. rank 0 commits a blocking checkpoint at the resize point
    3. barrier("commit", epoch)   nobody proceeds past an uncommitted save
    4. re-form the device mesh    MeshSpec.build(dp=world) — shrink is a
                                  device subset, growth re-admits the tail
    5. adopt the newest committed checkpoint (layout-independent: zero1/
       autoshard snapshots are canonical full layout, so a dp=8 state
       restores onto dp=4 bitwise), refusing on an mp-geometry conflict
    6. rescale lr via the pluggable RescalePolicy (linear-lr default,
       warmup ramp after growth), resume from the exact datapipe position

and raises Resized so the caller re-enters its loop on the new mesh —
recompilation amortized by the executor compile cache. A worker whose
membership lapsed (it was partitioned or restarted) is REFUSED by the
generation-fenced heartbeat and re-joins under a strictly newer epoch:
restarted stragglers rejoin at the next epoch instead of restarting the
job. See docs/elastic.md for the lifecycle and the manual runbook.
"""

import threading
import time

import numpy as np

from .. import monitor
from .. import trace
from . import mesh as mesh_mod
from .master import MasterClient, MasterService

__all__ = ["ElasticConfig", "ElasticController", "ElasticError", "Resized",
           "RescalePolicy", "LinearRescale", "ConstantRescale",
           "find_lr_var"]


class ElasticError(RuntimeError):
    """The resize protocol failed (barrier timeout, world below
    min_world); the job cannot safely continue on this worker."""


class Resized(Exception):
    """The controller re-formed the mesh: the caller must re-enter its
    step loop (the scope/pipe are already re-seated on the adopted
    checkpoint, exactly like resilience.RolledBack)."""

    def __init__(self, epoch, world_size, rank, members, old_world,
                 manifest=None, mesh=None):
        super().__init__(
            f"elastic resize: epoch {epoch}, world {old_world} -> "
            f"{world_size} (rank {rank})")
        self.epoch = epoch
        self.world_size = world_size
        self.rank = rank
        self.members = list(members)
        self.old_world = old_world
        self.manifest = manifest
        self.mesh = mesh


# --------------------------------------------------------------- rescale
class RescalePolicy:
    """How global batch and lr react to a world-size change.

    The contract: `lr_scale(base_world, world)` is the steady-state lr
    multiplier vs the base configuration, `batch_scale` the global-batch
    multiplier (informational — per-worker batch is what the datapipe
    actually controls), and `warmup_steps` is how many steps the lr ramps
    from its pre-resize value to the new target after a GROWTH (big fresh
    batch + full lr at step one after a grow is the classic divergence
    recipe; shrink applies the new lr immediately).
    """

    warmup_steps = 0

    def lr_scale(self, base_world, world):
        return 1.0

    def batch_scale(self, base_world, world):
        return 1.0


class LinearRescale(RescalePolicy):
    """Linear scaling rule: per-worker batch stays fixed, so the global
    batch — and with it the lr — scales with the world size."""

    def __init__(self, warmup_steps=0):
        self.warmup_steps = int(warmup_steps)

    def lr_scale(self, base_world, world):
        return float(world) / float(base_world)

    def batch_scale(self, base_world, world):
        return float(world) / float(base_world)


class ConstantRescale(RescalePolicy):
    """Keep global batch and lr fixed across resizes (every worker
    computes the full global batch — the parity-drill configuration, and
    the right choice when reproducibility beats throughput)."""


def find_lr_var(program, scope=None):
    """Name of the optimizer's global learning-rate var in `program`
    (optimizer._create_global_learning_rate names it learning_rate_<n>),
    or None. With `scope`, only names actually materialized there."""
    if program is None:
        return None
    for var in program.list_vars():
        if var.name.startswith("learning_rate") and var.persistable:
            if scope is None or scope.find_var(var.name) is not None:
                return var.name
    return None


# ------------------------------------------------------------ controller
class ElasticConfig:
    """master:            endpoint "host:port", a MasterClient, or an
                          in-process MasterService (tests)
    name:                 this worker's membership name (unique per job)
    addr:                 advertised address (informational)
    ttl:                  membership lease; a worker silent for ttl is
                          reaped and the survivors resize
    heartbeat_interval:   beat cadence (default ttl/3)
    start_world:          block start() until this many workers joined
                          (None = start stepping immediately)
    min_world:            resize below this raises ElasticError
    policy:               RescalePolicy (default LinearRescale())
    lr_var:               learning-rate var name (None = auto-detect from
                          the runner's program)
    mesh_spec:            MeshSpec re-formed at each resize (None = the
                          mesh, if any, is the caller's business via
                          mesh_factory/on_resize)
    checkpoint_on_resize: rank 0 commits a blocking save at the barrier
    restore_on_resize:    every survivor adopts the newest committed
                          checkpoint after the commit barrier
    barrier_timeout:      per-barrier wait; resize_timeout bounds the
                          whole protocol including restarts
    """

    def __init__(self, master, name, addr="", ttl=5.0,
                 heartbeat_interval=None, start_world=None, min_world=1,
                 policy=None, lr_var=None, mesh_spec=None,
                 checkpoint_on_resize=True, restore_on_resize=True,
                 barrier_timeout=30.0, resize_timeout=120.0):
        self.master = master
        self.name = str(name)
        self.addr = str(addr)
        self.ttl = float(ttl)
        self.heartbeat_interval = (self.ttl / 3.0 if heartbeat_interval
                                   is None else float(heartbeat_interval))
        self.start_world = start_world
        self.min_world = int(min_world)
        self.policy = policy if policy is not None else LinearRescale()
        self.lr_var = lr_var
        self.mesh_spec = mesh_spec
        self.checkpoint_on_resize = bool(checkpoint_on_resize)
        self.restore_on_resize = bool(restore_on_resize)
        self.barrier_timeout = float(barrier_timeout)
        self.resize_timeout = float(resize_timeout)


class ElasticController:
    """One per trainer, wrapped around the step loop.

        ctl = ElasticController(ElasticConfig(master, name="w0"))
        ctl.start(runner)            # join + initial barrier -> rank/world
        while training:
            step()
            ctl.poll(runner, pipe)   # raises Resized on an epoch change
        ctl.stop()

    With a ResilientRunner the wiring is automatic: pass the controller
    as ResilienceConfig(elastic=ctl) and the runner polls at every step
    boundary, drains membership on SIGTERM, and the Trainer re-enters its
    loop on Resized.

    mesh_factory(world, rank, members) -> Mesh overrides cfg.mesh_spec;
    on_resize(resized) observes every completed resize (rebuild a
    ParallelExecutor over resized.mesh here).
    """

    def __init__(self, config, mesh_factory=None, on_resize=None):
        self.config = config
        self.name = config.name
        self.on_resize = on_resize
        if mesh_factory is not None:
            self.mesh_factory = mesh_factory
        elif config.mesh_spec is not None:
            self.mesh_factory = \
                lambda world, rank, members: config.mesh_spec.build(world)
        else:
            self.mesh_factory = None
        m = config.master
        self._owns_master = isinstance(m, str)
        self._master = MasterClient(m) if self._owns_master else m
        self.epoch = -1
        self.world_size = 0
        self.rank = -1
        self.members = []
        self.mesh = None
        self.resizes = 0
        self.base_lr = None
        self.base_world = None
        self._lr_var = config.lr_var
        self._cur_lr = None
        self._ramp = []          # pending warmup lr values, one per poll
        self._resize_pending = threading.Event()
        self._needs_rejoin = False
        self._stop_evt = threading.Event()
        self._hb_thread = None
        self._started = False

    # ------------------------------------------------------------ lifecycle
    def start(self, runner=None):
        """Join the membership, optionally wait for start_world peers,
        pass the initial barrier to learn rank/world, start heartbeats."""
        cfg = self.config
        r = self._master.elastic_join(self.name, cfg.addr, cfg.ttl)
        self.epoch = int(r["epoch"])
        if cfg.start_world:
            deadline = time.monotonic() + cfg.resize_timeout
            while len(self._master.elastic_membership()["members"]) \
                    < int(cfg.start_world):
                if time.monotonic() > deadline:
                    raise ElasticError(
                        f"{self.name}: only "
                        f"{len(self._master.elastic_membership()['members'])}"
                        f" of start_world={cfg.start_world} workers joined "
                        f"within {cfg.resize_timeout}s")
                time.sleep(0.02)
        members, rank, epoch = self._join_barriers()
        self.epoch = epoch
        self.members = members
        self.rank = rank
        self.world_size = len(members)
        self.base_world = int(cfg.start_world or self.world_size)
        if self.mesh_factory is not None:
            self.mesh = self.mesh_factory(self.world_size, self.rank,
                                          self.members)
        self._capture_base_lr(runner)
        self._record_membership_gauges()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, name=f"elastic-hb-{self.name}",
            daemon=True)
        self._hb_thread.start()
        self._started = True
        return self

    def drain(self):
        """Explicit SIGTERM-drain: leave the membership NOW so the
        survivors resize immediately instead of waiting out the TTL. The
        heartbeat stops first — a post-leave beat would be refused as a
        zombie anyway."""
        self._stop_evt.set()
        try:
            self._master.elastic_leave(self.name)
        except Exception:  # noqa: BLE001 — best-effort on the way down
            pass
        monitor.registry().counter(
            "elastic_drains_total",
            help="explicit membership leaves (SIGTERM-drain)").inc()

    def stop(self):
        """Leave + tear down (normal end of training)."""
        if not self._stop_evt.is_set():
            self.drain()
        if self._hb_thread is not None and self._hb_thread.is_alive():
            self._hb_thread.join(timeout=10.0)
        if self._owns_master:
            try:
                self._master.close()
            except Exception:  # noqa: BLE001
                pass

    def status(self):
        return {"name": self.name, "epoch": self.epoch,
                "world_size": self.world_size, "rank": self.rank,
                "members": list(self.members), "resizes": self.resizes,
                "resize_pending": self._resize_pending.is_set()}

    # ------------------------------------------------------------ heartbeat
    def _hb_loop(self):
        cfg = self.config
        while not self._stop_evt.is_set():
            try:
                r = self._master.elastic_heartbeat(self.name, self.epoch)
                if not r.get("known"):
                    # membership lapsed: the survivors already resized away
                    # from us — rejoin under a NEW epoch at the next step
                    # boundary (never resurrect the old one)
                    self._needs_rejoin = True
                    self._resize_pending.set()
                elif r.get("stale") or int(r["epoch"]) != self.epoch:
                    self._resize_pending.set()
            except Exception:  # noqa: BLE001 — a missed beat is not fatal
                pass
            self._stop_evt.wait(cfg.heartbeat_interval)

    def resize_pending(self):
        return self._resize_pending.is_set()

    # ----------------------------------------------------------------- poll
    def poll(self, runner=None, pipe=None):
        """Step-boundary hook. Applies any in-flight lr warmup ramp, then
        runs the resize protocol if an epoch change is pending — raising
        Resized so the caller re-enters its loop on the new mesh."""
        if self._stop_evt.is_set():
            return  # draining: never resize (or rejoin) on the way down
        if self._ramp:
            self._apply_lr(self._ramp.pop(0), runner)
        if not (self._resize_pending.is_set() or self._needs_rejoin):
            return
        self._resize(runner, pipe)

    # --------------------------------------------------------------- resize
    def _join_barriers(self):
        """The joiner's half of the resize protocol. A fresh worker must
        answer BOTH fleet barriers: incumbents run resize -> (rank-0
        save) -> commit, and the commit releases only when every member
        of the epoch — joiners included — arrives. A join that only
        answered the first barrier would wedge the incumbents' commit."""
        cfg = self.config
        deadline = time.monotonic() + cfg.resize_timeout
        while True:
            members, rank, epoch = self._barrier_until_released(
                "resize", deadline=deadline)
            b2 = self._master.elastic_barrier(
                self.name, epoch, "commit", cfg.barrier_timeout)
            if b2.get("ok"):
                return members, rank, epoch
            if b2.get("unknown"):
                self._needs_rejoin = True
            if time.monotonic() > deadline:
                raise ElasticError(
                    f"{self.name}: join commit barrier did not release "
                    f"within {cfg.resize_timeout}s (last: {b2})")

    def _barrier_until_released(self, phase, epoch=None, deadline=None):
        """Drive one barrier phase to release, restarting on epoch moves
        (concurrent leave/join while the barrier forms) and re-joining if
        our own membership lapsed mid-protocol. Returns (members, rank,
        epoch)."""
        cfg = self.config
        if deadline is None:
            deadline = time.monotonic() + cfg.resize_timeout
        while True:
            if self._needs_rejoin:
                if self._stop_evt.is_set():
                    # a drained worker's in-flight barrier RPC comes back
                    # `unknown` after its own leave; rejoining here would
                    # resurrect the membership we just gave up
                    raise ElasticError(
                        f"{self.name}: draining — refusing to rejoin a "
                        f"membership we left")
                r = self._master.elastic_join(self.name, cfg.addr, cfg.ttl)
                self._needs_rejoin = False
                epoch = int(r["epoch"])
                monitor.registry().counter(
                    "elastic_rejoins_total",
                    help="lapsed workers re-admitted under a new epoch"
                ).inc()
            if epoch is None:
                epoch = int(self._master.elastic_membership()["epoch"])
            b = self._master.elastic_barrier(
                self.name, epoch, phase, cfg.barrier_timeout)
            if b.get("ok"):
                return list(b["members"]), int(b["rank"]), int(b["epoch"])
            if b.get("unknown"):
                self._needs_rejoin = True
            if time.monotonic() > deadline:
                raise ElasticError(
                    f"{self.name}: barrier {phase!r} did not release "
                    f"within {cfg.resize_timeout}s (last: {b})")
            # restart against the reported epoch; on a bare timeout retry
            # the same epoch (stragglers may still be finishing a step)
            epoch = int(b["epoch"]) if b.get("restart") else epoch

    def _resize(self, runner, pipe):
        cfg = self.config
        t0 = time.perf_counter()
        old_world, old_epoch = self.world_size, self.epoch
        reg = monitor.registry()
        try:
            with trace.span("elastic.resize", kind="elastic",
                            worker=self.name, old_epoch=old_epoch,
                            old_world=old_world):
                resized = self._resize_inner(runner, pipe, old_world)
        except ElasticError:
            reg.counter("elastic_resize_failures_total",
                        help="resize protocol failures").inc()
            trace.maybe_dump("elastic_resize_failed")
            raise
        ms = (time.perf_counter() - t0) * 1000.0
        self.resizes += 1
        reg.counter("elastic_resizes_total",
                    help="completed elastic mesh resizes").inc()
        reg.gauge("elastic_resize_duration_ms",
                  help="wall time of the last resize (barrier + "
                       "checkpoint + mesh re-form + restore)").set(ms)
        self._record_membership_gauges()
        if self.on_resize is not None:
            self.on_resize(resized)
        raise resized

    def _resize_inner(self, runner, pipe, old_world):
        cfg = self.config
        deadline = time.monotonic() + cfg.resize_timeout
        while True:
            members, rank, epoch = self._barrier_until_released(
                "resize", deadline=deadline)
            if len(members) < cfg.min_world:
                raise ElasticError(
                    f"world shrank to {len(members)} < min_world="
                    f"{cfg.min_world} (members {members})")
            # rank 0 commits the fleet's resume point; the commit barrier
            # guarantees nobody adopts an uncommitted save. If membership
            # moves between the two barriers (a straggler rejoining while
            # we restore — the rejoin-during-restore race) the commit
            # barrier restarts and the whole protocol re-runs against the
            # newer epoch.
            if rank == 0 and cfg.checkpoint_on_resize \
                    and getattr(runner, "checkpoint", None) is not None:
                runner.save(pipe=pipe, block=True,
                            extra={"elastic": {"epoch": epoch,
                                               "world_size": len(members),
                                               "members": members}})
            b2 = self._master.elastic_barrier(
                self.name, epoch, "commit", cfg.barrier_timeout)
            if b2.get("ok"):
                break
            if b2.get("unknown"):
                self._needs_rejoin = True
            if time.monotonic() > deadline:
                raise ElasticError(
                    f"{self.name}: commit barrier did not release within "
                    f"{cfg.resize_timeout}s (last: {b2})")
        # re-form the mesh BEFORE adopting state, so the restore can
        # refuse a checkpoint whose mp geometry conflicts with it
        new_mesh = None
        if self.mesh_factory is not None:
            new_mesh = self.mesh_factory(len(members), rank, members)
        manifest = None
        if cfg.restore_on_resize and runner is not None \
                and getattr(runner, "checkpoint", None) is not None:
            expect = mesh_mod.mesh_geometry(new_mesh)
            if expect is None and cfg.mesh_spec is not None:
                expect = cfg.mesh_spec.geometry(len(members))
            manifest = runner.adopt(pipe=pipe, expect_mesh=expect)
        self.mesh = new_mesh
        self.epoch = epoch
        self.members = members
        self.rank = rank
        self.world_size = len(members)
        self._apply_rescale(old_world, len(members), runner)
        self._resize_pending.clear()
        return Resized(epoch, len(members), rank, members, old_world,
                       manifest=manifest, mesh=new_mesh)

    # -------------------------------------------------------------- rescale
    def _capture_base_lr(self, runner):
        if self._lr_var is None and runner is not None:
            self._lr_var = find_lr_var(getattr(runner, "program", None),
                                       getattr(runner, "scope", None))
        if self._lr_var is None or runner is None \
                or getattr(runner, "scope", None) is None:
            return
        v = runner.scope.find_var(self._lr_var)
        if v is not None:
            self.base_lr = float(np.asarray(v).reshape(-1)[0])
            self._cur_lr = self.base_lr

    def _apply_lr(self, lr, runner):
        if self._lr_var is None or runner is None \
                or getattr(runner, "scope", None) is None:
            return
        runner.scope.set_var(self._lr_var,
                             np.full([1], lr, dtype=np.float32))
        self._cur_lr = float(lr)
        monitor.registry().gauge(
            "elastic_lr", help="learning rate after elastic rescale "
                               "(includes the warmup ramp)").set(lr)

    def _apply_rescale(self, old_world, world, runner):
        policy = self.config.policy
        if self.base_lr is None:
            self._capture_base_lr(runner)
        base_world = self.base_world or old_world or world
        if self.base_lr is None or not base_world:
            return
        target = self.base_lr * policy.lr_scale(base_world, world)
        prev = self._cur_lr if self._cur_lr is not None else self.base_lr
        grew = old_world and world > old_world
        if grew and policy.warmup_steps > 0 and target != prev:
            # ramp from the pre-resize lr to the new target over
            # warmup_steps polls; the final value lands exactly on target
            n = policy.warmup_steps
            self._ramp = [prev + (target - prev) * (i + 1) / n
                          for i in range(n)]
            self._apply_lr(prev, runner)  # hold until the ramp starts
        else:
            self._ramp = []
            self._apply_lr(target, runner)

    # -------------------------------------------------------------- metrics
    def _record_membership_gauges(self):
        reg = monitor.registry()
        reg.gauge("elastic_epoch",
                  help="current membership epoch").set(self.epoch)
        reg.gauge("elastic_world_size",
                  help="live dp world size (membership count)"
                  ).set(self.world_size)


def fetch_status(endpoint, timeout=10.0):
    """Membership snapshot from a running master ("host:port") — the
    `python -m paddle_tpu elastic status` CLI backend."""
    c = MasterClient(endpoint, connect_timeout=timeout)
    try:
        m = c.elastic_membership()
        return {"endpoint": endpoint, "epoch": int(m["epoch"]),
                "world_size": len(m["members"]),
                "members": dict(m["members"])}
    finally:
        c.close()
