"""Elastic-training coordination service: task-lease master + discovery.

Reference parity: go/master/service.go (task queues :89 Todo/Pending/Done/
Failed, lease timeout + failure cap :341 processFailedTask, GetTask :373,
TaskFinished :411 with pass rollover, snapshot :207) and the etcd
registration in go/pserver/etcd_client.go:67 (here: a TTL'd in-master
registry, since the zero-dependency equivalent of etcd for this runtime is
the master itself).

The master hands out *tasks* (groups of data chunks) under a lease: a
trainer that dies mid-task simply never reports, the lease times out, and
the task is re-dispatched to a live trainer — up to `failure_max` times,
after which the task is discarded to `failed`. When todo and pending drain,
the pass counter advances and done+failed recycle as the next pass's todo.
Every mutation snapshots state to disk so a restarted master resumes the
pass where it died (reference snapshots to etcd; here a file, CRC-guarded).

Transport: the same length-prefixed pickle framing as the variable runtime
(parallel/rpc.py) — this is control-plane traffic, orders of magnitude off
the data path.

Elastic membership (parallel/elastic.py rides on this): trainers join a
TTL'd membership set; every join/leave — explicit, connection close, or
heartbeat lapse — bumps a monotonically increasing *membership epoch*.
Heartbeats are generation-fenced: a beat carrying a stale epoch tells the
worker a resize is pending, and a beat from a lapsed (already-reaped)
member is refused outright — the worker must re-JOIN, which lands it in a
strictly newer epoch, so a zombie can never resurrect the epoch the
survivors already resized away from. The resize barrier releases when
every member of the target epoch has arrived; if membership moves while
the barrier forms (concurrent leave+join), waiters are told to restart
against the new epoch instead of deadlocking on a set that no longer
exists.
"""

import socket
import threading
import time

from . import rpc as _rpc

__all__ = ["Task", "MembershipTable", "MasterService", "MasterClient",
           "Heartbeater", "task_iterator", "PassAfter", "PassBefore",
           "NoMoreAvailable", "AllTasksFailed"]


class PassBefore(RuntimeError):
    """Client is on an earlier pass than the master (drop to next pass)."""


class PassAfter(RuntimeError):
    """Client ran ahead of the master; wait for the pass to roll over."""


class NoMoreAvailable(RuntimeError):
    """No todo tasks right now (others still pending); retry shortly."""


class AllTasksFailed(RuntimeError):
    """Every task of the pass hit the failure cap."""


_ERRS = {"pass_before": PassBefore, "pass_after": PassAfter,
         "no_more": NoMoreAvailable, "all_failed": AllTasksFailed}


class Task:
    """reference service.go:62 TaskMeta+Task: id, epoch (lease generation),
    payload chunks (opaque to the master)."""

    def __init__(self, task_id, chunks):
        self.id = task_id
        self.epoch = 0
        self.num_failure = 0
        self.chunks = list(chunks)

    def __repr__(self):
        return f"Task(id={self.id}, epoch={self.epoch}, chunks={len(self.chunks)})"


def _partition(chunks, chunks_per_task):
    """reference partition():105 — group chunks into tasks of
    chunks_per_task, ids dense from 0 (the reference's nanosecond+rand id
    dance is a workaround it itself FIXMEs; dense ids snapshot cleanly)."""
    chunks_per_task = max(1, int(chunks_per_task))
    tasks = []
    for i in range(0, len(chunks), chunks_per_task):
        tasks.append(Task(len(tasks), chunks[i:i + chunks_per_task]))
    return tasks


class MembershipTable:
    """THE TTL'd, epoch-fenced membership primitive.

    One implementation serves both control planes: the elastic trainer
    mesh (MasterService wraps it in RPC ops) and the serving fleet
    (serve/fleet/membership.py holds one directly). The contract:

    - every join/leave/TTL-lapse bumps a monotonically increasing
      *membership epoch* — a lapse IS a leave, not a soft mark;
    - heartbeats are generation-fenced: a beat from a lapsed (already
      reaped) member is refused (``known=False``) — the member must
      re-JOIN, which lands it in a strictly NEWER epoch, so a zombie can
      never resurrect the epoch the survivors already moved away from;
    - leaves are owner-guarded: a stale connection's teardown cannot
      evict a member that already re-joined under a different owner.

    Not synchronized — the embedding service holds its own lock around
    every call (MasterService its condition variable, fleet Membership
    its mutex). ``on_change`` fires under that lock on every epoch bump
    so the embedder can invalidate forming barriers / update gauges.
    """

    def __init__(self, clock=time.monotonic, on_change=None):
        self._clock = clock
        self.on_change = on_change
        self.members = {}  # name -> {"addr", "expire", "ttl", "owner"}
        self.epoch = 0

    def _bump(self):
        self.epoch += 1
        if self.on_change is not None:
            self.on_change()

    def reap(self, now=None):
        """TTL lapse IS a leave: reaping bumps the epoch so survivors
        resize. Returns the reaped names."""
        now = self._clock() if now is None else now
        dead = [n for n, m in self.members.items() if m["expire"] <= now]
        for n in dead:
            del self.members[n]
        if dead:
            self._bump()
        return dead

    def join(self, name, addr="", ttl=10.0, owner=None):
        """(Re-)join under a fresh lease; always lands in a new epoch."""
        self.reap()
        self.members[name] = {"addr": str(addr),
                              "expire": self._clock() + float(ttl),
                              "ttl": float(ttl), "owner": owner}
        self._bump()
        return self.epoch

    def leave(self, name, owner=None):
        """Explicit departure. With `owner` set, only evicts a membership
        the same owner created (stale-socket teardown guard). Returns
        whether anything was evicted."""
        m = self.members.get(name)
        if m is not None and (owner is None or m["owner"] is None
                              or m["owner"] == owner):
            del self.members[name]
            self._bump()
            return True
        return False

    def heartbeat(self, name, epoch):
        """Generation-fenced liveness. known=False means the member
        lapsed (or never joined): refreshing its TTL here would resurrect
        a stale epoch — it must re-join instead. ``stale`` tells a live
        member its view of the epoch is behind (a resize is pending)."""
        self.reap()
        m = self.members.get(name)
        if m is None:
            return {"known": False, "epoch": self.epoch}
        m["expire"] = self._clock() + m["ttl"]
        return {"known": True, "epoch": self.epoch,
                "stale": int(epoch) != self.epoch}

    def refresh(self, name):
        """Renew one member's lease without the epoch fence (used where
        presence was already established under the embedder's lock)."""
        m = self.members.get(name)
        if m is not None:
            m["expire"] = self._clock() + m["ttl"]

    def get(self, name):
        return self.members.get(name)

    def addrs(self):
        return {n: m["addr"] for n, m in self.members.items()}

    def __contains__(self, name):
        return name in self.members

    def __len__(self):
        return len(self.members)


class MasterService:
    """In-process task-lease service; serve() exposes it over TCP."""

    def __init__(self, chunks_per_task=1, lease_timeout=3.0, failure_max=3,
                 snapshot_path=None, snapshot_every=32):
        self.chunks_per_task = chunks_per_task
        self.lease_timeout = float(lease_timeout)
        self.failure_max = int(failure_max)
        self.snapshot_path = snapshot_path
        # batch snapshots: a full-state pickle per dispatch is O(dataset)
        # under the lock; recover() requeues pending leases anyway, so a
        # slightly stale snapshot only replays a few reports
        self.snapshot_every = max(1, int(snapshot_every))
        self._mutations = 0
        self._mu = threading.Condition()
        self.todo = []
        self.pending = {}   # task_id -> (task, deadline)
        self.done = []
        self.failed = []
        self.cur_pass = 0
        self._registry = {}  # (kind, name) -> (addr, expire_time)
        # elastic membership: the shared TTL'd epoch-fenced table (owner =
        # the serving connection that joined a member, so a stale
        # connection's teardown can't evict a member that already
        # re-joined over a fresh socket). Epoch bumps invalidate any
        # barrier forming against an older epoch, under self._mu.
        self._table = MembershipTable(on_change=self._membership_moved)
        self._barrier_arrived = {}  # (epoch, phase) -> set(names)
        self._barrier_release = {}  # (epoch, phase) -> sorted member list
        # distributed compile service: first-misser compiles, peers fetch
        # the serialized PTAC1 blob by content digest (single-flight
        # leases dedup N simultaneous missers down to ONE compile)
        self._compiled = {}        # digest -> whole-file PTAC1 blob
        self._compile_leases = {}  # digest -> lease expire time
        self._compile_counts = {"puts": 0, "duplicate_puts": 0, "gets": 0,
                                "hits": 0, "waits": 0, "leases": 0,
                                "lease_rejects": 0, "expired_leases": 0}
        self._stop = False
        self._init_done = False
        self._conns = set()  # accepted sockets, closed on stop()
        self._checker = threading.Thread(target=self._timeout_loop,
                                         daemon=True)
        self._checker.start()

    # ---------------------------------------------------------------- state
    def set_dataset(self, chunks):
        """reference SetDataset:281 — idempotent after first success."""
        with self._mu:
            if self._init_done:
                return
            self.todo = _partition(chunks, self.chunks_per_task)
            self._init_done = True
            self._snapshot_locked(force=True)

    def _snapshot_locked(self, force=False):
        """reference snapshot():207 — persist queues + pass counter.
        Unforced calls batch by mutation count; pass boundaries, dataset
        init, and stop() force a write."""
        if not self.snapshot_path:
            return
        self._mutations += 1
        if not force and self._mutations % self.snapshot_every != 0:
            return
        state = {"todo": self.todo, "pending": self.pending,
                 "done": self.done, "failed": self.failed,
                 "cur_pass": self.cur_pass, "init_done": self._init_done}
        _rpc.dump_crc_blob(self.snapshot_path, state)

    @classmethod
    def recover(cls, snapshot_path, **kwargs):
        """Restart from a snapshot: pending leases are conservatively
        requeued (their holders may have died with the master; reference
        recover() reloads state and lets timeouts sort it out — with the
        AfterFunc timers lost, requeueing is the correct translation)."""
        state = _rpc.load_crc_blob(snapshot_path)
        svc = cls(snapshot_path=snapshot_path, **kwargs)
        with svc._mu:
            svc.todo = state["todo"] + [t for t, _ in
                                        state["pending"].values()]
            svc.done = state["done"]
            svc.failed = state["failed"]
            svc.cur_pass = state["cur_pass"]
            svc._init_done = state["init_done"]
        return svc

    # ---------------------------------------------------------------- tasks
    def get_task(self, pass_id):
        """reference GetTask:373."""
        with self._mu:
            if not self._init_done:
                raise NoMoreAvailable("dataset not set yet")
            if pass_id < self.cur_pass:
                raise PassBefore(f"client pass {pass_id} < {self.cur_pass}")
            if pass_id > self.cur_pass:
                raise PassAfter(f"client pass {pass_id} > {self.cur_pass}")
            if not self.todo:
                if not self.done and not self.pending:
                    raise AllTasksFailed("all tasks of this pass failed")
                raise NoMoreAvailable("no todo tasks (others pending)")
            t = self.todo.pop(0)
            t.epoch += 1
            self.pending[t.id] = (t, time.monotonic() + self.lease_timeout)
            self._snapshot_locked()
            return t

    def task_finished(self, task_id):
        """reference TaskFinished:411 (incl. pass rollover)."""
        with self._mu:
            entry = self.pending.pop(task_id, None)
            if entry is None:
                return  # late report after timeout requeue: ignore
            t, _ = entry
            t.num_failure = 0
            self.done.append(t)
            self._maybe_rollover_locked()
            self._snapshot_locked()

    def _maybe_rollover_locked(self):
        """Advance the pass when todo+pending drain. Must ALSO run on the
        failure paths: if the pass's last outstanding task hits the failure
        cap, waiting for a task_finished that can never come would livelock
        every trainer in NoMoreAvailable. (The reference only checks in
        TaskFinished — its own 'deal with failed tasks' TODO.) A pass with
        zero successes stays put so get_task raises AllTasksFailed."""
        if not self.todo and not self.pending and self.done:
            self.cur_pass += 1
            self.todo = self.done + self.failed
            for t2 in self.todo:
                t2.num_failure = 0
            self.done, self.failed = [], []
            self._mu.notify_all()
            self._snapshot_locked(force=True)

    def task_failed(self, task_id, epoch):
        """reference TaskFailed:454."""
        with self._mu:
            entry = self.pending.get(task_id)
            if entry is None:
                return
            self._process_failed_locked(task_id, epoch)
            self._maybe_rollover_locked()
            self._snapshot_locked()

    def _process_failed_locked(self, task_id, epoch):
        """reference processFailedTask:341."""
        t, _ = self.pending[task_id]
        if t.epoch != epoch:
            return  # stale report from a previous lease
        del self.pending[task_id]
        t.num_failure += 1
        if t.num_failure > self.failure_max:
            self.failed.append(t)
        else:
            self.todo.append(t)

    def _timeout_loop(self):
        """Lease reaper (reference time.AfterFunc per dispatch; a scan
        thread is equivalent and survives recover())."""
        while not self._stop:
            time.sleep(min(0.1, self.lease_timeout / 4))
            now = time.monotonic()
            with self._mu:
                expired = [(tid, t.epoch)
                           for tid, (t, dl) in self.pending.items()
                           if dl <= now]
                for tid, epoch in expired:
                    self._process_failed_locked(tid, epoch)
                if expired:
                    self._maybe_rollover_locked()
                    self._snapshot_locked()
                # registry TTL expiry
                dead = [k for k, (_, exp) in self._registry.items()
                        if exp <= now]
                for k in dead:
                    del self._registry[k]
                # elastic membership TTL expiry (heartbeat lapse -> the
                # survivors get a new epoch and resize)
                self._table.reap(now)
                # single-flight compile leases whose holder died: wake
                # blocked fetchers so one of them re-takes the lease and
                # compiles instead of waiting on a corpse
                lapsed = [d for d, exp in self._compile_leases.items()
                          if exp <= now]
                for d in lapsed:
                    del self._compile_leases[d]
                if lapsed:
                    self._compile_counts["expired_leases"] += len(lapsed)
                    self._mu.notify_all()

    def counts(self):
        with self._mu:
            return {"todo": len(self.todo), "pending": len(self.pending),
                    "done": len(self.done), "failed": len(self.failed),
                    "cur_pass": self.cur_pass}

    # ------------------------------------------------------------ discovery
    def register(self, kind, name, addr, ttl=10.0):
        """reference etcd_client.go:67 Register — TTL'd; heartbeat by
        re-registering."""
        with self._mu:
            self._registry[(kind, name)] = (addr, time.monotonic() + ttl)

    def lookup(self, kind):
        with self._mu:
            now = time.monotonic()
            return {name: addr for (k, name), (addr, exp)
                    in self._registry.items() if k == kind and exp > now}

    # ----------------------------------------------------------- membership
    def _membership_moved(self):
        """MembershipTable on_change hook (fires under self._mu): every
        epoch bump invalidates any barrier forming against an older epoch
        (its waiters restart) and wakes everyone parked on the lock."""
        epoch = self._table.epoch
        for key in [k for k in self._barrier_arrived if k[0] != epoch]:
            del self._barrier_arrived[key]
        for key in [k for k in self._barrier_release if k[0] < epoch - 1]:
            del self._barrier_release[key]
        self._mu.notify_all()

    def elastic_join(self, name, addr="", ttl=10.0, _owner=None):
        with self._mu:
            self._table.join(name, addr, ttl, owner=_owner)
            return {"epoch": self._table.epoch,
                    "members": self._table.addrs()}

    def elastic_leave(self, name, _owner=None):
        """Explicit departure (SIGTERM-drain). With _owner set, only
        evicts a membership this connection created — a dead socket's
        teardown must not take down the re-joined incarnation."""
        with self._mu:
            self._table.leave(name, owner=_owner)
            return {"epoch": self._table.epoch}

    def elastic_heartbeat(self, name, epoch):
        """Generation-fenced liveness. known=False means the member lapsed
        (or never joined): the TTL reaper already resized the survivors
        away from it, so refreshing the TTL here would resurrect a stale
        epoch — the worker must re-join instead."""
        with self._mu:
            return self._table.heartbeat(name, epoch)

    def elastic_membership(self):
        with self._mu:
            self._table.reap()
            return {"epoch": self._table.epoch,
                    "members": self._table.addrs()}

    def elastic_barrier(self, name, epoch, phase="resize", timeout=30.0):
        """Block until every member of `epoch` arrived at (epoch, phase).

        Returns {"ok": True, "members": [...], "rank": i} on release.
        If membership moves while the barrier forms (a waiter's TTL
        lapses, a worker joins, a socket dies) the epoch advances and
        every waiter gets {"restart": True, "epoch": new} — the
        controller re-syncs and re-arrives instead of deadlocking on a
        membership set that no longer exists. Waiting at the barrier IS
        liveness: each wakeup refreshes the waiter's TTL, so a slow
        straggler elsewhere can't expire the workers already parked here.
        """
        epoch = int(epoch)
        deadline = time.monotonic() + float(timeout)
        with self._mu:
            while True:
                now = time.monotonic()
                self._table.reap(now)
                if self._table.epoch != epoch:
                    return {"ok": False, "restart": True,
                            "epoch": self._table.epoch}
                if name not in self._table:
                    return {"ok": False, "restart": True, "unknown": True,
                            "epoch": self._table.epoch}
                self._table.refresh(name)
                key = (epoch, phase)
                self._barrier_arrived.setdefault(key, set()).add(name)
                members = self._barrier_release.get(key)
                if members is None and self._barrier_arrived[key] \
                        >= set(self._table.members):
                    members = sorted(self._table.members)
                    self._barrier_release[key] = members
                    self._mu.notify_all()
                if members is not None:
                    return {"ok": True, "epoch": epoch, "phase": phase,
                            "members": members,
                            "rank": members.index(name)}
                if now >= deadline:
                    return {"ok": False, "timeout": True,
                            "epoch": self._table.epoch,
                            "waiting_for": sorted(
                                set(self._table.members)
                                - self._barrier_arrived.get(key, set()))}
                self._mu.wait(min(0.05, max(0.001, deadline - now)))

    # ------------------------------------------------- distributed compile
    # fetch_compiled service: the first replica to miss a digest takes a
    # single-flight lease and compiles; everyone else blocks on
    # compiled_get until the winner publishes the serialized PTAC1 blob.
    # Blobs are opaque whole-file bytes here — the fetching replica's
    # L2Store re-validates magic/digest/payload checksum before commit,
    # so a corrupt publish can never poison a peer's cache.

    @staticmethod
    def _check_digest(digest):
        from ..cache.keys import is_digest

        if not is_digest(digest):
            raise _rpc.RpcError(f"malformed compile digest {digest!r}")
        return digest

    def compiled_put(self, digest, blob):
        """Publish a compiled blob under its content digest and release
        the single-flight lease; wakes every fetcher parked on it."""
        digest, blob = self._check_digest(digest), bytes(blob)
        with self._mu:
            dup = digest in self._compiled
            self._compiled[digest] = blob
            self._compile_leases.pop(digest, None)
            self._compile_counts["puts"] += 1
            if dup:
                self._compile_counts["duplicate_puts"] += 1
            self._mu.notify_all()
            return {"stored": True, "bytes": len(blob), "duplicate": dup}

    def compiled_get(self, digest, wait_s=0.0):
        """Fetch a blob by digest; with wait_s > 0, park until the
        leaseholder publishes it (or the wait times out -> None)."""
        digest = self._check_digest(digest)
        deadline = time.monotonic() + float(wait_s)
        with self._mu:
            self._compile_counts["gets"] += 1
            waited = False
            while True:
                blob = self._compiled.get(digest)
                if blob is not None:
                    self._compile_counts["hits"] += 1
                    if waited:
                        self._compile_counts["waits"] += 1
                    return blob
                now = time.monotonic()
                if now >= deadline:
                    return None
                waited = True
                self._mu.wait(min(0.05, max(0.001, deadline - now)))

    def compiled_lease(self, digest, ttl=120.0):
        """Single-flight compile dedup: grant at most one live lease per
        digest. granted=True means the caller compiles (and must
        compiled_put, or the lease expires and a waiter re-leases);
        granted=False means someone else is on it (or it's cached)."""
        digest = self._check_digest(digest)
        with self._mu:
            if digest in self._compiled:
                return {"granted": False, "cached": True}
            now = time.monotonic()
            exp = self._compile_leases.get(digest)
            if exp is not None and exp > now:
                self._compile_counts["lease_rejects"] += 1
                return {"granted": False, "cached": False}
            self._compile_leases[digest] = now + float(ttl)
            self._compile_counts["leases"] += 1
            return {"granted": True, "cached": False}

    def compiled_stats(self):
        with self._mu:
            return dict(self._compile_counts,
                        entries=len(self._compiled),
                        bytes=sum(len(b)
                                  for b in self._compiled.values()),
                        active_leases=len(self._compile_leases))

    # -------------------------------------------------------------- serving
    def serve(self, bind="127.0.0.1:0"):
        host, port = bind.rsplit(":", 1)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(32)
        self.port = self._listener.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        # fleet observability: push the master's registry (lease/member
        # gauges) to a collector when FLAGS_obs_push is set; no-op else
        from ..obs import maybe_start as _obs_start

        self._obs_client = _obs_start("master")
        return self.port

    def stop(self):
        self._stop = True
        obs_client = getattr(self, "_obs_client", None)
        if obs_client is not None:
            self._obs_client = None
            obs_client.stop()
        with self._mu:
            self._snapshot_locked(force=True)
        try:
            self._listener.close()
        except (AttributeError, OSError):
            pass
        # also drop live connections: a stopped master must go silent, not
        # keep answering RPCs on old sockets (clients reconnect-with-retry
        # to the replacement; see MasterClient._call)
        with self._mu:
            conns, self._conns = set(self._conns), set()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    def _accept_loop(self):
        while not self._stop:
            try:
                self._listener.settimeout(0.2)
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._mu:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        # leases granted over THIS connection and not yet reported back.
        # A trainer that dies mid-task takes its socket with it: requeue
        # its outstanding leases on disconnect instead of leaking them
        # until the lease timeout stalls the whole pass on one dead peer.
        held = {}  # task_id -> epoch as granted here
        joined = set()  # member names elastic_join'd over THIS connection
        owner = id(conn)
        try:
            while True:
                msg = _rpc._recv_msg(conn)
                op, args = msg[0], msg[1:]
                try:
                    if op == "set_dataset":
                        self.set_dataset(args[0])
                        reply = ("ok", None)
                    elif op == "get_task":
                        t = self.get_task(args[0])
                        held[t.id] = t.epoch
                        reply = ("ok", (t.id, t.epoch, t.chunks))
                    elif op == "task_finished":
                        self.task_finished(args[0])
                        held.pop(args[0], None)
                        reply = ("ok", None)
                    elif op == "task_failed":
                        self.task_failed(args[0], args[1])
                        held.pop(args[0], None)
                        reply = ("ok", None)
                    elif op == "register":
                        self.register(*args)
                        reply = ("ok", None)
                    elif op == "lookup":
                        reply = ("ok", self.lookup(args[0]))
                    elif op == "elastic_join":
                        joined.add(args[0])
                        reply = ("ok", self.elastic_join(*args,
                                                         _owner=owner))
                    elif op == "elastic_leave":
                        joined.discard(args[0])
                        reply = ("ok", self.elastic_leave(args[0],
                                                          _owner=owner))
                    elif op == "elastic_heartbeat":
                        reply = ("ok", self.elastic_heartbeat(*args))
                    elif op == "elastic_membership":
                        reply = ("ok", self.elastic_membership())
                    elif op == "elastic_barrier":
                        reply = ("ok", self.elastic_barrier(*args))
                    elif op == "compiled_put":
                        reply = ("ok", self.compiled_put(*args))
                    elif op == "compiled_get":
                        reply = ("ok", self.compiled_get(*args))
                    elif op == "compiled_lease":
                        reply = ("ok", self.compiled_lease(*args))
                    elif op == "compiled_stats":
                        reply = ("ok", self.compiled_stats())
                    elif op == "counts":
                        reply = ("ok", self.counts())
                    elif op == "exit":
                        self.stop()
                        return
                    else:
                        reply = ("err", f"unknown op {op!r}")
                except tuple(_ERRS.values()) as e:
                    key = next(k for k, cls in _ERRS.items()
                               if isinstance(e, cls))
                    reply = ("taskerr", key, str(e))
                except _rpc.RpcError as e:
                    # a bad argument (e.g. malformed compile digest)
                    # rejects the op, not the connection
                    reply = ("err", str(e))
                _rpc._send_msg(conn, reply)
        except (ConnectionError, EOFError, OSError):
            return
        finally:
            with self._mu:
                self._conns.discard(conn)
                # reclaim this connection's outstanding leases now; the
                # epoch guard in _process_failed_locked drops entries a
                # reconnected client already re-leased under a new epoch
                requeued = False
                for tid, epoch in held.items():
                    if tid in self.pending:
                        self._process_failed_locked(tid, epoch)
                        requeued = True
                if requeued:
                    self._maybe_rollover_locked()
                    self._snapshot_locked()
            # a trainer that dies takes its socket with it: its membership
            # leaves NOW (survivors resize immediately) instead of waiting
            # out the TTL. The owner guard keeps this teardown from
            # evicting a member that already re-joined over a new socket.
            for name in joined:
                self.elastic_leave(name, _owner=owner)
            try:
                conn.close()
            except OSError:
                pass


class MasterClient:
    """reference go/master/client.go + python v2 master client.

    Transport faults (connection reset, broken pipe, a master restart)
    are retried with exponential backoff: the socket is dropped and a
    fresh connection dialed per attempt, so a trainer rides out a master
    restart instead of dying on the first hiccup (the reference client
    re-dials through its etcd watch the same way). Retried get_task calls
    are at-least-once — a lease the master granted just before the
    connection died is simply reclaimed by the lease timeout.
    """

    def __init__(self, endpoint, connect_timeout=30.0, retry=None):
        self._endpoint = endpoint
        self._connect_timeout = float(connect_timeout)
        self._lock = threading.Lock()
        self._sock = None
        self._closed = False
        if retry is None:
            from ..resilience.retry import RetryPolicy

            retry = RetryPolicy(kind="master_client")
        self._retry = retry
        with self._lock:
            self._connect_locked()  # fail fast when the master is absent

    def _connect_locked(self):
        self._sock = _rpc.dial(self._endpoint, self._connect_timeout)

    def _drop_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call(self, *msg):
        from ..resilience.errors import TransientError

        def attempt():
            with self._lock:
                # checked under the lock EVERY attempt: close() may land
                # while the retry policy sleeps between attempts (outside
                # the lock), and a post-close attempt must not re-dial —
                # that socket would leak with nobody left to close it.
                # RpcError is not transient, so the retry loop stops here.
                if self._closed:
                    raise _rpc.RpcError(
                        f"master client for {self._endpoint} is closed")
                try:
                    if self._sock is None:
                        self._connect_locked()
                    _rpc._send_msg(self._sock, msg)
                    resp = _rpc._recv_msg(self._sock)
                except (ConnectionError, EOFError, socket.timeout,
                        OSError) as e:
                    self._drop_locked()  # next attempt re-dials
                    raise TransientError(
                        f"master rpc {msg[0]!r} to {self._endpoint} "
                        f"failed: {e}") from e
            if resp[0] == "taskerr":
                raise _ERRS[resp[1]](resp[2])
            if resp[0] != "ok":
                raise _rpc.RpcError(str(resp[1:]))
            return resp[1]

        return self._retry.call(attempt)

    def set_dataset(self, chunks):
        return self._call("set_dataset", list(chunks))

    def get_task(self, pass_id):
        tid, epoch, chunks = self._call("get_task", pass_id)
        t = Task(tid, chunks)
        t.epoch = epoch
        return t

    def task_finished(self, task_id):
        return self._call("task_finished", task_id)

    def task_failed(self, task_id, epoch):
        return self._call("task_failed", task_id, epoch)

    def register(self, kind, name, addr, ttl=10.0):
        return self._call("register", kind, name, addr, ttl)

    def lookup(self, kind):
        return self._call("lookup", kind)

    # elastic membership (see parallel/elastic.py for the controller that
    # drives these around a training step loop)
    def elastic_join(self, name, addr="", ttl=10.0):
        return self._call("elastic_join", name, addr, ttl)

    def elastic_leave(self, name):
        return self._call("elastic_leave", name)

    def elastic_heartbeat(self, name, epoch):
        return self._call("elastic_heartbeat", name, epoch)

    def elastic_membership(self):
        return self._call("elastic_membership")

    def elastic_barrier(self, name, epoch, phase="resize", timeout=30.0):
        return self._call("elastic_barrier", name, epoch, phase, timeout)

    # distributed compile service (see cache/service.py for the client
    # that rides these from the executors' L2-miss path)
    def compiled_put(self, digest, blob):
        return self._call("compiled_put", digest, blob)

    def compiled_get(self, digest, wait_s=0.0):
        return self._call("compiled_get", digest, wait_s)

    def compiled_lease(self, digest, ttl=120.0):
        return self._call("compiled_lease", digest, ttl)

    def compiled_stats(self):
        return self._call("compiled_stats")

    def counts(self):
        return self._call("counts")

    def close(self):
        """Disconnect THIS client; the master keeps serving other trainers
        (a departing trainer must never take the coordination service — and
        every live lease reaper — down with it). Terminal: a concurrent
        _call riding a reconnect-retry loop stops at its next attempt
        instead of re-dialing a socket nobody would ever close."""
        with self._lock:
            self._closed = True
            self._drop_locked()

    def shutdown_service(self):
        """Stop the master service itself (job teardown)."""
        with self._lock:
            self._closed = True
            try:
                if self._sock is None:
                    self._connect_locked()
                _rpc._send_msg(self._sock, ("exit",))
            except OSError:
                pass
            self._drop_locked()


class Heartbeater:
    """Background TTL re-registration against the master's discovery
    registry (reference etcd_client.go keepalive lease): a serving-fleet
    replica registers (kind, name, addr) and re-registers every ttl/3 so
    the entry outlives hiccups but expires ~one ttl after the process
    dies — which is exactly how the fleet router's discovery loop learns
    about replica death without the replica saying goodbye. Registration
    faults are swallowed (the retry policy inside MasterClient already
    rode out what it could; a missed beat just shortens the lease)."""

    def __init__(self, client, kind, name, addr, ttl=10.0, interval=None):
        self.client = client
        self._kind = kind
        self._name = name
        self._addr = addr
        self._ttl = float(ttl)
        self._interval = (self._ttl / 3.0 if interval is None
                          else float(interval))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"heartbeat-{name}",
                                        daemon=True)
        self.beats = 0

    def start(self):
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.client.register(self._kind, self._name, self._addr,
                                     ttl=self._ttl)
                self.beats += 1
            except Exception:  # noqa: BLE001 — a missed beat is not fatal
                pass
            self._stop.wait(self._interval)

    def stop(self, join=True):
        self._stop.set()
        if join and self._thread.is_alive():
            self._thread.join(timeout=10.0)

    def close(self):
        """One-call teardown: stop the beat loop, then disconnect the
        underlying MasterClient. client.close() is terminal, so a beat
        caught mid-reconnect stops at its next attempt instead of
        re-dialing; the master itself keeps serving other trainers."""
        self.stop()
        self.client.close()


def task_iterator(client, pass_id, poll_interval=0.1, max_wait=60.0):
    """Generator a trainer drives one pass with: lease tasks, yield their
    chunks, report finished; ends when the master rolls to the next pass
    (the python v2 master reader-creator equivalent). On an exception inside
    the consumer the task is reported failed, not finished."""
    deadline = time.monotonic() + max_wait
    while True:
        try:
            task = client.get_task(pass_id)
        except (PassBefore, AllTasksFailed):
            return
        except (NoMoreAvailable, PassAfter):
            if time.monotonic() > deadline:
                raise
            time.sleep(poll_interval)
            continue
        deadline = time.monotonic() + max_wait
        try:
            for chunk in task.chunks:
                yield chunk
        except BaseException:
            client.task_failed(task.id, task.epoch)
            raise
        client.task_finished(task.id)
