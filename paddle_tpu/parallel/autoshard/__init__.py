"""paddle_tpu.parallel.autoshard — GSPMD-style sharding propagation.

Seed a handful of params with `parallel.set_sharding` (or wrap layer
construction in `parallel.sharding_scope`), and autoshard produces a
*total* ShardingPlan assigning every Program variable — params,
activations, grads, optimizer slots — a PartitionSpec over the mesh.
ParallelExecutor lowers the plan as `with_sharding_constraint` at op
outputs inside the compiled step fn when `FLAGS_autoshard` /
`BuildStrategy.auto_sharding` is on. See docs/autoshard.md.

    fluid.parallel.set_sharding(emb_w, ("mp", None))
    fluid.parallel.set_sharding(fc_w, (None, "mp"))
    bs = fluid.BuildStrategy(); bs.auto_sharding = True
    pe = fluid.ParallelExecutor(loss_name=loss.name,
                                mesh_shape={"dp": 4, "mp": 2},
                                build_strategy=bs)
"""

from .spec import normalize_spec, canon, pad_spec, spec_str
from .plan import ShardingPlan, transition_bytes
from .rules import register_rule, rule_for, registered_ops
from .propagate import (build_plan, validate_seeds, register_plan,
                        active_plan, reset_registry, manifest_section)
from .search import (plan_cost, enumerate_seed_candidates, search_plan,
                     SearchResult)

__all__ = [
    "normalize_spec", "canon", "pad_spec", "spec_str",
    "ShardingPlan", "transition_bytes",
    "register_rule", "rule_for", "registered_ops",
    "build_plan", "validate_seeds",
    "register_plan", "active_plan", "reset_registry", "manifest_section",
    "plan_cost", "enumerate_seed_candidates", "search_plan",
    "SearchResult",
]
