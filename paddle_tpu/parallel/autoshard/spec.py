"""PartitionSpec normalization and seed validation.

A sharding spec in paddle_tpu is a tuple with one entry per tensor dim:
a mesh-axis name (str) to shard that dim, or None to replicate it. A spec
shorter than the rank leaves trailing dims replicated. This module accepts
the looser user-facing forms (bare axis string, jax.sharding.PartitionSpec,
1-element per-dim tuples) and canonicalizes them, and validates seed
annotations against a mesh *before* any compilation happens.
"""

__all__ = [
    "normalize_spec", "canon", "pad_spec", "validate_seed_spec",
    "spec_str",
]


def normalize_spec(spec):
    """Canonicalize a user-supplied spec to a tuple of str|None.

    Accepts:
      * a bare mesh-axis name string — shorthand for sharding dim 0
      * a ``jax.sharding.PartitionSpec`` (iterated positionally)
      * any iterable of entries, each a str, None, or a 1-element
        tuple/list wrapping a str (the jax per-dim tuple form)

    Raises TypeError for anything else, including multi-axis-per-dim
    entries which paddle_tpu does not support.
    """
    if isinstance(spec, str):
        return (spec,)
    try:
        from jax.sharding import PartitionSpec as _PS
    except Exception:  # pragma: no cover - jax always present in-tree
        _PS = None
    if _PS is not None and isinstance(spec, _PS):
        spec = tuple(spec)
    try:
        entries = tuple(spec)
    except TypeError:
        raise TypeError(
            f"sharding spec must be a mesh-axis name, a PartitionSpec, or "
            f"a tuple of axis-name/None entries, got {spec!r}")
    out = []
    for e in entries:
        if e is None or isinstance(e, str):
            out.append(e)
        elif (isinstance(e, (tuple, list)) and len(e) == 1
              and isinstance(e[0], str)):
            out.append(e[0])  # jax allows ("mp",) per dim; unwrap it
        else:
            raise TypeError(
                f"spec entries must be mesh-axis names or None, got {e!r}"
                + (" (multiple mesh axes per dim are not supported)"
                   if isinstance(e, (tuple, list)) else ""))
    return tuple(out)


def canon(spec):
    """Canonical comparison form: trim trailing Nones (trailing dims are
    replicated either way), so ('dp', None) == ('dp',) == ('dp',)."""
    if spec is None:
        return None
    spec = tuple(spec)
    n = len(spec)
    while n and spec[n - 1] is None:
        n -= 1
    return spec[:n]


def pad_spec(spec, rank):
    """Pad with trailing Nones to `rank` entries (for display/lowering)."""
    spec = tuple(spec)
    return spec + (None,) * max(0, rank - len(spec))


def spec_str(spec):
    if spec is None:
        return "?"
    if not canon(spec):
        return "replicated"
    return "(" + ", ".join(a if a is not None else "None"
                           for a in tuple(spec)) + ")"


def validate_seed_spec(name, spec, shape, mesh_axes):
    """Validate one seed annotation against the mesh. Raises ValueError
    with the var name, the spec, and the mesh axes in the message —
    this runs at plan-construction time, long before _state_sharding
    would trip over it inside the compiled step.

    `mesh_axes` is a {axis_name: size} dict. Dynamic dims (None/-1) are
    skipped for divisibility — the runtime shape check in the executor
    remains authoritative for those.
    """
    spec = tuple(spec)
    rank = None if shape is None else len(shape)
    if rank is not None and len(spec) > rank:
        raise ValueError(
            f"variable {name!r}: sharding spec {spec_str(spec)} is longer "
            f"than its rank {rank} (shape {tuple(shape)})")
    for d, ax in enumerate(spec):
        if ax is None:
            continue
        if ax not in mesh_axes:
            raise ValueError(
                f"variable {name!r}: sharding spec {spec_str(spec)} names "
                f"mesh axis {ax!r} which is not in the mesh "
                f"(axes: {sorted(mesh_axes)})")
        size = int(mesh_axes[ax])
        if shape is None:
            continue
        dim = shape[d]
        if dim is None or int(dim) < 0:
            continue  # dynamic dim: runtime check is authoritative
        if int(dim) % size != 0:
            raise ValueError(
                f"variable {name!r}: dim {d} of shape {tuple(shape)} is "
                f"not divisible by mesh axis {ax!r} (size {size}) for "
                f"spec {spec_str(spec)}")
