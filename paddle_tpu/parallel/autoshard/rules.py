"""Per-op sharding propagation rules.

Each rule receives (op, ctx) and yields (var_name, proposed_spec) pairs.
Rules are *bidirectional*: they propose specs for outputs from known input
specs AND for inputs from known output specs, so the fixpoint engine in
propagate.py can push seeds both up and down the graph. A proposal is just
a suggestion — the engine arbitrates conflicts with the collective-bytes
cost model, so rules never mutate state directly.

The `ctx` object provides:
    ctx.spec(name)   -> current canonical spec tuple, or None if unknown
    ctx.shape(name)  -> static shape tuple (entries may be None/-1), or None
    ctx.rank(name)   -> len(shape) or None
    ctx.mesh_axes    -> {axis_name: size}
"""

from ..zero1 import ZERO1_SHARDABLE_SLOTS

__all__ = ["register_rule", "rule_for", "registered_ops"]

_RULES = {}


def register_rule(*op_types):
    def deco(fn):
        for t in op_types:
            _RULES[t] = fn
        return fn
    return deco


def rule_for(op_type):
    return _RULES.get(op_type)


def registered_ops():
    return sorted(_RULES)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------
def _first(names):
    return names[0] if names else None


def _at(spec, d):
    """Entry of a (possibly short) spec at dim d."""
    if spec is None:
        return None
    return spec[d] if d < len(spec) else None


def _share(ctx, names):
    """Propose the first known spec among `names` to every other name of
    the same rank — the workhorse for ops where all args are laid out
    identically (sum, activations, assign-likes)."""
    known = None
    for n in names:
        s = ctx.spec(n)
        if s is not None:
            known = s
            break
    if known is None:
        return
    r = None
    for n in names:
        if ctx.spec(n) == known:
            r = ctx.rank(n)
            break
    for n in names:
        if ctx.spec(n) is None and (r is None or ctx.rank(n) == r):
            yield n, known


# ---------------------------------------------------------------------------
# elementwise / shape-preserving: X spec == Out spec, both directions
# ---------------------------------------------------------------------------
@register_rule(
    "relu", "sigmoid", "tanh", "abs", "exp", "sqrt", "square", "log",
    "softsign", "softplus", "ceil", "floor", "round", "reciprocal",
    "leaky_relu", "elu", "relu6", "hard_sigmoid", "swish", "scale",
    "cast", "clip", "dropout", "softmax", "assign", "increment",
    "memcpy", "print")
def _rule_unary(op, ctx):
    x = _first(op.input("X"))
    out = _first(op.output("Out"))
    if x is None or out is None:
        return
    xs, os_ = ctx.spec(x), ctx.spec(out)
    if xs is not None and os_ is None:
        yield out, xs
    elif os_ is not None and xs is None:
        yield x, os_
    # dropout's Mask rides along with Out
    for m in op.output("Mask"):
        if ctx.spec(m) is None and (xs or os_) is not None:
            yield m, xs if xs is not None else os_


@register_rule("sum")
def _rule_sum(op, ctx):
    names = list(op.input("X")) + list(op.output("Out"))
    yield from _share(ctx, names)


@register_rule(
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow")
def _rule_elementwise(op, ctx):
    x = _first(op.input("X"))
    y = _first(op.input("Y"))
    out = _first(op.output("Out"))
    if x is None or out is None:
        return
    xr = ctx.rank(x)
    # X and Out always share layout
    xs, os_ = ctx.spec(x), ctx.spec(out)
    if xs is not None and os_ is None:
        yield out, xs
    elif os_ is not None and xs is None:
        yield x, os_
    ref = xs if xs is not None else os_
    if y is None or xr is None:
        return
    yr = ctx.rank(y)
    if yr is None:
        return
    # Y dim j aligns with X dim (axis + j); default axis = x_rank - y_rank
    axis = op.attrs.get("axis", -1)
    if axis is None or axis < 0:
        axis = xr - yr
    ysh = ctx.shape(y) or ()
    if ref is not None and ctx.spec(y) is None:
        prop = []
        for j in range(yr):
            dim = ysh[j] if j < len(ysh) else None
            # a broadcasting (size-1) Y dim stays replicated
            if dim == 1:
                prop.append(None)
            else:
                prop.append(_at(ref, axis + j))
        yield y, tuple(prop)
    elif ctx.spec(y) is not None:
        ys = ctx.spec(y)
        prop = [None] * xr
        for j in range(yr):
            dim = ysh[j] if j < len(ysh) else None
            if dim != 1 and 0 <= axis + j < xr:
                prop[axis + j] = _at(ys, j)
        if ref is None:
            yield out, tuple(prop)
            yield x, tuple(prop)
        elif any(a is not None and _at(ref, i) is not None
                 and a != _at(ref, i) for i, a in enumerate(prop)):
            # both operands annotated and they CONTRADICT on a dim: put
            # Y's view in front of the arbiter so the disagreement is
            # resolved by cost (and recorded), not silently dropped
            yield out, tuple(prop)


# ---------------------------------------------------------------------------
# contractions: mul / matmul / conv2d
# ---------------------------------------------------------------------------
@register_rule("mul")
def _rule_mul(op, ctx):
    x = _first(op.input("X"))
    y = _first(op.input("Y"))
    out = _first(op.output("Out"))
    if None in (x, y, out):
        return
    xnc = op.attrs.get("x_num_col_dims", 1) or 1
    ync = op.attrs.get("y_num_col_dims", 1) or 1
    xr, yr, orr = ctx.rank(x), ctx.rank(y), ctx.rank(out)
    if None in (xr, yr, orr):
        return
    xs, ys, os_ = ctx.spec(x), ctx.spec(y), ctx.spec(out)
    # Out = [X rows (dims < xnc)] + [Y cols (dims >= ync)].
    # Contracting dims (X[xnc:], Y[:ync]) are flattened in the kernel, so
    # sharding there would misorder the flatten — keep them replicated and
    # only carry the batch/row and column layouts through.
    if os_ is None and (xs is not None or ys is not None):
        prop = [_at(xs, i) for i in range(xnc)]
        prop += [_at(ys, ync + j) for j in range(yr - ync)]
        yield out, tuple(prop)
    if xs is None and os_ is not None:
        yield x, tuple(_at(os_, i) for i in range(xnc))
    if ys is None and os_ is not None:
        prop = [None] * ync
        prop += [_at(os_, xnc + j) for j in range(yr - ync)]
        yield y, tuple(prop)


@register_rule("matmul")
def _rule_matmul(op, ctx):
    x = _first(op.input("X"))
    y = _first(op.input("Y"))
    out = _first(op.output("Out"))
    if None in (x, y, out):
        return
    xr, yr, orr = ctx.rank(x), ctx.rank(y), ctx.rank(out)
    if None in (xr, yr, orr) or xr < 2 or yr < 2 or orr < 2:
        return  # 1-D operands get squeezed; punt to the default rule
    tx = bool(op.attrs.get("transpose_X", False))
    ty = bool(op.attrs.get("transpose_Y", False))
    xs, ys, os_ = ctx.spec(x), ctx.spec(y), ctx.spec(out)
    # row dim of the product in X, col dim in Y (post-transpose)
    xm = xr - 1 if tx else xr - 2
    yn = yr - 2 if ty else yr - 1
    nb = orr - 2  # leading batch dims are elementwise with X's
    if os_ is None and (xs is not None or ys is not None):
        prop = [_at(xs, d) for d in range(min(nb, xr - 2))]
        prop += [None] * (nb - len(prop))
        prop += [_at(xs, xm), _at(ys, yn)]
        yield out, tuple(prop)
    if xs is None and os_ is not None:
        prop = [_at(os_, d) for d in range(xr - 2)]
        m, k = (_at(os_, orr - 2), None)
        prop += [k, m] if tx else [m, k]
        yield x, tuple(prop)
    if ys is None and os_ is not None:
        prop = [_at(os_, d) for d in range(yr - 2)]
        n, k = (_at(os_, orr - 1), None)
        prop += [n, k] if ty else [k, n]
        yield y, tuple(prop)


@register_rule("conv2d", "depthwise_conv2d")
def _rule_conv2d(op, ctx):
    x = _first(op.input("Input"))
    w = _first(op.input("Filter"))
    out = _first(op.output("Output"))
    if None in (x, w, out):
        return
    nhwc = op.attrs.get("data_format", "NCHW") == "NHWC"
    c_ax = 3 if nhwc else 1
    xs, ws, os_ = ctx.spec(x), ctx.spec(w), ctx.spec(out)
    # Out batch follows Input batch; Out channels follow Filter[0] (Cout);
    # spatial dims stay replicated (halo exchange is out of scope); the
    # contracting Cin dim (Input channel vs Filter[1]) stays replicated.
    if os_ is None and (xs is not None or ws is not None):
        prop = [None, None, None, None]
        prop[0] = _at(xs, 0)
        prop[c_ax] = _at(ws, 0)
        yield out, tuple(prop)
    if xs is None and os_ is not None:
        prop = [None, None, None, None]
        prop[0] = _at(os_, 0)
        yield x, tuple(prop)
    if ws is None and os_ is not None:
        yield w, (_at(os_, c_ax),)


# ---------------------------------------------------------------------------
# reductions and losses
# ---------------------------------------------------------------------------
@register_rule("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
               "reduce_prod")
def _rule_reduce(op, ctx):
    x = _first(op.input("X"))
    out = _first(op.output("Out"))
    if x is None or out is None:
        return
    xr = ctx.rank(x)
    if xr is None:
        return
    if op.attrs.get("reduce_all", False):
        if ctx.spec(out) is None:
            yield out, ()
        return
    dim = op.attrs.get("dim", 0)
    dims = {d % xr for d in ([dim] if isinstance(dim, int) else list(dim))}
    keep = bool(op.attrs.get("keep_dim", False))
    xs, os_ = ctx.spec(x), ctx.spec(out)
    if xs is not None and os_ is None:
        prop = []
        for i in range(xr):
            if i in dims:
                if keep:
                    prop.append(None)
            else:
                prop.append(_at(xs, i))
        yield out, tuple(prop)
    elif os_ is not None and xs is None:
        prop, j = [], 0
        for i in range(xr):
            if i in dims:
                prop.append(None)
                if keep:
                    j += 1
            else:
                prop.append(_at(os_, j))
                j += 1
        yield x, tuple(prop)


@register_rule("mean")
def _rule_mean(op, ctx):
    out = _first(op.output("Out"))
    if out is not None and ctx.spec(out) is None:
        yield out, ()


@register_rule("cross_entropy")
def _rule_cross_entropy(op, ctx):
    x = _first(op.input("X"))
    out = _first(op.output("Y"))
    if x is None or out is None:
        return
    xs = ctx.spec(x)
    if xs is not None and ctx.spec(out) is None:
        xr = ctx.rank(x) or 2
        # loss is [batch..., 1]: batch dims carry over, class dim reduced
        yield out, tuple(_at(xs, i) for i in range(xr - 1)) + (None,)


@register_rule("softmax_with_cross_entropy")
def _rule_softmax_xent(op, ctx):
    x = _first(op.input("Logits"))
    if x is None:
        return
    xs = ctx.spec(x)
    if xs is None:
        return
    xr = ctx.rank(x) or 2
    batch = tuple(_at(xs, i) for i in range(xr - 1))
    for sm in op.output("Softmax"):
        if ctx.spec(sm) is None:
            yield sm, xs
    for loss in op.output("Loss"):
        if ctx.spec(loss) is None:
            yield loss, batch + (None,)


@register_rule("square_error_cost", "accuracy")
def _rule_pairwise_loss(op, ctx):
    names = list(op.input("X")) + list(op.input("Input")) \
        + list(op.input("Label")) + list(op.output("Out"))
    yield from _share(ctx, names)


# ---------------------------------------------------------------------------
# layout ops: reshape / transpose / concat / split
# ---------------------------------------------------------------------------
def _reshape_specs(src_shape, dst_shape, src_spec, mesh_axes):
    """Propagate `src_spec` through a reshape from src_shape to dst_shape.
    Returns the dst spec, or None if nothing survives the mapping."""
    if src_spec is None:
        return None
    out = [None] * len(dst_shape)
    i = j = 0
    while i < len(src_shape) and j < len(dst_shape):
        a = src_shape[i] if src_shape[i] is not None else -1
        b = dst_shape[j] if dst_shape[j] is not None else -1
        if a == b:
            if i < len(src_spec):
                out[j] = src_spec[i]
            i += 1
            j += 1
            continue
        if a < 0 or b < 0:
            break
        # group of src dims <-> group of dst dims with equal product
        gi, gj = [i], [j]
        pa, pb = a, b
        i += 1
        j += 1
        while pa != pb:
            if pa < pb:
                if i >= len(src_shape):
                    return tuple(out)
                nxt = src_shape[i]
                if nxt is None or nxt < 0:
                    return tuple(out)
                pa *= nxt
                gi.append(i)
                i += 1
            else:
                if j >= len(dst_shape):
                    return tuple(out)
                nxt = dst_shape[j]
                if nxt is None or nxt < 0:
                    return tuple(out)
                pb *= nxt
                gj.append(j)
                j += 1
        # sharding on the major-most src dim of the group survives onto the
        # major-most dst dim if the axis size divides it; anything else in
        # the group is dropped (would interleave after the flatten).
        ax = src_spec[gi[0]] if gi[0] < len(src_spec) else None
        if ax is not None:
            d0 = dst_shape[gj[0]]
            size = mesh_axes.get(ax)
            if (d0 is not None and d0 > 0 and size
                    and d0 % int(size) == 0):
                out[gj[0]] = ax
    return tuple(out)


@register_rule("reshape", "flatten", "squeeze", "unsqueeze")
def _rule_reshape(op, ctx):
    x = _first(op.input("X"))
    out = _first(op.output("Out"))
    if x is None or out is None:
        return
    xsh, osh = ctx.shape(x), ctx.shape(out)
    if xsh is None or osh is None:
        return
    xs, os_ = ctx.spec(x), ctx.spec(out)
    from .spec import pad_spec
    if xs is not None and os_ is None:
        prop = _reshape_specs(xsh, osh, pad_spec(xs, len(xsh)),
                              ctx.mesh_axes)
        if prop is not None:
            yield out, prop
    elif os_ is not None and xs is None:
        prop = _reshape_specs(osh, xsh, pad_spec(os_, len(osh)),
                              ctx.mesh_axes)
        if prop is not None:
            yield x, prop


@register_rule("transpose")
def _rule_transpose(op, ctx):
    x = _first(op.input("X"))
    out = _first(op.output("Out"))
    if x is None or out is None:
        return
    perm = list(op.attrs.get("axis", []))
    if not perm:
        return
    xs, os_ = ctx.spec(x), ctx.spec(out)
    if xs is not None and os_ is None:
        yield out, tuple(_at(xs, p) for p in perm)
    elif os_ is not None and xs is None:
        inv = [0] * len(perm)
        for i, p in enumerate(perm):
            inv[p] = i
        yield x, tuple(_at(os_, q) for q in inv)


@register_rule("concat")
def _rule_concat(op, ctx):
    xs = list(op.input("X"))
    out = _first(op.output("Out"))
    if not xs or out is None:
        return
    r = ctx.rank(xs[0])
    if r is None:
        return
    axis = op.attrs.get("axis", 0) % r
    known = None
    for n in xs + [out]:
        s = ctx.spec(n)
        if s is not None:
            known = s
            break
    if known is None:
        return
    prop = tuple(None if i == axis else _at(known, i) for i in range(r))
    for n in xs + [out]:
        if ctx.spec(n) is None:
            yield n, prop


@register_rule("split")
def _rule_split(op, ctx):
    x = _first(op.input("X"))
    outs = list(op.output("Out"))
    if x is None or not outs:
        return
    r = ctx.rank(x)
    if r is None:
        return
    axis = op.attrs.get("axis", 0) % r
    known = ctx.spec(x)
    if known is None:
        for n in outs:
            s = ctx.spec(n)
            if s is not None:
                known = s
                break
    if known is None:
        return
    prop = tuple(None if i == axis else _at(known, i) for i in range(r))
    for n in [x] + outs:
        if ctx.spec(n) is None:
            yield n, prop


# ---------------------------------------------------------------------------
# embedding / norm / misc
# ---------------------------------------------------------------------------
@register_rule("lookup_table")
def _rule_lookup_table(op, ctx):
    ids = _first(op.input("Ids"))
    w = _first(op.input("W"))
    out = _first(op.output("Out"))
    if None in (ids, w, out):
        return
    ir = ctx.rank(ids)
    if ir is None:
        return
    is_, ws, os_ = ctx.spec(ids), ctx.spec(w), ctx.spec(out)
    # Out = Ids[:-1] + (D,): batch layout follows Ids, feature dim follows
    # W's column layout. A row-sharded (vocab) W contributes a psum, not an
    # output sharding — the gather result is replicated over that axis.
    if os_ is None and (is_ is not None or ws is not None):
        prop = tuple(_at(is_, i) for i in range(ir - 1)) + (_at(ws, 1),)
        yield out, prop
    if is_ is None and os_ is not None:
        yield ids, tuple(_at(os_, i) for i in range(ir - 1)) + (None,)


@register_rule("batch_norm")
def _rule_batch_norm(op, ctx):
    x = _first(op.input("X"))
    y = _first(op.output("Y"))
    if x is None or y is None:
        return
    nhwc = op.attrs.get("data_layout", "NCHW") == "NHWC"
    xs, ys = ctx.spec(x), ctx.spec(y)
    if xs is not None and ys is None:
        yield y, xs
    elif ys is not None and xs is None:
        yield x, ys
    ref = xs if xs is not None else ys
    if ref is None:
        return
    xr = ctx.rank(x) or 4
    c = _at(ref, xr - 1 if nhwc else 1)
    for slot in ("Scale", "Bias", "Mean", "Variance"):
        for n in op.input(slot):
            if ctx.spec(n) is None:
                yield n, (c,)
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        for n in op.output(slot):
            if ctx.spec(n) is None and ctx.rank(n) == 1:
                yield n, (c,)


@register_rule("fill_constant", "gaussian_random", "uniform_random",
               "fill_constant_batch_size_like", "one_hot", "shape",
               "top_k")
def _rule_fresh_replicated(op, ctx):
    # value-constructor outputs (and ops whose layout we don't model)
    # default to replicated so downstream consumers see *something*
    for slot, names in op.outputs.items():
        for n in names:
            if ctx.spec(n) is None:
                yield n, ()


# ---------------------------------------------------------------------------
# zero1 collective ops and optimizer update ops
# ---------------------------------------------------------------------------
@register_rule("zero1_scatter")
def _rule_zero1_scatter(op, ctx):
    ax = op.attrs.get("axis_name", "dp")
    for n in op.output("Out"):
        if ctx.spec(n) is None:
            yield n, (ax, None)


@register_rule("zero1_gather")
def _rule_zero1_gather(op, ctx):
    for n in op.output("Out"):
        if ctx.spec(n) is None:
            yield n, ()


def _optimizer_rule(op, ctx):
    """Shared rule for update ops: every Param-shaped slot (Grad, ParamOut,
    accumulators and their outputs) carries the Param's layout; scalar
    bookkeeping (LearningRate, beta pows) is replicated."""
    p = _first(op.input("Param"))
    if p is None:
        return
    ps = ctx.spec(p)
    psh = ctx.shape(p)
    for slots in (op.inputs, op.outputs):
        for slot, names in slots.items():
            for n in names:
                if n == p or ctx.spec(n) is not None:
                    continue
                if psh is not None and ctx.shape(n) == psh:
                    if ps is not None:
                        yield n, ps
                else:
                    yield n, ()
    if ps is not None:
        for n in op.output("ParamOut"):
            if ctx.spec(n) is None:
                yield n, ps


for _t in list(ZERO1_SHARDABLE_SLOTS) + ["ftrl", "lars_momentum"]:
    _RULES.setdefault(_t, _optimizer_rule)


# ---------------------------------------------------------------------------
# engine-level defaults for unregistered ops
# ---------------------------------------------------------------------------
def grad_mirror_rule(op, ctx):
    """Generic rule for `*_grad` ops: the default grad maker emits forward
    inputs under their original slots and gradients under `{slot}@GRAD`,
    so each grad output mirrors its forward twin's layout (the gradient
    of a var lives where the var lives). This keeps param grads aligned with
    the param's seed instead of whatever activation spec happens to reach
    the grad op first."""
    for slot, gnames in op.outputs.items():
        if not slot.endswith("@GRAD"):
            continue
        fnames = op.input(slot[: -len("@GRAD")])
        for g, f in zip(gnames, fnames):
            if ctx.shape(g) != ctx.shape(f):
                continue
            gs, fs = ctx.spec(g), ctx.spec(f)
            if fs is not None and gs is None:
                yield g, fs
            elif gs is not None and fs is None:
                yield f, gs


def default_rule(op, ctx):
    """Fallback: with exactly one output, copy the spec of a same-rank
    input (and vice versa). Conservative — rank must match exactly."""
    outs = [n for ns in op.outputs.values() for n in ns]
    if len(outs) != 1:
        return
    out = outs[0]
    orr = ctx.rank(out)
    ins = [n for ns in op.inputs.values() for n in ns]
    os_ = ctx.spec(out)
    if os_ is None:
        for n in ins:
            s = ctx.spec(n)
            if s is not None and ctx.rank(n) == orr:
                yield out, s
                return
    else:
        for n in ins:
            if ctx.spec(n) is None and ctx.rank(n) == orr:
                yield n, os_
                return
