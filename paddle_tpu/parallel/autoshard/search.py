"""Autoshard plan SEARCH: from propagate-from-seeds to pick-the-seeds.

`build_plan` answers "given these seeds, what does every var get?";
`search_plan` answers "which seeds?". It enumerates candidate seed
placements for the largest parameters across the mesh axes
(replicated, or one (dim, axis) shard per candidate), propagates each
trial to a full plan with ``build_plan``, scores whole plans with one
unified cost model, and keeps the cheapest:

    score_s = compute_s + comm_s [+ infeasible penalty]
    compute_s = sum(flops_i / shard_factor(out_i)) / peak_flops
    comm_s    = (reshard bytes + dp grad-sync bytes) / ici_bw
    penalty   = applied when analysis.hbm peak-HBM-per-replica exceeds
                the budget — infeasible plans lose to any feasible one

The manual seed set (the program's own `set_sharding` annotations) is
always evaluated first and the greedy ascent only ever accepts strict
improvements, so `search_plan(...).cost <= plan_cost(manual)` holds by
construction — green_gate asserts exactly that on the bench model.
"""

from ...core.framework import GRAD_VAR_SUFFIX
from .plan import _DTYPE_BYTES, _axes_factor, _numel
from .propagate import build_plan
from .spec import canon, normalize_spec, spec_str, validate_seed_spec

__all__ = ["plan_cost", "enumerate_seed_candidates", "search_plan",
           "SearchResult", "PEAK_FLOPS", "ICI_BYTES_PER_S"]

# nominal device constants for the analytic score: a v4-class chip and
# one ICI link. Absolute values only scale the score; plans are ranked
# by the compute/comm *ratio*, which these keep realistic.
PEAK_FLOPS = 275e12
ICI_BYTES_PER_S = 9e10
_INFEASIBLE_S = 1e9


def _param_bytes(plan, name):
    shape = plan.shapes.get(name)
    dt = plan.dtypes.get(name, "float32")
    return _numel(shape, plan.mesh_axes) * _DTYPE_BYTES.get(str(dt), 4)


def plan_cost(program, plan, batch_size=1, hbm_budget=None):
    """Score one total plan; returns a dict with `score_s` plus its
    breakdown (compute_s, comm_s, peak_hbm_bytes, feasible)."""
    # imported at call time: analysis (and transitively ops) imports the
    # parallel package, which imports this module
    from ...analysis.hbm import estimate_peak_hbm
    from ...trace.costs import op_costs

    mesh_axes = plan.mesh_axes
    compute_flops = 0.0
    for row in op_costs(program, batch_size=batch_size):
        spec = plan.spec_of(row["out"]) if row["out"] else None
        factor = _axes_factor(spec, mesh_axes) if spec else 1
        compute_flops += row["flops_est"] / max(1, factor)

    comm_bytes = plan.reshard_bytes_per_step()
    # dp gradient synchronization: any param grad NOT sharded over the
    # batch axis is all-reduced across it (ring: 2(n-1)/n x bytes)
    dp = plan.batch_axis
    n_dp = int(mesh_axes.get(dp, 1)) if dp else 1
    if n_dp > 1:
        gb = program.global_block()
        for name, v in gb.vars.items():
            if not getattr(v, "persistable", False):
                continue
            g = name + GRAD_VAR_SUFFIX
            if g not in plan.specs:
                continue
            gspec = canon(plan.spec_of(g)) or ()
            if dp in gspec:
                continue
            comm_bytes += int(2 * (n_dp - 1) / n_dp
                              * _param_bytes(plan, name))

    est = estimate_peak_hbm(program, mesh_axes=mesh_axes, aplan=plan,
                            nominal_batch=batch_size)
    peak = int(est["peak_bytes_per_replica"])
    feasible = hbm_budget is None or peak <= int(hbm_budget)

    compute_s = compute_flops / PEAK_FLOPS
    comm_s = comm_bytes / ICI_BYTES_PER_S
    score = compute_s + comm_s + (0.0 if feasible else _INFEASIBLE_S)
    return {
        "score_s": score,
        "compute_s": compute_s,
        "comm_s": comm_s,
        "compute_flops": compute_flops,
        "comm_bytes": int(comm_bytes),
        "peak_hbm_bytes": peak,
        "feasible": feasible,
        "digest": plan.digest(),
    }


def enumerate_seed_candidates(program, mesh_axes, batch_axis="dp",
                              max_params=8, min_bytes=1 << 10):
    """{param name: [candidate specs]} for the largest `max_params`
    parameters: replicated plus every valid single-(dim, axis) shard
    over the non-batch mesh axes (the batch axis stays the data axis;
    sharding weights over it is zero1's job, not the plan search's)."""
    mesh_axes = {str(k): int(v) for k, v in dict(mesh_axes).items()}
    gb = program.global_block()
    params = []
    for name, v in gb.vars.items():
        if not getattr(v, "persistable", False) or not v.shape:
            continue
        if any(d is None or int(d) < 0 for d in v.shape):
            continue
        nbytes = _numel(tuple(v.shape), mesh_axes) * 4
        if nbytes >= min_bytes:
            params.append((nbytes, name, tuple(v.shape)))
    params.sort(key=lambda t: (-t[0], t[1]))

    out = {}
    axes = [a for a in mesh_axes if a != batch_axis and mesh_axes[a] > 1]
    for _, name, shape in params[:max_params]:
        cands = [()]
        for ax in axes:
            for d in range(len(shape)):
                spec = (None,) * d + (ax,)
                try:
                    validate_seed_spec(name, spec, shape, mesh_axes)
                except ValueError:
                    continue
                cands.append(spec)
        out[name] = cands
    return out


class SearchResult:
    __slots__ = ("plan", "seeds", "cost", "manual_cost", "evaluated",
                 "improved", "trace")

    def __init__(self, plan, seeds, cost, manual_cost, evaluated, trace):
        self.plan = plan
        self.seeds = seeds
        self.cost = cost
        self.manual_cost = manual_cost
        self.evaluated = evaluated
        self.improved = cost["score_s"] < manual_cost["score_s"]
        self.trace = trace

    def to_dict(self):
        return {
            "seeds": {n: list(s) for n, s in sorted(self.seeds.items())},
            "cost": dict(self.cost),
            "manual_cost": dict(self.manual_cost),
            "evaluated": self.evaluated,
            "improved": self.improved,
            "digest": self.plan.digest(),
            "mesh_axes": dict(self.plan.mesh_axes),
            "trace": list(self.trace),
        }

    def render(self):
        c, m = self.cost, self.manual_cost
        lines = [
            f"autoshard search  mesh["
            + "×".join(f"{k}={v}"
                       for k, v in self.plan.mesh_axes.items())
            + f"]  {self.evaluated} plans evaluated",
            f"  manual   score {m['score_s']:.3e} s  "
            f"(compute {m['compute_s']:.3e}  comm {m['comm_s']:.3e}  "
            f"hbm {m['peak_hbm_bytes'] / 1e6:.1f} MB"
            + ("" if m["feasible"] else "  INFEASIBLE") + ")",
            f"  searched score {c['score_s']:.3e} s  "
            f"(compute {c['compute_s']:.3e}  comm {c['comm_s']:.3e}  "
            f"hbm {c['peak_hbm_bytes'] / 1e6:.1f} MB"
            + ("" if c["feasible"] else "  INFEASIBLE") + ")",
        ]
        if self.seeds:
            for n, s in sorted(self.seeds.items()):
                lines.append(f"  seed {n}: {spec_str(s)}")
        else:
            lines.append("  seed set: empty (pure batch-axis plan)")
        lines.append(f"  improved={self.improved}  "
                     f"digest {self.plan.digest()}")
        return "\n".join(lines)


def search_plan(program, mesh_axes, batch_axis="dp", batch_size=1,
                hbm_budget=None, max_params=8, rounds=2):
    """Greedy coordinate-descent over seed placements.

    Starts from the program's own annotations (the manual plan), then
    per parameter (largest first) tries every candidate spec while the
    others stay fixed, accepting strict score improvements; repeats up
    to `rounds` passes or until a pass changes nothing."""
    mesh_axes = {str(k): int(v) for k, v in dict(mesh_axes).items()}

    manual_seeds = {}
    for name, v in program.global_block().vars.items():
        s = getattr(v, "sharding", None)
        if s is not None:
            manual_seeds[name] = canon(normalize_spec(s)) or ()

    def evaluate(seeds):
        plan = build_plan(program, mesh_axes, batch_axis=batch_axis,
                          extra_seeds={n: s for n, s in seeds.items() if s},
                          ignore_program_seeds=True)
        return plan, plan_cost(program, plan, batch_size=batch_size,
                               hbm_budget=hbm_budget)

    best_seeds = dict(manual_seeds)
    best_plan, manual_cost = evaluate(best_seeds)
    best_cost = manual_cost
    evaluated = 1
    trace = [{"seeds": dict(best_seeds),
              "score_s": best_cost["score_s"], "kept": True}]

    candidates = enumerate_seed_candidates(
        program, mesh_axes, batch_axis=batch_axis, max_params=max_params)
    for _ in range(max(1, int(rounds))):
        changed = False
        for name, cands in candidates.items():
            for spec in cands:
                spec = canon(spec) or ()
                if best_seeds.get(name, ()) == spec:
                    continue
                trial = dict(best_seeds)
                if spec:
                    trial[name] = spec
                else:
                    trial.pop(name, None)
                plan, cost = evaluate(trial)
                evaluated += 1
                kept = cost["score_s"] < best_cost["score_s"]
                trace.append({"var": name, "spec": list(spec),
                              "score_s": cost["score_s"], "kept": kept})
                if kept:
                    best_seeds, best_plan, best_cost = trial, plan, cost
                    changed = True
        if not changed:
            break
    return SearchResult(best_plan, best_seeds, best_cost, manual_cost,
                        evaluated, trace)
