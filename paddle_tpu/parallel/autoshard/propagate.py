"""GSPMD-style sharding propagation over a Program.

The fixpoint sweeps the global block with while/cond sub-block ops
folded inline (their def-use summarized onto the parent walk, like
analysis.dataflow does), so specs flow into loop bodies and back out
through escaping writes.

Seeds (user `parallel.set_sharding` annotations, plus the batch axis on
data vars) are pushed through the op graph by the per-op rules in
rules.py, forward and backward, until a fixpoint. Gradient vars are
linked to their forward vars through backward.py's naming convention
(`X@GRAD`, including the `@GRAD@RENAME@...` fresh names), so one seed on
a parameter lands on its grad and optimizer slots too. Conflicting
proposals are arbitrated once per var with the analytic collective-bytes
model in plan.py and then locked; later disagreeing proposals become
recorded reshard edges. Finalization assigns `()` (replicated) to
everything still unknown, so the resulting plan is always *total*.
"""

from ... import flags
from ...core.framework import GRAD_VAR_SUFFIX
from ...backward import _strip_grad_suffix
from .plan import (ShardingPlan, transition_bytes, _axes_factor,
                   SRC_SEED, SRC_FEED, SRC_DERIVED, SRC_GRAD,
                   SRC_RESOLVED, SRC_DEFAULT, _PRIORITY)
from .rules import rule_for, default_rule, grad_mirror_rule
from .spec import normalize_spec, canon, validate_seed_spec

__all__ = ["build_plan", "validate_seeds", "register_plan",
           "active_plan", "reset_registry", "manifest_section"]

flags.define(
    "autoshard", bool, False,
    "Propagate sharding seeds over the whole Program and lower the plan "
    "as with_sharding_constraint in the compiled step "
    "(BuildStrategy.auto_sharding overrides per-executor).")

_MAX_ITERS = 64


class _Ctx:
    """Read-only view the rules use."""

    __slots__ = ("_specs", "_shapes", "mesh_axes")

    def __init__(self, specs, shapes, mesh_axes):
        self._specs = specs
        self._shapes = shapes
        self.mesh_axes = mesh_axes

    def spec(self, name):
        st = self._specs.get(name)
        return None if st is None else st[0]

    def shape(self, name):
        return self._shapes.get(name)

    def rank(self, name):
        s = self._shapes.get(name)
        return None if s is None else len(s)


def validate_seeds(program, mesh_axes):
    """Validate every `set_sharding` annotation in `program` against the
    mesh. Raises ValueError (naming the var, the spec, and the mesh axes)
    at plan-construction/compile time rather than deep inside
    _state_sharding at run time."""
    mesh_axes = dict(mesh_axes)
    for name, v in program.global_block().vars.items():
        s = getattr(v, "sharding", None)
        if s is None:
            continue
        s = normalize_spec(s)
        validate_seed_spec(name, s, v.shape, mesh_axes)


def build_plan(program, mesh_axes, batch_axis="dp", extra_seeds=None,
               ignore_program_seeds=False):
    """Produce a total ShardingPlan for `program` on a {axis: size} mesh.

    `extra_seeds` ({name: spec}) adds seeds without mutating the program
    (used by the CLI; program annotations still win on collision).
    `ignore_program_seeds` drops the program's own `set_sharding`
    annotations so `extra_seeds` fully define the seeding — the search in
    search.py uses this to evaluate candidate placements side by side.
    Raises ValueError on invalid seeds."""
    mesh_axes = {str(k): int(v) for k, v in dict(mesh_axes).items()}
    block = program.global_block()
    plan = ShardingPlan(mesh_axes, batch_axis=batch_axis)

    # Register vars and flatten ops across while/cond sub-blocks: the
    # sweep visits sub-block ops inline, right after the op that owns
    # them (the way dataflow._summarize_sub folds their def-use onto the
    # parent node), so a loop body reading a sharded param propagates
    # specs through body-locals and back out via escaping writes. Names
    # the sub-block does NOT redeclare resolve to the parent var, which
    # is already registered — parent entries win on collision.
    def _register_vars(blk):
        for name, v in blk.vars.items():
            if name in plan.specs:
                continue
            plan.shapes[name] = None if v.shape is None else tuple(v.shape)
            plan.dtypes[name] = str(getattr(v, "dtype", "float32"))
            plan.specs[name] = None

    def _flatten_ops(blk, into):
        for op in blk.ops:
            into.append(op)
            for a in op.attrs.values():
                if hasattr(a, "ops") and hasattr(a, "vars"):
                    _register_vars(a)
                    _flatten_ops(a, into)

    _register_vars(block)

    state = {}  # name -> (canonical spec, source)

    def assign(name, spec, src):
        state[name] = (canon(spec), src)

    seen_edges = set()

    def offer(name, spec, src, via):
        """Propose `spec` for `name`; returns True if the assignment
        changed. Locked entries (seeds, feeds, resolved conflicts) never
        change — disagreement is recorded as a reshard edge instead."""
        if name not in plan.specs:
            return False
        spec = canon(spec)
        cur = state.get(name)
        if cur is None:
            assign(name, spec, src)
            return True
        cur_spec, cur_src = cur
        if cur_spec == spec:
            return False
        shape = plan.shapes.get(name)
        dtype = plan.dtypes.get(name, "float32")
        cost_in = transition_bytes(shape, dtype, spec, cur_spec, mesh_axes)
        if _PRIORITY[cur_src] >= _PRIORITY[SRC_RESOLVED]:
            edge = (name, spec)
            if edge not in seen_edges:
                seen_edges.add(edge)
                plan.reshard_edges.append({
                    "var": name, "src": spec, "dst": cur_spec,
                    "op": via, "bytes": cost_in})
            return False
        # derived-vs-derived: arbitrate once with the cost model, lock
        cost_out = transition_bytes(shape, dtype, cur_spec, spec, mesh_axes)
        if cost_out < cost_in:
            kept, dropped, cost = spec, cur_spec, cost_out
        elif cost_in < cost_out:
            kept, dropped, cost = cur_spec, spec, cost_in
        else:  # tie: prefer the more-sharded layout (less resident memory)
            if _axes_factor(spec, mesh_axes) > \
                    _axes_factor(cur_spec, mesh_axes):
                kept, dropped, cost = spec, cur_spec, cost_out
            else:
                kept, dropped, cost = cur_spec, spec, cost_in
        plan.conflicts.append({
            "var": name, "kept": kept, "dropped": dropped,
            "op": via, "reshard_bytes": cost})
        changed = kept != cur_spec
        assign(name, kept, SRC_RESOLVED)
        return changed

    # -- seeds ------------------------------------------------------------
    seeds = {}
    if not ignore_program_seeds:
        for name, v in block.vars.items():
            s = getattr(v, "sharding", None)
            if s is not None:
                seeds[name] = s
    for name, s in dict(extra_seeds or {}).items():
        seeds.setdefault(name, s)
    for name, s in seeds.items():
        s = normalize_spec(s)
        shape = plan.shapes.get(name)
        validate_seed_spec(name, s, shape, mesh_axes)
        assign(name, s, SRC_SEED)
    if batch_axis and batch_axis in mesh_axes:
        for name, v in block.vars.items():
            if v.is_data and name not in seeds and \
                    plan.shapes.get(name):
                assign(name, (batch_axis,), SRC_FEED)

    # -- fixpoint ---------------------------------------------------------
    ops = []
    _flatten_ops(block, ops)
    ctx = _Ctx(state, plan.shapes, mesh_axes)
    grad_names = [n for n in plan.specs if GRAD_VAR_SUFFIX in n]

    def sweep(op_seq):
        changed = False
        for op in op_seq:
            rule = rule_for(op.type)
            if rule is None:
                # grad ops mirror their forward twins; guessing with the
                # generic same-rank copy there picks arbitrary inputs
                rule = grad_mirror_rule if op.type.endswith("_grad") \
                    else default_rule
            for name, spec in (rule(op, ctx) or ()):
                changed |= offer(name, spec, SRC_DERIVED, op.type)
        return changed

    def link_grads():
        changed = False
        for g in grad_names:
            f = _strip_grad_suffix(g)
            if f not in plan.specs or \
                    plan.shapes.get(f) != plan.shapes.get(g):
                continue  # only link same-shape pairs (sum'd renames etc.)
            gs, fs = ctx.spec(g), ctx.spec(f)
            if fs is not None and gs is None:
                changed |= offer(g, fs, SRC_GRAD, "grad-link")
            elif gs is not None and fs is None:
                changed |= offer(f, gs, SRC_GRAD, "grad-link")
        return changed

    for it in range(_MAX_ITERS):
        changed = link_grads()  # before the sweeps: seeds reach grads first
        changed |= sweep(ops)
        changed |= sweep(reversed(ops))
        changed |= link_grads()
        plan.iterations = it + 1
        if not changed:
            break

    # -- finalize: total plan ---------------------------------------------
    for name in plan.specs:
        st = state.get(name)
        if st is None:
            plan.specs[name] = ()
            plan.sources[name] = SRC_DEFAULT
        else:
            plan.specs[name] = st[0]
            plan.sources[name] = st[1]
    return plan


# ---------------------------------------------------------------------------
# process-wide registry: resilience.checkpoint records the active plan's
# digest + param layouts in manifest.json (mirrors zero1's contract —
# snapshots are always written in full/unsharded layout, so restores are
# layout-independent and the manifest section is purely descriptive)
# ---------------------------------------------------------------------------
_ACTIVE_PLAN = None


def register_plan(plan):
    global _ACTIVE_PLAN
    _ACTIVE_PLAN = plan


def active_plan():
    return _ACTIVE_PLAN


def reset_registry():
    global _ACTIVE_PLAN
    _ACTIVE_PLAN = None


def manifest_section(snapshot_names):
    """Manifest entry for a checkpoint covering `snapshot_names`, or None
    when no autoshard plan is active or none of the saved vars are in it."""
    p = _ACTIVE_PLAN
    if p is None:
        return None
    names = [n for n in snapshot_names if n in p.specs]
    if not names:
        return None
    return {
        "digest": p.digest(),
        "mesh_axes": dict(p.mesh_axes),
        "layout": "full",
        "params": {n: list(canon(p.spec_of(n)) or ())
                   for n in names if canon(p.spec_of(n))},
    }
