"""ShardingPlan: the total var -> PartitionSpec assignment plus the
bookkeeping the rest of the stack consumes — conflict/reshard edges with
an analytic collective-bytes estimate (same ring model as
zero1.Zero1Plan.collective_bytes), a stable digest for compile-cache keys
and checkpoint manifests, and the boundary set lowered to
with_sharding_constraint in the compiled step fn.

mesh_axes is a plain {axis_name: size} dict — not a jax Mesh — so plans
can be built and rendered (CLI `shard plan`) on hosts with one device.
"""

import hashlib
import json

from .spec import canon, spec_str

__all__ = ["ShardingPlan", "transition_bytes"]

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
}

# spec assignment sources, in increasing override priority
SRC_DEFAULT = "default"        # finalize(): nothing reached it -> replicated
SRC_DERIVED = "derived"        # produced by a propagation rule
SRC_GRAD = "grad-link"         # copied across the fwd/grad var linkage
SRC_RESOLVED = "conflict"      # winner of a cost-arbitrated conflict
SRC_FEED = "feed"              # batch-axis seed on a data var
SRC_SEED = "seed"              # user annotation (locked)

_PRIORITY = {SRC_DEFAULT: 0, SRC_DERIVED: 1, SRC_GRAD: 1,
             SRC_RESOLVED: 2, SRC_FEED: 3, SRC_SEED: 4}


def _axes_factor(spec, mesh_axes):
    n = 1
    for ax in canon(spec) or ():
        if ax is not None:
            n *= int(mesh_axes.get(ax, 1))
    return n


def _numel(shape, mesh_axes):
    """Static element count; dynamic dims substitute the mesh device count
    as a nominal per-axis batch so estimates stay comparable across vars."""
    if not shape:
        return 1
    nominal = 1
    for s in mesh_axes.values():
        nominal *= int(s)
    n = 1
    for d in shape:
        d = -1 if d is None else int(d)
        n *= nominal if d < 0 else d
    return n


def transition_bytes(shape, dtype, src_spec, dst_spec, mesh_axes):
    """Estimated per-device ring-collective bytes to move one array from
    layout src_spec to dst_spec (zero1's model: all_gather and
    reduce_scatter cost (N-1)/N * bytes; a slice of a replicated array is
    free; mixed resharding is approximated as an all-to-all at the same
    (N-1)/N rate over the union of the involved axes)."""
    a, b = canon(src_spec) or (), canon(dst_spec) or ()
    if a == b:
        return 0
    itot = _numel(shape, mesh_axes) * _DTYPE_BYTES.get(str(dtype), 4)
    if not a:
        return 0  # replicated -> sharded: local slice, no comms
    axes = {ax for ax in a + b if ax is not None}
    n = 1
    for ax in axes:
        n *= int(mesh_axes.get(ax, 1))
    if n <= 1:
        return 0
    return int(itot * (n - 1) / n)


class ShardingPlan:
    def __init__(self, mesh_axes, batch_axis=None):
        self.mesh_axes = {str(k): int(v) for k, v in dict(mesh_axes).items()}
        self.batch_axis = batch_axis
        self.specs = {}        # name -> canonical spec tuple
        self.sources = {}      # name -> SRC_* tag
        self.shapes = {}       # name -> static shape tuple (or None)
        self.dtypes = {}       # name -> dtype string
        self.conflicts = []    # resolved conflicts (dicts)
        self.reshard_edges = []  # forced layout changes (dicts)
        self.unresolved = []   # locked-vs-locked contradictions (names)
        self.iterations = 0

    # -- queries ----------------------------------------------------------
    def spec_of(self, name):
        return self.specs.get(name)

    def source_of(self, name):
        return self.sources.get(name, SRC_DEFAULT)

    def is_total(self):
        return not self.unresolved and all(
            s is not None for s in self.specs.values())

    def sharded_names(self):
        return {n for n, s in self.specs.items() if canon(s)}

    def boundary_specs(self):
        """{name: spec} for vars that get a with_sharding_constraint —
        only genuinely sharded vars; replicated ones are left to XLA."""
        return {n: s for n, s in self.specs.items() if canon(s)}

    def reshard_bytes_per_step(self):
        return sum(int(e.get("bytes", 0)) for e in self.reshard_edges) + \
            sum(int(c.get("reshard_bytes", 0)) for c in self.conflicts)

    # -- identity ---------------------------------------------------------
    def digest(self):
        body = {
            "mesh": sorted(self.mesh_axes.items()),
            "specs": sorted((n, list(canon(s) or ()))
                            for n, s in self.specs.items()),
        }
        blob = json.dumps(body, sort_keys=True).encode()
        return hashlib.sha1(blob).hexdigest()[:16]

    # -- reporting --------------------------------------------------------
    def describe(self):
        return {
            "mesh_axes": dict(self.mesh_axes),
            "digest": self.digest(),
            "n_vars": len(self.specs),
            "n_sharded": len(self.sharded_names()),
            "n_conflicts": len(self.conflicts),
            "n_reshard_edges": len(self.reshard_edges),
            "unresolved": list(self.unresolved),
            "total": self.is_total(),
            "iterations": self.iterations,
            "reshard_bytes_per_step": self.reshard_bytes_per_step(),
            "specs": {n: list(canon(s) or ())
                      for n, s in sorted(self.specs.items())},
            "sources": dict(sorted(self.sources.items())),
            "conflicts": list(self.conflicts),
            "reshard_edges": list(self.reshard_edges),
        }

    def render(self, verbose=True):
        mesh = "×".join(f"{k}={v}" for k, v in self.mesh_axes.items())
        lines = [f"autoshard plan  mesh[{mesh}]  digest {self.digest()}",
                 f"  vars {len(self.specs)}  sharded "
                 f"{len(self.sharded_names())}  conflicts "
                 f"{len(self.conflicts)}  reshard "
                 f"~{self.reshard_bytes_per_step()} B/step  "
                 f"total={self.is_total()}"]
        if verbose:
            w = max((len(n) for n in self.specs), default=4)
            for n in sorted(self.specs):
                shp = self.shapes.get(n)
                shp = "?" if shp is None else str(tuple(shp))
                lines.append(
                    f"  {n:<{w}}  {shp:<18} "
                    f"{spec_str(self.specs[n]):<16} "
                    f"[{self.source_of(n)}]")
        for c in self.conflicts:
            lines.append(
                f"  conflict {c['var']}: kept {spec_str(c['kept'])} "
                f"over {spec_str(c['dropped'])} (op {c.get('op')}, "
                f"~{c.get('reshard_bytes', 0)} B)")
        for e in self.reshard_edges:
            lines.append(
                f"  reshard  {e['var']}: {spec_str(e['src'])} -> "
                f"{spec_str(e['dst'])} (op {e.get('op')}, "
                f"~{e.get('bytes', 0)} B)")
        for n in self.unresolved:
            lines.append(f"  UNRESOLVED {n}")
        return "\n".join(lines)
