"""Variable-transport RPC runtime: the gRPC-runtime equivalent.

Reference parity: paddle/fluid/operators/detail/ (grpc_client.h:164
AsyncSendVariable/AsyncGetVariable + batch/fetch barriers :136-160,
grpc_server.h:47 AsyncGRPCServer, protocol send_recv.proto:17
SendVariable/GetVariable) and listen_and_serv_op.cc's sync update loop.

Transport: length-prefixed pickled messages over TCP sockets (the reference's
legacy LightNetwork.h:40 style, with send_recv.proto's message surface).
Variables serialize as (numpy bytes, dtype, shape, lod). The server mirrors
RunSyncUpdate: collect grads from all trainers -> barrier -> run per-param
optimize blocks -> serve params until fetch barrier.

Port discovery: server writes /tmp/paddle.<pid>.port once bound (reference
listen_and_serv_op.cc SavePort), so tests can fork a pserver and find it.
"""

import os
import pickle
import socket
import struct
import threading

import numpy as np

__all__ = ["VariableClient", "VariableServer", "serialize_var",
           "deserialize_var", "RpcError", "dial"]


def dial(endpoint, timeout):
    """Connect to a "host:port" endpoint: the one reconnect primitive the
    control-plane clients (MasterClient re-dial-per-retry, VariableClient,
    the fleet router's probes) share. Connect is bounded by `timeout`;
    the returned socket is blocking thereafter — a sync-mode get
    legitimately waits for the slowest trainer's round (e.g. first-step
    XLA compile can exceed any fixed timeout)."""
    host, port = endpoint.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.settimeout(None)
    return sock


class RpcError(RuntimeError):
    """Typed failure from the variable server (reference PADDLE_ENFORCE on
    gRPC statuses)."""


def _check_ok(resp, what):
    if resp != ("ok",):
        detail = resp[1] if isinstance(resp, tuple) and len(resp) > 1 else resp
        raise RpcError(f"{what} failed: {detail}")

_MAGIC = b"PTRV"


def dump_crc_blob(path, obj):
    """Atomically persist `obj` as CRC32-prefixed pickle (tmp + rename) —
    the snapshot framing shared by the master service and the pserver
    checkpointer (reference guards both with CRC32 too,
    go/pserver/service.go:190)."""
    import zlib

    payload = pickle.dumps(obj, protocol=4)
    blob = zlib.crc32(payload).to_bytes(4, "big") + payload
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())  # rename-before-data after power loss = torn file
    os.replace(tmp, path)
    # fsync the parent so the RENAME itself survives power loss (else the
    # dir entry may still point at the old blob — or nothing — on replay)
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def load_crc_blob(path):
    import zlib

    with open(path, "rb") as f:
        blob = f.read()
    crc, payload = blob[:4], blob[4:]
    if zlib.crc32(payload).to_bytes(4, "big") != crc:
        raise IOError(f"corrupt snapshot/checkpoint {path!r}")
    return pickle.loads(payload)


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=4)
    sock.sendall(_MAGIC + struct.pack(">Q", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock):
    hdr = _recv_exact(sock, 12)
    if hdr[:4] != _MAGIC:
        raise ConnectionError("bad frame magic")
    (ln,) = struct.unpack(">Q", hdr[4:])
    return pickle.loads(_recv_exact(sock, ln))


def serialize_var(value):
    """LoDTensor / SelectedRows / numpy / jax array -> wire dict
    (send_recv.proto VariableMessage: dims, lod, serialized bytes; the
    SelectedRows kind carries rows + height like the reference's
    SelectedRows message)."""
    from ..core.lod_tensor import LoDTensor
    from ..core.selected_rows import SelectedRows

    if isinstance(value, SelectedRows):
        rows = np.asarray(value.rows).reshape(-1).astype(np.int64)
        vals = np.asarray(value.values)
        return {"kind": "selected_rows", "data": vals.tobytes(),
                "dtype": str(vals.dtype), "shape": vals.shape,
                "rows": rows.tobytes(), "height": value.height, "lod": []}
    if isinstance(value, LoDTensor):
        arr = np.asarray(value.numpy())
        return {"kind": "lod_tensor", "data": arr.tobytes(),
                "dtype": str(arr.dtype), "shape": arr.shape,
                "lod": value.lod()}
    arr = np.asarray(value)
    return {"kind": "tensor", "data": arr.tobytes(),
            "dtype": str(arr.dtype), "shape": arr.shape, "lod": []}


def deserialize_var(msg):
    from ..core.lod_tensor import LoDTensor
    from ..core.selected_rows import SelectedRows

    arr = np.frombuffer(
        msg["data"], dtype=np.dtype(msg["dtype"])).reshape(msg["shape"])
    if msg["kind"] == "selected_rows":
        rows = np.frombuffer(msg["rows"], dtype=np.int64)
        return SelectedRows(rows.copy(), arr.copy(), msg["height"])
    if msg["kind"] == "lod_tensor" and msg["lod"]:
        return LoDTensor(arr.copy(), msg["lod"])
    return arr.copy()


class VariableClient:
    """Per-endpoint connection (reference RPCClient, grpc_client.h:164)."""

    def __init__(self, endpoint, connect_timeout=60.0):
        self._sock = dial(endpoint, connect_timeout)

    def send_var(self, name, value):
        _send_msg(self._sock, ("send", name, serialize_var(value)))
        _check_ok(_recv_msg(self._sock), f"send_var({name})")

    def get_var(self, name):
        _send_msg(self._sock, ("get", name))
        resp = _recv_msg(self._sock)
        tag, payload = resp[0], resp[1]
        if tag == "err":
            raise RpcError(f"get_var({name}) failed: {payload}")
        return deserialize_var(payload)

    def prefetch(self, table_name, ids):
        """reference grpc_client.h AsyncPrefetchVariable: send lookup ids,
        receive the table rows (served by the pserver's prefetch block)."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        _send_msg(self._sock, ("prefetch", table_name, serialize_var(ids)))
        resp = _recv_msg(self._sock)
        if resp[0] == "err":
            raise RpcError(f"prefetch({table_name}) failed: {resp[1]}")
        return deserialize_var(resp[1])

    def batch_barrier(self):
        """reference BATCH_BARRIER_MESSAGE after grads sent."""
        _send_msg(self._sock, ("batch_barrier",))
        _check_ok(_recv_msg(self._sock), "batch_barrier")

    def fetch_barrier(self):
        """reference FETCH_BARRIER_MESSAGE after params fetched."""
        _send_msg(self._sock, ("fetch_barrier",))
        _check_ok(_recv_msg(self._sock), "fetch_barrier")

    def shutdown(self):
        try:
            _send_msg(self._sock, ("exit",))
        except OSError:
            pass
        self._sock.close()


class VariableServer:
    """Sync-update variable server (reference AsyncGRPCServer +
    listen_and_serv_op RunSyncLoop).

    on_round(recv_names) is invoked once all `num_trainers` batch barriers
    arrive; it should run the optimize blocks against the owning scope. Gets
    are served only between on_round completion and the fetch barriers
    (sync semantics)."""

    def __init__(self, bind="127.0.0.1:0", num_trainers=1, get_var=None,
                 put_var=None, on_round=None, sync_mode=True, on_grad=None,
                 on_prefetch=None):
        host, port = bind.rsplit(":", 1)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self.num_trainers = num_trainers
        self.sync_mode = sync_mode
        self._get_var = get_var
        self._put_var = put_var
        self._on_round = on_round
        self._on_grad = on_grad  # async mode: per-grad update callback
        self._on_prefetch = on_prefetch  # (table_name, ids) -> rows
        self._lock = threading.Condition()
        self._batch_count = 0
        self._fetch_count = 0
        self._round_done = not sync_mode
        self._received = []
        self._stop = False
        self._threads = []

    def save_port(self, path=None):
        path = path or f"/tmp/paddle.{os.getpid()}.port"
        with open(path, "w") as f:
            f.write(str(self.port))
        return path

    # ------------------------------------------------------------------
    def serve_forever(self):
        """Accept loop; one thread per connection (reference grpc_server
        thread pools)."""
        accept_thread = threading.Thread(target=self._accept_loop,
                                         daemon=True)
        accept_thread.start()
        with self._lock:
            while not self._stop:
                self._lock.wait(0.1)
        self._listener.close()

    def start(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def stop(self):
        with self._lock:
            self._stop = True
            self._lock.notify_all()

    def _accept_loop(self):
        while not self._stop:
            try:
                self._listener.settimeout(0.2)
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    # ------------------------------------------------------------------
    def _serve_conn(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                op = msg[0]
                if op == "send":
                    _, name, payload = msg
                    value = deserialize_var(payload)
                    with self._lock:
                        self._received.append(name)
                    if self._put_var:
                        self._put_var(name, value)
                    if not self.sync_mode and self._on_grad:
                        # async update (reference async_update.md design):
                        # run this grad's optimize block immediately
                        with self._lock:
                            self._on_grad(name)
                    _send_msg(conn, ("ok",))
                elif op == "batch_barrier":
                    self._handle_batch_barrier()
                    _send_msg(conn, ("ok",))
                elif op == "get":
                    _, name = msg
                    with self._lock:
                        while self.sync_mode and not self._round_done \
                                and not self._stop:
                            self._lock.wait(0.1)
                    try:
                        value = self._get_var(name)
                        _send_msg(conn, ("var", serialize_var(value)))
                    except KeyError as e:
                        _send_msg(conn, ("err", str(e)))
                elif op == "prefetch":
                    # served at any time (reference prefetch runs outside
                    # the sync round: lookups are read-mostly and the table
                    # grows on first touch)
                    _, table_name, payload = msg
                    if self._on_prefetch is None:
                        _send_msg(conn, ("err", "no prefetch handler"))
                    else:
                        ids = deserialize_var(payload)
                        with self._lock:
                            rows = self._on_prefetch(table_name, ids)
                        _send_msg(conn, ("rows", serialize_var(rows)))
                elif op == "fetch_barrier":
                    self._handle_fetch_barrier()
                    _send_msg(conn, ("ok",))
                elif op == "exit":
                    self.stop()
                    return
        except (ConnectionError, EOFError, OSError):
            return
        except Exception:
            import traceback
            traceback.print_exc()
            try:
                _send_msg(conn, ("err", "server error; see pserver log"))
            except OSError:
                pass
            return

    def _handle_batch_barrier(self):
        with self._lock:
            self._batch_count += 1
            if self._batch_count >= self.num_trainers:
                received, self._received = self._received, []
                self._batch_count = 0
                if self._on_round:
                    self._on_round(received)
                self._round_done = True
                self._lock.notify_all()
            else:
                while self._batch_count != 0 and not self._stop:
                    self._lock.wait(0.1)

    def _handle_fetch_barrier(self):
        with self._lock:
            self._fetch_count += 1
            if self._fetch_count >= self.num_trainers:
                self._fetch_count = 0
                if self.sync_mode:
                    self._round_done = False
                self._lock.notify_all()
            else:
                while self._fetch_count != 0 and not self._stop:
                    self._lock.wait(0.1)
