"""Mesh-sharded embedding: the collective counterpart of the pserver
distributed lookup table.

Reference contrast: the reference's only sharded-embedding path is the
pserver prefetch RPC (distribute_transpiler.py:624, operators/prefetch_op.cc)
— host round-trips per lookup. On TPU the idiomatic form keeps the table
row-sharded across the mesh in HBM and resolves lookups with one psum over
ICI: every device gathers the ids that fall in its row range (masked local
gather) and the psum assembles full rows everywhere. The gradient is the
transpose (masked local scatter-add), which jax derives automatically, so a
training step over a sharded table needs no hand-written backward.

All functions are shard_map-based and jit/pjit compatible.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["shard_table", "sharded_embedding_lookup"]


def shard_table(table, mesh, axis="mp"):
    """Place a [V, D] table row-sharded over mesh axis `axis` (V must divide
    evenly; pad the vocab up like every TP implementation does)."""
    nshards = mesh.shape[axis]
    assert table.shape[0] % nshards == 0, (
        f"vocab {table.shape[0]} not divisible by {nshards} shards; pad it")
    return jax.device_put(
        table, jax.sharding.NamedSharding(mesh, P(axis, None)))


def _local_lookup(table_shard, ids, axis, nshards, vocab):
    rows_per = vocab // nshards
    start = jax.lax.axis_index(axis) * rows_per
    local = ids - start
    ok = (local >= 0) & (local < rows_per)
    rows = jnp.take(table_shard, jnp.clip(local, 0, rows_per - 1), axis=0)
    rows = jnp.where(ok[..., None], rows, jnp.zeros((), rows.dtype))
    return jax.lax.psum(rows, axis)


def sharded_embedding_lookup(table, ids, mesh, axis="mp"):
    """ids [...] int -> rows [..., D]; `table` [V, D] sharded on rows over
    `axis` (see shard_table). Exact match with jnp.take on the unsharded
    table; differentiable through the table operand."""
    nshards = mesh.shape[axis]
    vocab = table.shape[0]
    fn = jax.shard_map(
        functools.partial(_local_lookup, axis=axis, nshards=nshards,
                          vocab=vocab),
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(table, ids)
