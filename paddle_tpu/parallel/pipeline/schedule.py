"""1F1B microbatch schedule: event order, analytic bound, simulator.

The schedule is PipeDream-flush style 1F1B: stage s warms up with
``min(m, p-1-s)`` forwards, then alternates one-forward-one-backward
until all m backwards are done, then drains. With equal-cost stages the
bubble (idle) fraction of the steady step is exactly

    bubble = (p - 1) / (m + p - 1)

which the runner reports alongside the fraction it actually measured.
"""

__all__ = ["analytic_bubble", "schedule_1f1b", "simulate_schedule"]


def analytic_bubble(n_stages, n_microbatches):
    """Ideal 1F1B bubble fraction (p-1)/(m+p-1) for equal-cost stages."""
    p, m = int(n_stages), int(n_microbatches)
    if p <= 1:
        return 0.0
    return (p - 1) / float(m + p - 1)


def schedule_1f1b(n_stages, n_microbatches):
    """Per-stage event lists: [("F"|"B", microbatch), ...] per stage.

    Stage s runs min(m, p-1-s) warm-up forwards, then strictly
    alternates F/B (one-forward-one-backward) until every microbatch's
    backward has run."""
    p, m = int(n_stages), int(n_microbatches)
    if p < 1 or m < 1:
        raise ValueError(f"need n_stages>=1, n_microbatches>=1 "
                         f"(got {p}, {m})")
    events = []
    for s in range(p):
        warm = min(m, p - 1 - s)
        ev = [("F", mb) for mb in range(warm)]
        nf, nb = warm, 0
        while nb < m:
            if nf < m:
                ev.append(("F", nf))
                nf += 1
            ev.append(("B", nb))
            nb += 1
        events.append(ev)
    return events


def simulate_schedule(events, durations=None):
    """Earliest-start simulation of per-stage event lists.

    `durations`: {("F"|"B", stage): seconds} or None for unit costs.
    Dependencies: F(s, mb) needs F(s-1, mb); B(s, mb) needs B(s+1, mb)
    and F(s, mb); each stage runs its own events serially in list order.
    Returns {makespan, busy (per stage), bubble_fraction}."""
    p = len(events)
    if durations is None:
        durations = {}
    done = {}    # (kind, stage, mb) -> finish time
    busy = [0.0] * p
    pos = [0] * p
    prev_end = [0.0] * p
    total = sum(len(ev) for ev in events)
    ran = 0
    while ran < total:
        progressed = False
        for s in range(p):
            if pos[s] >= len(events[s]):
                continue
            kind, mb = events[s][pos[s]]
            deps = []
            if kind == "F" and s > 0:
                deps.append(("F", s - 1, mb))
            if kind == "B":
                if s < p - 1:
                    deps.append(("B", s + 1, mb))
                deps.append(("F", s, mb))
            if any(d not in done for d in deps):
                continue
            start = max([prev_end[s]] + [done[d] for d in deps])
            dur = float(durations.get((kind, s), 1.0))
            done[(kind, s, mb)] = start + dur
            prev_end[s] = start + dur
            busy[s] += dur
            pos[s] += 1
            ran += 1
            progressed = True
        if not progressed:
            stuck = [(s, events[s][pos[s]]) for s in range(p)
                     if pos[s] < len(events[s])]
            raise RuntimeError(f"schedule deadlock; waiting: {stuck}")
    makespan = max(prev_end) if p else 0.0
    bubble = 0.0
    if makespan > 0 and p:
        bubble = 1.0 - sum(busy) / (p * makespan)
    return {"makespan": makespan, "busy": busy,
            "bubble_fraction": bubble}
