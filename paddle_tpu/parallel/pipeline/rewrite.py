"""Stage-program rewriter: one program -> per-(stage, phase) sub-programs.

Each sub-program keeps only the ops assigned to its (stage, phase) cell
and turns every cross-subprogram value into an explicit interface:

  * a value crossing a STAGE boundary gets a ``pipeline_recv`` op at the
    consumer (fed through a fresh ``name@PPIN`` data var) and a
    ``pipeline_send`` op at the producer (fetched as ``name@PPOUT``) —
    identities off-mesh, ``ppermute`` hops along a mapped pp axis;
  * a value crossing only a PHASE boundary within one stage (stashed
    activations for the backward, parameter grads for the optimizer)
    becomes a plain feed/fetch pair — it never leaves the stage's
    devices, so no collective is emitted;
  * persistable vars (params, optimizer state, lr) stay in the shared
    scope, untouched.

Like the overlap scheduler, the rewrite refuses unsafe programs instead
of mutating them quietly: the SOURCE program must be free of PTA030-034
dataflow hazards and PTA040/041 partition-legality errors, and every
rewritten stage program is re-verified before it is returned.
"""

from .partition import (PHASE_BWD, PHASE_FWD, PHASE_OPT, _PSEUDO_OPS,
                        check_partition)

__all__ = ["StageProgram", "build_stage_programs",
           "PP_IN_SUFFIX", "PP_OUT_SUFFIX", "PIPELINE_CODES"]

PP_IN_SUFFIX = "@PPIN"
PP_OUT_SUFFIX = "@PPOUT"
PIPELINE_CODES = ("PTA040", "PTA041")

_PHASES = (PHASE_FWD, PHASE_BWD, PHASE_OPT)


class StageProgram:
    """One executable cell of the pipeline: (stage, phase) + interface."""

    __slots__ = ("program", "stage", "phase", "data_feeds", "boundary_in",
                 "local_in", "boundary_out", "local_out", "user_fetches",
                 "fetch_names")

    def __init__(self, program, stage, phase):
        self.program = program
        self.stage = stage
        self.phase = phase
        self.data_feeds = []     # original is_data feeds this cell reads
        self.boundary_in = {}    # var name -> producing stage
        self.local_in = []       # same-stage cross-phase feeds
        self.boundary_out = {}   # var name -> [consuming stages]
        self.local_out = []      # same-stage cross-phase fetches
        self.user_fetches = []   # caller fetch_names owned by this cell
        self.fetch_names = []    # full fetch list passed to the executor

    def describe(self):
        return (f"stage {self.stage} {self.phase}: "
                f"{len(self.program.global_block().ops)} ops, "
                f"feeds {self.data_feeds}, "
                f"recv {sorted(self.boundary_in)}, "
                f"send {sorted(self.boundary_out)}, "
                f"stash in/out {len(self.local_in)}/{len(self.local_out)}")


def _require_hazard_free(program, feed_names, what, plan=None, graph=None):
    """check_hazards (+ check_partition when a plan is given); raises
    ProgramVerificationError on any error-severity finding."""
    # analysis imported at call time: analysis.dataflow itself imports the
    # ops package, which imports parallel (and therefore this package)
    from ...analysis.dataflow import DATAFLOW_CODES, check_hazards
    from ...analysis.diagnostics import ProgramVerificationError, Report

    report = Report(level="full", context=f"pipeline-{what}")
    g = check_hazards(program, report, feed_names=feed_names, graph=graph)
    if plan is not None:
        check_partition(program, plan, report, graph=g,
                        feed_names=feed_names)
    bad = [d for d in report.diagnostics
           if d.code in DATAFLOW_CODES + PIPELINE_CODES
           and d.severity == "error"]
    if bad:
        raise ProgramVerificationError(report)
    return g


def _block_reads(op):
    names = set(op.input_arg_names())
    for v in op.attrs.values():
        if hasattr(v, "ops"):
            for sub in v.ops:
                names |= _block_reads(sub)
    return names


def build_stage_programs(program, plan, feed_names=(), fetch_names=(),
                         check=True):
    """Split `program` along `plan` into {(stage, phase): StageProgram}.

    `feed_names` are the program's data feeds; `fetch_names` (e.g. the
    loss) are routed to the sub-program that defines them. With `check`
    (default) the source and every stage program are hazard-verified."""
    gb = program.global_block()
    ops = gb.ops
    feed_names = list(feed_names)
    fetch_names = list(fetch_names)
    if check:
        _require_hazard_free(program, feed_names, "source", plan=plan)

    # -- cell membership --------------------------------------------------
    cell_of = {}  # op idx -> (stage, phase)
    for i, op in enumerate(ops):
        if op.type in _PSEUDO_OPS:
            continue
        st = plan.stage_of(i)
        if st is None:
            raise ValueError(f"op#{i}({op.type}) has no stage assignment")
        cell_of[i] = (st, plan.phases[i])
    cells = sorted({c for c in cell_of.values()},
                   key=lambda c: (c[0], _PHASES.index(c[1])))

    # name -> cell that first defines it (program order)
    def_cell = {}
    for i, op in enumerate(ops):
        if i not in cell_of:
            continue
        for n in op.output_arg_names():
            def_cell.setdefault(n, cell_of[i])

    out = {}
    for cell in cells:
        stage, phase = cell
        kept = [i for i in range(len(ops)) if cell_of.get(i) == cell]
        clone = program.clone()
        cgb = clone.global_block()
        clone_ops = cgb.ops
        cgb.ops = [clone_ops[i] for i in kept]
        sp = StageProgram(clone, stage, phase)

        reads, defined = set(), set()
        for i in kept:
            reads |= _block_reads(ops[i])
            defined |= set(ops[i].output_arg_names())
        external = []
        for n in sorted(reads):
            if n in defined:
                # defined within the cell before/at the read (program
                # order preserved); a pre-def read would be an external
                # version, which PTA041 already rejects for boundaries
                first_def_here = min(i for i in kept
                                     if n in ops[i].output_arg_names())
                first_read_here = min(
                    i for i in kept if n in _block_reads(ops[i]))
                if first_read_here >= first_def_here:
                    continue
            v = cgb.vars.get(n)
            if v is None or v.persistable:
                continue  # scope-resident state
            if v.is_data:
                sp.data_feeds.append(n)
                continue
            external.append(n)

        for n in external:
            src = def_cell.get(n)
            if src is None:
                # never written anywhere: treat as an extra data feed
                cgb.vars[n].is_data = True
                sp.data_feeds.append(n)
            elif src[0] != stage:
                sp.boundary_in[n] = src[0]
            else:
                cgb.vars[n].is_data = True
                sp.local_in.append(n)

        # recv ops (front, in name order) for cross-stage arrivals
        for k, n in enumerate(sorted(sp.boundary_in)):
            src_stage = sp.boundary_in[n]
            v = cgb.vars[n]
            cgb.create_var(name=n + PP_IN_SUFFIX, shape=v.shape,
                           dtype=v.dtype, is_data=True)
            cgb.insert_op(
                k, "pipeline_recv",
                inputs={"X": [n + PP_IN_SUFFIX]}, outputs={"Out": [n]},
                attrs={"axis_name": plan.axis,
                       "peer": stage - src_stage})
        out[cell] = sp

    # -- producer-side interface ------------------------------------------
    for cell, sp in out.items():
        stage, phase = cell
        cgb = sp.program.global_block()
        consumers = {}  # name -> set of consuming cells
        for other, osp in out.items():
            if other == cell:
                continue
            for n in osp.boundary_in:
                if def_cell.get(n) == cell:
                    consumers.setdefault(n, set()).add(other)
            for n in osp.local_in:
                if def_cell.get(n) == cell:
                    consumers.setdefault(n, set()).add(other)
        for n in sorted(consumers):
            dst_stages = sorted({c[0] for c in consumers[n]} - {stage})
            if dst_stages:
                v = cgb.vars[n]
                cgb.create_var(name=n + PP_OUT_SUFFIX, shape=v.shape,
                               dtype=v.dtype)
                cgb.append_op(
                    "pipeline_send",
                    inputs={"X": [n]}, outputs={"Out": [n + PP_OUT_SUFFIX]},
                    attrs={"axis_name": plan.axis,
                           "peer": dst_stages[0] - stage})
                sp.boundary_out[n] = dst_stages
            if any(c[0] == stage for c in consumers[n]):
                sp.local_out.append(n)
        for n in fetch_names:
            if def_cell.get(n) == cell:
                sp.user_fetches.append(n)
        sp.fetch_names = (
            [n + PP_OUT_SUFFIX for n in sorted(sp.boundary_out)]
            + sorted(sp.local_out) + list(sp.user_fetches))
        sp.program._mutation += 1
        sp.program._pipeline_stage = (plan.digest(), stage, phase)
        if check:
            from ...analysis.dataflow import DATAFLOW_CODES, check_hazards
            from ...analysis.diagnostics import (ProgramVerificationError,
                                                 Report)

            report = Report(level="full",
                            context=f"pipeline-stage{stage}-{phase}")
            check_hazards(sp.program, report,
                          feed_names=sp.data_feeds + sp.local_in
                          + [n + PP_IN_SUFFIX for n in sp.boundary_in])
            bad = [d for d in report.diagnostics
                   if d.code in DATAFLOW_CODES and d.severity == "error"]
            if bad:
                raise ProgramVerificationError(report)
    return out
