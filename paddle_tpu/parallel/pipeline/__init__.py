"""Inter-op pipeline parallelism over the `pp` mesh axis.

NOT the input pipeline: the deprecated top-level module
``paddle_tpu/pipeline.py`` is the legacy *device-staged input feeder* shim
(now backed by ``paddle_tpu.datapipe``). THIS package is model-parallel
*pipeline* parallelism — it splits a ProgramDesc into stages along the
``pp`` mesh axis and runs them with microbatched 1F1B scheduling
(docs/pipeline.md).

Three layers:

  * ``partition`` — min-cut the SSA dependency graph
    (``analysis.dataflow``) into ``n_stages`` contiguous forward
    intervals, balancing per-stage FLOPs (``trace.costs``) against the
    activation bytes crossing each cut, then fold backward/optimizer ops
    onto their forward twins' stages. ``check_partition`` emits the
    PTA040/PTA041 legality codes.
  * ``rewrite`` — split the program into per-(stage, phase) sub-programs
    with explicit ``pipeline_send``/``pipeline_recv`` boundary ops
    (identity off-mesh, ``ppermute`` on a mapped pp axis). The source and
    every stage program are hazard-checked (PTA030-034 + PTA040/041) the
    same way the overlap scheduler re-verifies its reorders — an illegal
    split raises ProgramVerificationError, it is never silently run.
  * ``schedule``/``runner`` — the 1F1B microbatch order, its analytic
    bubble bound (p-1)/(m+p-1), and a host-staged ``PipelineRunner`` that
    executes the stage programs through Executor/ParallelExecutor,
    accumulates microbatch gradients, and reports the measured bubble
    fraction.
"""

from ... import flags
from .partition import (StagePlan, partition, check_partition, op_phase,
                        PHASE_FWD, PHASE_BWD, PHASE_OPT)
from .rewrite import (StageProgram, build_stage_programs,
                      PP_IN_SUFFIX, PP_OUT_SUFFIX)
from .schedule import analytic_bubble, schedule_1f1b, simulate_schedule
from .runner import PipelineRunner

__all__ = [
    "StagePlan", "partition", "check_partition", "op_phase",
    "PHASE_FWD", "PHASE_BWD", "PHASE_OPT",
    "StageProgram", "build_stage_programs",
    "PP_IN_SUFFIX", "PP_OUT_SUFFIX",
    "analytic_bubble", "schedule_1f1b", "simulate_schedule",
    "PipelineRunner",
    "register_pipeline", "active_pipeline", "reset_registry",
    "manifest_section",
]

flags.define(
    "pipeline_stages", int, 0,
    "Pipeline-parallel stage count over the pp mesh axis (0 = off). The "
    "PipelineRunner takes explicit arguments; this flag is the default "
    "for the CLI/bench entry points.")


# ---------------------------------------------------------------------------
# process-wide registry: resilience.checkpoint stamps the active pipeline
# geometry (stage count, pp axis, schedule, microbatches) into
# manifest.json next to the mesh/zero1/autoshard sections, so `checkpoint
# inspect` can render it and a pp-mismatched restore fails loudly through
# check_mesh_compat (the mesh section carries pp too).
# ---------------------------------------------------------------------------
_ACTIVE = None


def register_pipeline(info):
    """Record the running pipeline geometry: a dict with at least
    `stages`; `axis`, `microbatches`, `schedule`, `digest` ride along."""
    global _ACTIVE
    _ACTIVE = dict(info) if info else None


def active_pipeline():
    return None if _ACTIVE is None else dict(_ACTIVE)


def reset_registry():
    global _ACTIVE
    _ACTIVE = None


def manifest_section():
    """Manifest entry describing the active pipeline, or None."""
    if _ACTIVE is None:
        return None
    sec = {"axis": "pp", "schedule": "1f1b"}
    sec.update(_ACTIVE)
    return sec
