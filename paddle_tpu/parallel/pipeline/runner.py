"""PipelineRunner: host-staged 1F1B execution of the stage programs.

The runner splits each step's feeds into ``n_microbatches`` along axis 0,
drives the per-(stage, phase) sub-programs in the 1F1B event order from
``schedule.schedule_1f1b``, moves boundary values between stages through
their ``@PPIN``/``@PPOUT`` interface vars, accumulates parameter
gradients across microbatches (sum x 1/m, float32), and finally runs each
stage's optimizer cell once against the shared scope.

Executed through a plain Executor the stage boundary ops are identities
(off-mesh), so a pipeline replay is bitwise-comparable to the same
microbatched loop with ``n_stages=1`` — that property is what
tests/test_pipeline_parallel.py and ``bench --dry pipeline`` assert.
Executed through ParallelExecutor with ``mesh_shape={"dp": d, "pp": p}``
each cell compiles against the full mesh and data feeds shard over dp.

Per-event wall times feed ``schedule.simulate_schedule`` to report the
measured bubble fraction next to the analytic (p-1)/(m+p-1) bound.
"""

import time

import numpy as np

from ...core.framework import GRAD_VAR_SUFFIX
from ...core.scope import global_scope
from .partition import PHASE_BWD, PHASE_FWD, PHASE_OPT, partition
from .rewrite import PP_IN_SUFFIX, PP_OUT_SUFFIX, build_stage_programs
from .schedule import analytic_bubble, schedule_1f1b, simulate_schedule

__all__ = ["PipelineRunner"]

_KIND_PHASE = {"F": PHASE_FWD, "B": PHASE_BWD}


def _split_microbatches(feed, m):
    """Split every feed value into m equal chunks along axis 0."""
    outs = [dict() for _ in range(m)]
    for name, val in feed.items():
        arr = np.asarray(val)
        if arr.ndim == 0 or arr.shape[0] % m:
            raise ValueError(
                f"feed {name!r} (shape {arr.shape}) not splittable into "
                f"{m} microbatches along axis 0")
        for mb, chunk in enumerate(np.split(arr, m, axis=0)):
            outs[mb][name] = chunk
    return outs


class PipelineRunner:
    """Partition + rewrite + 1F1B-execute one training program.

    The caller runs the startup program into `scope` first; persistable
    state stays there across steps, exactly as with a plain Executor."""

    def __init__(self, program, n_stages, loss_name=None, feed_names=(),
                 n_microbatches=1, fetch_names=None, scope=None, plan=None,
                 batch_size=1, parallel_executor=None, check=True):
        self.n_stages = int(n_stages)
        self.n_microbatches = int(n_microbatches)
        if self.n_stages < 1 or self.n_microbatches < 1:
            raise ValueError("n_stages and n_microbatches must be >= 1")
        self.loss_name = loss_name
        self.feed_names = list(feed_names)
        self.scope = scope if scope is not None else global_scope()
        user_fetches = list(fetch_names or ())
        if loss_name and loss_name not in user_fetches:
            user_fetches.insert(0, loss_name)
        self.plan = plan if plan is not None else partition(
            program, self.n_stages, feed_names=self.feed_names,
            batch_size=batch_size)
        self.stages = build_stage_programs(
            program, self.plan, feed_names=self.feed_names,
            fetch_names=user_fetches, check=check)
        # Executor imported at construction time: executor.py transitively
        # imports the parallel package that owns this module
        from ...executor import Executor

        self._pe = parallel_executor  # optional ParallelExecutor per cell
        self._exe = Executor()
        self.last_report = None
        from . import register_pipeline  # package registry (late import)
        register_pipeline({
            "stages": self.n_stages,
            "microbatches": self.n_microbatches,
            "digest": self.plan.digest(),
            "bubble_analytic": analytic_bubble(self.n_stages,
                                               self.n_microbatches),
        })

    # -- one sub-program execution ----------------------------------------
    def _run_cell(self, sp, feed):
        if self._pe is not None:
            pe = self._pe.get(sp) if callable(
                getattr(self._pe, "get", None)) else self._pe
            vals = pe.run(sp.fetch_names, feed=feed)
        else:
            vals = self._exe.run(sp.program, feed=feed,
                                 fetch_list=sp.fetch_names,
                                 scope=self.scope)
        return dict(zip(sp.fetch_names, vals))

    def _cell_feed(self, sp, mb_feed, values, mb):
        feed = {}
        for n in sp.data_feeds:
            if n in mb_feed:
                feed[n] = mb_feed[n]
            else:
                feed[n] = values[(n, mb)]
        for n, src in sp.boundary_in.items():
            feed[n + PP_IN_SUFFIX] = values[(n, mb)]
        for n in sp.local_in:
            feed[n] = values[(n, mb)]
        return feed

    def _ready(self, sp, mb_feed, values, mb):
        for n in sp.data_feeds:
            if n not in mb_feed and (n, mb) not in values:
                return False
        for n in sp.boundary_in:
            if (n, mb) not in values:
                return False
        for n in sp.local_in:
            if (n, mb) not in values:
                return False
        return True

    def _store_outputs(self, sp, got, values, mb):
        for n in sp.boundary_out:
            values[(n, mb)] = got[n + PP_OUT_SUFFIX]
        for n in sp.local_out:
            values[(n, mb)] = got[n]

    # -- one optimizer pass against accumulated grads ----------------------
    def _run_opt(self, values):
        m = self.n_microbatches
        inv_m = np.float32(1.0 / m)
        for (stage, phase), sp in sorted(self.stages.items()):
            if phase != PHASE_OPT:
                continue
            feed = {}
            names = (list(sp.data_feeds) + list(sp.boundary_in)
                     + list(sp.local_in))
            for n in names:
                if n.endswith(GRAD_VAR_SUFFIX):
                    acc = values[(n, 0)].astype(np.float32)
                    for mb in range(1, m):
                        acc = acc + values[(n, mb)].astype(np.float32)
                    val = acc * inv_m
                else:
                    val = values[(n, m - 1)]
                if n in sp.boundary_in:
                    feed[n + PP_IN_SUFFIX] = val
                else:
                    feed[n] = val
            self._run_cell(sp, feed)

    # -- the step ----------------------------------------------------------
    def run(self, feed, fetch_list=None):
        """One training step: returns {loss, fetches, bubble_fraction,
        bubble_analytic, event_times}. `fetch_list` defaults to the
        fetches given at construction."""
        m, p = self.n_microbatches, self.n_stages
        mb_feeds = _split_microbatches(
            {n: feed[n] for n in self.feed_names if n in feed}, m)
        values = {}            # (var name, mb) -> host array
        per_mb_fetch = {}      # (fetch name, mb) -> host value
        events = schedule_1f1b(p, m)
        pos = [0] * p
        durations = {}         # (kind, stage) -> [seconds per event]
        total = sum(len(ev) for ev in events)
        ran = 0
        fwd_done = set()
        while ran < total:
            progressed = False
            for s in range(p):
                if pos[s] >= len(events[s]):
                    continue
                kind, mb = events[s][pos[s]]
                sp = self.stages.get((s, _KIND_PHASE[kind]))
                if sp is None:  # stage has no ops in this phase
                    if kind == "F":
                        fwd_done.add((s, mb))
                    pos[s] += 1
                    ran += 1
                    progressed = True
                    continue
                if kind == "B" and (s, mb) not in fwd_done:
                    continue
                if not self._ready(sp, mb_feeds[mb], values, mb):
                    continue
                t0 = time.perf_counter()
                got = self._run_cell(
                    sp, self._cell_feed(sp, mb_feeds[mb], values, mb))
                durations.setdefault((kind, s), []).append(
                    time.perf_counter() - t0)
                self._store_outputs(sp, got, values, mb)
                for n in sp.user_fetches:
                    per_mb_fetch[(n, mb)] = got[n]
                if kind == "F":
                    fwd_done.add((s, mb))
                pos[s] += 1
                ran += 1
                progressed = True
            if not progressed:
                stuck = [(s, events[s][pos[s]]) for s in range(p)
                         if pos[s] < len(events[s])]
                raise RuntimeError(
                    f"pipeline deadlock; stages waiting on {stuck}")
        self._run_opt(values)

        # loss / fetches: mean over microbatches, accumulated in float32
        # exactly like the gradients so an n_stages=1 replay is bitwise
        inv_m = np.float32(1.0 / m)
        fetches = {}
        for n in {k[0] for k in per_mb_fetch}:
            acc = np.asarray(per_mb_fetch[(n, 0)], dtype=np.float32)
            for mb in range(1, m):
                acc = acc + np.asarray(per_mb_fetch[(n, mb)],
                                       dtype=np.float32)
            fetches[n] = acc * inv_m
        loss = fetches.get(self.loss_name) if self.loss_name else None

        # structural bubble: unit-cost simulation of the executed event
        # order (this is what the (p-1)/(m+p-1) bound describes);
        # measured bubble: the same simulation over wall times, which on
        # a host-staged run also carries dispatch overhead + stage skew
        struct = simulate_schedule(events)
        mean_durs = {k: sum(v) / len(v) for k, v in durations.items()}
        sim = simulate_schedule(events, mean_durs) if mean_durs else struct
        self.last_report = {
            "loss": loss,
            "fetches": fetches,
            "n_stages": p,
            "n_microbatches": m,
            "bubble_fraction": struct["bubble_fraction"],
            "bubble_measured": sim["bubble_fraction"],
            "bubble_analytic": analytic_bubble(p, m),
            "makespan_s": sim["makespan"],
            "plan": self.plan.to_dict(),
        }
        from ... import monitor

        reg = monitor.registry()
        reg.gauge("pipeline_stages",
                  help="pipeline-parallel stage count").set(float(p))
        reg.gauge("pipeline_microbatches",
                  help="1F1B microbatches per step").set(float(m))
        reg.gauge("pipeline_bubble_fraction",
                  help="structural 1F1B bubble fraction of the executed "
                       "schedule").set(float(struct["bubble_fraction"]))
        reg.gauge("pipeline_bubble_measured",
                  help="wall-time 1F1B bubble fraction (includes host "
                       "dispatch overhead)").set(float(sim["bubble_fraction"]))
        reg.gauge("pipeline_bubble_analytic",
                  help="analytic 1F1B bubble bound (p-1)/(m+p-1)"
                  ).set(float(analytic_bubble(p, m)))
        return self.last_report
