"""Stage partitioning: min-cut the dependency graph into pipeline stages.

The forward ops of the global block form a sequence in program order
(program order is always a valid topological order — the dataflow graph
asserts as much). A p-stage partition is p-1 cut points in that sequence:

  * stage balance — each forward op is weighted by its analytic FLOPs
    (``trace.costs.op_costs``) times 3, the forward plus its ~2x backward
    twin, and a linear-partition DP first finds the minimal achievable
    max-stage weight;
  * cut cost — every non-persistable var defined before a cut and read
    after it must be shipped across the pp boundary (activation forward +
    its gradient backward, so 2x its bytes). A second DP picks, among all
    partitions within ``balance_slack`` of the balance optimum, the one
    with the fewest total boundary bytes.

Backward ops then inherit the stage of their paired forward op, optimizer
ops the stage that owns their Param, and ``check_partition`` verifies the
result: a same-phase dependency running from a later stage to an earlier
one (PTA040) or a boundary var rewritten after its send (PTA041) makes
the split illegal.
"""

import hashlib
import json

import numpy as np

from ...backward import _strip_grad_suffix
from ...core.framework import OpRole, OP_ROLE_ATTR_NAME
from ...trace.costs import op_costs

__all__ = ["StagePlan", "partition", "check_partition", "op_phase",
           "PHASE_FWD", "PHASE_BWD", "PHASE_OPT"]

PHASE_FWD = "fwd"
PHASE_BWD = "bwd"
PHASE_OPT = "opt"

# excluded from stage programs entirely: feeding/fetching is by name
_PSEUDO_OPS = frozenset(("feed", "fetch"))


def op_phase(op):
    """fwd / bwd / opt bucket for one op, from its OpRole attr."""
    role = op.attrs.get(OP_ROLE_ATTR_NAME, OpRole.Forward)
    if role == OpRole.Backward:
        return PHASE_BWD
    if role == OpRole.Optimize:
        return PHASE_OPT
    return PHASE_FWD  # Forward, Forward|Loss, RPC


def _dtype_bytes(dtype):
    try:
        return np.dtype(dtype).itemsize
    except TypeError:
        return 2 if str(dtype) == "bfloat16" else 4


def _var_bytes(var, nominal_batch):
    if var is None or var.shape is None:
        return 0.0
    numel = 1
    for d in var.shape:
        d = -1 if d is None else int(d)
        numel *= nominal_batch if d < 0 else max(1, d)
    return float(numel) * _dtype_bytes(var.dtype)


class StagePlan:
    """A stage assignment for one program: op index -> stage."""

    __slots__ = ("n_stages", "axis", "assignment", "phases", "stage_flops",
                 "boundaries", "cut_bytes", "max_stage_flops")

    def __init__(self, n_stages, assignment, phases, stage_flops,
                 boundaries, cut_bytes, axis="pp"):
        self.n_stages = int(n_stages)
        self.axis = axis
        self.assignment = dict(assignment)   # op idx -> stage
        self.phases = list(phases)           # op idx -> phase
        self.stage_flops = list(stage_flops)
        self.boundaries = list(boundaries)   # [{var, src, dst, bytes}]
        self.cut_bytes = float(cut_bytes)
        self.max_stage_flops = max(stage_flops) if stage_flops else 0.0

    def stage_of(self, op_idx):
        return self.assignment.get(op_idx)

    def balance(self):
        """max/mean stage FLOPs — 1.0 is a perfectly balanced split."""
        if not self.stage_flops or not sum(self.stage_flops):
            return 1.0
        mean = sum(self.stage_flops) / len(self.stage_flops)
        return self.max_stage_flops / mean if mean else 1.0

    def digest(self):
        payload = {"n": self.n_stages, "axis": self.axis,
                   "a": sorted(self.assignment.items())}
        return hashlib.sha1(
            json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]

    def to_dict(self):
        return {
            "n_stages": self.n_stages,
            "axis": self.axis,
            "digest": self.digest(),
            "stage_flops": [float(f) for f in self.stage_flops],
            "balance": float(self.balance()),
            "cut_bytes": float(self.cut_bytes),
            "boundaries": [dict(b) for b in self.boundaries],
            "ops_per_stage": [
                sum(1 for s in self.assignment.values() if s == k)
                for k in range(self.n_stages)],
        }

    def describe(self):
        lines = [f"pipeline plan: {self.n_stages} stages on '{self.axis}' "
                 f"(digest {self.digest()})"]
        for s in range(self.n_stages):
            nops = sum(1 for v in self.assignment.values() if v == s)
            lines.append(f"  stage {s}: {nops:3d} ops  "
                         f"{self.stage_flops[s] / 1e6:10.2f} MFLOP")
        lines.append(f"  balance {self.balance():.3f}  boundary "
                     f"{self.cut_bytes / 1e3:.1f} KB/microbatch "
                     f"({len(self.boundaries)} vars)")
        return "\n".join(lines)


def _linear_partition_minmax(weights, p):
    """Minimal achievable max-interval sum splitting `weights` into p
    contiguous intervals (classic linear-partition DP)."""
    n = len(weights)
    pre = [0.0]
    for w in weights:
        pre.append(pre[-1] + w)

    def span(a, b):  # sum of weights[a:b]
        return pre[b] - pre[a]

    INF = float("inf")
    f = [[INF] * (p + 1) for _ in range(n + 1)]
    f[0][0] = 0.0
    for j in range(1, n + 1):
        for s in range(1, min(p, j) + 1):
            for t in range(s - 1, j):
                cand = max(f[t][s - 1], span(t, j))
                if cand < f[j][s]:
                    f[j][s] = cand
    return f[n][p]


def _min_cut_partition(weights, cut_bytes, p, cap):
    """Among partitions with every interval sum <= cap, minimize total cut
    bytes; returns the list of cut positions (cut k = boundary after
    element k) or None when infeasible."""
    n = len(weights)
    pre = [0.0]
    for w in weights:
        pre.append(pre[-1] + w)
    INF = float("inf")
    g = [[INF] * (p + 1) for _ in range(n + 1)]
    back = [[None] * (p + 1) for _ in range(n + 1)]
    g[0][0] = 0.0
    for j in range(1, n + 1):
        for s in range(1, min(p, j) + 1):
            for t in range(s - 1, j):
                if pre[j] - pre[t] > cap:
                    continue
                cost = g[t][s - 1] + (cut_bytes[t - 1] if t > 0 else 0.0)
                if cost < g[j][s]:
                    g[j][s] = cost
                    back[j][s] = t
    if g[n][p] == INF:
        return None
    cuts, j, s = [], n, p
    while s > 1:
        t = back[j][s]
        cuts.append(t - 1)  # cut after forward position t-1
        j, s = t, s - 1
    cuts.reverse()
    return cuts


def partition(program, n_stages, feed_names=None, batch_size=1,
              balance_slack=0.25):
    """Build a StagePlan splitting `program` into `n_stages` stages.

    Raises ValueError when the program has fewer forward ops than stages.
    `balance_slack` widens the allowed max-stage weight over the balance
    optimum so the byte-minimizing DP has room to pick cheaper cuts."""
    n_stages = int(n_stages)
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    gb = program.global_block()
    ops = gb.ops
    phases = [op_phase(op) for op in ops]
    fwd_idx = [i for i, op in enumerate(ops)
               if phases[i] == PHASE_FWD and op.type not in _PSEUDO_OPS]
    if len(fwd_idx) < n_stages:
        raise ValueError(
            f"cannot split {len(fwd_idx)} forward ops into {n_stages} "
            f"pipeline stages")
    cost_by_idx = {r["index"]: r["flops_est"]
                   for r in op_costs(program, batch_size=batch_size)}
    # forward weight carries its ~2x backward twin so stage balance
    # reflects the full fwd+bwd residence of the stage
    weights = [max(cost_by_idx.get(i, 0.0), 1.0) * 3.0 for i in fwd_idx]
    nf = len(fwd_idx)

    # -- per-cut boundary bytes ------------------------------------------
    # an activation defined at forward position a with last forward read at
    # position b crosses every cut a <= k < b; 2x bytes for its gradient
    pos_of = {i: k for k, i in enumerate(fwd_idx)}
    def_pos, last_read = {}, {}
    for k, i in enumerate(fwd_idx):
        op = ops[i]
        for n in op.input_arg_names():
            if n in def_pos:
                last_read[n] = max(last_read.get(n, k), k)
        for n in op.output_arg_names():
            v = gb.vars.get(n)
            if n not in def_pos and v is not None and not v.persistable:
                def_pos[n] = k
    add_at = [0.0] * (nf + 1)
    rem_at = [0.0] * (nf + 1)
    nominal = max(1, int(batch_size))
    var_cross_bytes = {}
    for n, a in def_pos.items():
        b = last_read.get(n, a)
        if b <= a:
            continue
        nbytes = 2.0 * _var_bytes(gb.vars.get(n), nominal)
        var_cross_bytes[n] = nbytes
        add_at[a] += nbytes
        rem_at[b] += nbytes
    cut_bytes = [0.0] * max(1, nf - 1)
    cur = 0.0
    for k in range(nf - 1):
        cur -= rem_at[k]
        cur += add_at[k]
        cut_bytes[k] = cur

    # -- choose cuts ------------------------------------------------------
    if n_stages == 1:
        cuts = []
    else:
        mstar = _linear_partition_minmax(weights, n_stages)
        cap = mstar * (1.0 + float(balance_slack))
        cuts = _min_cut_partition(weights, cut_bytes, n_stages, cap)
        if cuts is None:  # slack too tight under ties; fall back to exact
            cuts = _min_cut_partition(weights, cut_bytes, n_stages, mstar)
        assert cuts is not None, "linear-partition DP disagrees with itself"

    stage_of_pos = [0] * nf
    s = 0
    cut_set = set(cuts)
    for k in range(nf):
        stage_of_pos[k] = s
        if k in cut_set:
            s += 1

    # -- fold every op onto a stage --------------------------------------
    assignment = {}
    first_writer = {}
    for i, op in enumerate(ops):
        for n in op.output_arg_names():
            first_writer.setdefault(n, i)
    for i in fwd_idx:
        assignment[i] = stage_of_pos[pos_of[i]]

    def fwd_stage_of_var(name):
        w = first_writer.get(name)
        return assignment.get(w) if w is not None else None

    last = n_stages - 1
    for i, op in enumerate(ops):
        if i in assignment or op.type in _PSEUDO_OPS:
            continue
        ph = phases[i]
        if ph == PHASE_BWD:
            cands = []
            for n in op.input_arg_names() + op.output_arg_names():
                f = _strip_grad_suffix(n) if "@GRAD" in n else n
                st = fwd_stage_of_var(f)
                if st is not None:
                    cands.append(st)
            assignment[i] = max(cands) if cands else last
        elif ph == PHASE_OPT:
            p_in = op.input("Param")
            st = None
            if p_in:
                # the stage whose forward consumes the param owns its update
                pname = p_in[0]
                reads = [assignment[j] for j in fwd_idx
                         if pname in ops[j].input_arg_names()]
                st = max(reads) if reads else None
                if st is None:
                    for g in op.input("Grad"):
                        st = fwd_stage_of_var(_strip_grad_suffix(g))
                        if st is not None:
                            break
            if st is None:
                cands = [fwd_stage_of_var(n)
                         for n in op.input_arg_names()]
                cands = [c for c in cands if c is not None]
                st = max(cands) if cands else 0
            assignment[i] = st
        else:  # residual forward-phase pseudo ops
            cands = [fwd_stage_of_var(n) for n in op.input_arg_names()]
            cands = [c for c in cands if c is not None]
            assignment[i] = max(cands) if cands else 0
    for i, op in enumerate(ops):
        if op.type in _PSEUDO_OPS and i not in assignment:
            # feed-type ops follow their first consumer, fetch their source
            outs = set(op.output_arg_names())
            users = [assignment[j] for j, o in enumerate(ops)
                     if j in assignment and outs & set(o.input_arg_names())]
            srcs = [fwd_stage_of_var(n) for n in op.input_arg_names()]
            srcs = [c for c in srcs if c is not None]
            assignment[i] = min(users) if users else \
                (max(srcs) if srcs else 0)

    # -- stats + boundary list -------------------------------------------
    stage_flops = [0.0] * n_stages
    for i, st in assignment.items():
        stage_flops[st] += cost_by_idx.get(i, 0.0)
    boundaries = []
    total_cut = 0.0
    for n, a in sorted(def_pos.items()):
        b = last_read.get(n, a)
        src, dst = stage_of_pos[a], stage_of_pos[b]
        if dst > src:
            nbytes = var_cross_bytes.get(n, 0.0)
            boundaries.append({"var": n, "src": src, "dst": dst,
                               "bytes": nbytes})
            total_cut += nbytes
    return StagePlan(n_stages, assignment, phases, stage_flops,
                     boundaries, total_cut)


def check_partition(program, plan, report, graph=None, feed_names=None):
    """Emit PTA040/PTA041 diagnostics for an illegal stage split.

    PTA040: a same-phase raw def-use edge runs against the pipeline
    direction (forward data flowing to an EARLIER stage, or gradient data
    flowing to a LATER one) — no 1F1B order can satisfy it.
    PTA041: a var that crosses a stage boundary has more than one SSA
    version, so the receiving stage would observe a stale copy."""
    from ...analysis.dataflow import DependencyGraph

    if graph is None:
        graph = DependencyGraph(program, feed_names=feed_names)
    ops = program.global_block().ops
    phases = plan.phases
    for node in graph.nodes:
        u = node.idx
        su = plan.stage_of(u)
        if su is None:
            continue
        for v, kinds in graph.succs[u].items():
            if "raw" not in kinds:
                continue
            sv = plan.stage_of(v)
            if sv is None or phases[u] != phases[v]:
                continue
            bad = (phases[u] == PHASE_FWD and sv < su) or \
                  (phases[u] == PHASE_BWD and sv > su)
            if bad:
                report.add(
                    "PTA040",
                    f"{phases[u]} dependency op#{u}({ops[u].type}) -> "
                    f"op#{v}({ops[v].type}) runs from stage {su} to stage "
                    f"{sv} against the pipeline direction",
                    op_idx=v, op_type=ops[v].type, block_idx=0)
    boundary_vars = {b["var"] for b in plan.boundaries}
    for name in sorted(boundary_vars):
        writers = [n.idx for n in graph.nodes if name in n.writes]
        if len(writers) > 1:
            report.add(
                "PTA041",
                f"boundary var {name!r} is written by ops "
                f"{writers} — versions after the first would be stale on "
                f"the receiving stage",
                var=name, op_idx=writers[1],
                op_type=ops[writers[1]].type, block_idx=0)
    return report
