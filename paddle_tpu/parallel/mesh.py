"""Device-mesh management: named-axis meshes for dp/mp/pp/sp.

Reference contrast: the reference enumerates CUDA places and builds
NCCLContextMap per device set (platform/nccl_helper.h:75). On TPU the mesh
IS the communicator: axes are named, shardings reference axis names, and XLA
emits ICI collectives for any cross-shard dataflow (SURVEY.md §2.4).
"""

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_parallel_mesh", "current_mesh", "mesh_scope",
           "mesh_geometry", "MeshSpec",
           "DP_AXIS", "MP_AXIS", "PP_AXIS", "SP_AXIS"]

DP_AXIS = "dp"   # data parallel (batch)
MP_AXIS = "mp"   # tensor/model parallel
PP_AXIS = "pp"   # pipeline stages
SP_AXIS = "sp"   # sequence/context parallel

_current = [None]


def make_mesh(shape=None, axis_names=None, devices=None):
    """Build a Mesh. shape: dict axis->size or tuple; default: all devices
    on the dp axis."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        return Mesh(np.array(devices), (DP_AXIS,))
    if isinstance(shape, dict):
        axis_names = tuple(shape.keys())
        dims = tuple(shape.values())
    else:
        dims = tuple(shape)
        axis_names = tuple(axis_names or
                           (DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS)[: len(dims)])
    n = int(np.prod(dims))
    if n != len(devices):
        raise ValueError(f"mesh shape {dims} needs {n} devices, "
                         f"have {len(devices)}")
    return Mesh(np.array(devices).reshape(dims), axis_names)


def data_parallel_mesh(num_devices=None):
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (DP_AXIS,))


def current_mesh():
    return _current[0]


def mesh_geometry(mesh):
    """{axis: size} of a Mesh (None in -> None out) — the shape that rides
    checkpoint manifests so a restore can refuse a conflicting mp size."""
    if mesh is None:
        return None
    return {str(a): int(s) for a, s in mesh.shape.items()}


class MeshSpec:
    """Re-formable mesh recipe for elastic training: the non-dp axes are
    fixed by the model (mp/pp sharding is baked into the checkpoint's
    meaning), the dp axis is whatever the surviving fleet supports.

        spec = MeshSpec(mp=2)          # dp is elastic, mp pinned at 2
        mesh = spec.build(dp=4)        # 4x2 over the first 8 devices
        mesh = spec.build(dp=2)        # re-formed at 2x2 after a shrink

    build() takes the leading `dp * fixed` devices, so shrinking is a pure
    subset (survivors keep their device slots) and growing re-admits the
    tail.
    """

    def __init__(self, **fixed_axes):
        self.fixed = {str(k): int(v) for k, v in fixed_axes.items()
                      if k != DP_AXIS}
        for ax, n in self.fixed.items():
            if n < 1:
                raise ValueError(f"mesh axis {ax!r} must be >= 1, got {n}")

    @property
    def fixed_size(self):
        return int(np.prod(list(self.fixed.values()))) if self.fixed else 1

    def max_dp(self, devices=None):
        n = len(devices) if devices is not None else jax.device_count()
        return n // self.fixed_size

    def build(self, dp, devices=None):
        dp = int(dp)
        if dp < 1:
            raise ValueError(f"dp must be >= 1, got {dp}")
        devices = list(devices if devices is not None else jax.devices())
        need = dp * self.fixed_size
        if need > len(devices):
            raise ValueError(
                f"MeshSpec(dp={dp}, {self.fixed}) needs {need} devices, "
                f"have {len(devices)}")
        shape = {DP_AXIS: dp}
        shape.update(self.fixed)
        return make_mesh(shape, devices=devices[:need])

    def geometry(self, dp):
        g = {DP_AXIS: int(dp)}
        g.update(self.fixed)
        return g

    def __repr__(self):
        return f"MeshSpec(dp=<elastic>, {self.fixed})"


class mesh_scope:
    """with mesh_scope(mesh): ... — sets the ambient mesh (used by
    ParallelExecutor and shard_map-based ops)."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        self._prev = _current[0]
        _current[0] = self.mesh
        self._ctx = self.mesh
        self._ctx.__enter__()
        return self.mesh

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)
        _current[0] = self._prev
        return False
