"""Device-mesh management: named-axis meshes for dp/mp/pp/sp.

Reference contrast: the reference enumerates CUDA places and builds
NCCLContextMap per device set (platform/nccl_helper.h:75). On TPU the mesh
IS the communicator: axes are named, shardings reference axis names, and XLA
emits ICI collectives for any cross-shard dataflow (SURVEY.md §2.4).
"""

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_parallel_mesh", "current_mesh", "mesh_scope",
           "DP_AXIS", "MP_AXIS", "PP_AXIS", "SP_AXIS"]

DP_AXIS = "dp"   # data parallel (batch)
MP_AXIS = "mp"   # tensor/model parallel
PP_AXIS = "pp"   # pipeline stages
SP_AXIS = "sp"   # sequence/context parallel

_current = [None]


def make_mesh(shape=None, axis_names=None, devices=None):
    """Build a Mesh. shape: dict axis->size or tuple; default: all devices
    on the dp axis."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        return Mesh(np.array(devices), (DP_AXIS,))
    if isinstance(shape, dict):
        axis_names = tuple(shape.keys())
        dims = tuple(shape.values())
    else:
        dims = tuple(shape)
        axis_names = tuple(axis_names or
                           (DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS)[: len(dims)])
    n = int(np.prod(dims))
    if n != len(devices):
        raise ValueError(f"mesh shape {dims} needs {n} devices, "
                         f"have {len(devices)}")
    return Mesh(np.array(devices).reshape(dims), axis_names)


def data_parallel_mesh(num_devices=None):
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (DP_AXIS,))


def current_mesh():
    return _current[0]


class mesh_scope:
    """with mesh_scope(mesh): ... — sets the ambient mesh (used by
    ParallelExecutor and shard_map-based ops)."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        self._prev = _current[0]
        _current[0] = self.mesh
        self._ctx = self.mesh
        self._ctx.__enter__()
        return self.mesh

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)
        _current[0] = self._prev
        return False
