"""User-facing sharding rules: declare how a Program variable is laid out
over the device mesh.

Reference contrast: the reference has no per-parameter placement API — its
tensor-parallel story is the pserver block-splitting transpiler. On TPU the
idiomatic form is a NamedSharding per parameter: annotate variables with
mesh-axis names and ParallelExecutor places state accordingly, letting XLA
insert the tensor-parallel collectives (SURVEY §2.4 TP row).

    w = fluid.layers.create_parameter(...)
    fluid.parallel.set_sharding(w, (None, "mp"))   # shard columns over mp
    pe = fluid.ParallelExecutor(loss_name=..., mesh_shape={"dp": 2, "mp": 4})
"""

from ..core.framework import Variable

__all__ = ["set_sharding", "get_sharding"]


def set_sharding(var, spec):
    """Declare `var`'s mesh placement. spec: one entry per tensor dim —
    a mesh axis name (str) to shard that dim, or None to replicate it.
    A spec shorter than the rank leaves trailing dims replicated."""
    if not isinstance(var, Variable):
        raise TypeError(f"set_sharding expects a Variable, got {type(var)}")
    spec = tuple(spec)
    for e in spec:
        if e is not None and not isinstance(e, str):
            raise TypeError(f"spec entries must be mesh-axis names or None, "
                            f"got {e!r}")
    if var.shape is not None and len(spec) > len(var.shape):
        raise ValueError(
            f"spec {spec} longer than {var.name}'s rank {len(var.shape)}")
    var.sharding = spec
    return var


def get_sharding(var):
    return getattr(var, "sharding", None)
