"""User-facing sharding rules: declare how a Program variable is laid out
over the device mesh.

Reference contrast: the reference has no per-parameter placement API — its
tensor-parallel story is the pserver block-splitting transpiler. On TPU the
idiomatic form is a NamedSharding per parameter: annotate variables with
mesh-axis names and ParallelExecutor places state accordingly, letting XLA
insert the tensor-parallel collectives (SURVEY §2.4 TP row).

    w = fluid.layers.create_parameter(...)
    fluid.parallel.set_sharding(w, (None, "mp"))   # shard columns over mp
    fluid.parallel.set_sharding(w2, "mp")          # bare axis: shard dim 0
    fluid.parallel.set_sharding(w3, PartitionSpec(None, "mp"))  # jax spec
    pe = fluid.ParallelExecutor(loss_name=..., mesh_shape={"dp": 2, "mp": 4})

With autoshard (docs/autoshard.md) a few seeds are enough — the plan
propagates them to every activation, grad and optimizer slot. To seed all
params built inside a block, use `sharding_scope`:

    with fluid.parallel.sharding_scope((None, "mp")):
        h = fluid.layers.fc(x, 256)   # weight gets (None, "mp")
"""

import contextlib

from ..core import framework
from ..core.framework import Variable
from .autoshard.spec import normalize_spec

__all__ = ["set_sharding", "get_sharding", "sharding_scope"]


def set_sharding(var, spec):
    """Declare `var`'s mesh placement. spec: one entry per tensor dim —
    a mesh axis name (str) to shard that dim, or None to replicate it.
    A spec shorter than the rank leaves trailing dims replicated. Also
    accepts a bare axis-name string (shards dim 0) and a
    jax.sharding.PartitionSpec; both normalize to the tuple form."""
    if not isinstance(var, Variable):
        raise TypeError(f"set_sharding expects a Variable, got {type(var)}")
    spec = normalize_spec(spec)
    if var.shape is not None and len(spec) > len(var.shape):
        raise ValueError(
            f"spec {spec} longer than {var.name}'s rank {len(var.shape)}")
    var.sharding = spec
    return var


def get_sharding(var):
    return getattr(var, "sharding", None)


@contextlib.contextmanager
def sharding_scope(spec):
    """Seed-annotate every parameter created inside the block with `spec`
    (truncated to each param's rank; params whose truncated spec names no
    mesh axis — e.g. 1-D biases under (None, "mp") — are left alone, as
    are params already annotated explicitly). Scopes nest; the innermost
    one wins."""
    spec = normalize_spec(spec)

    def hook(param):
        if getattr(param, "sharding", None) is not None:
            return
        rank = len(param.shape) if param.shape is not None else 0
        trimmed = spec[:rank]
        if any(e is not None for e in trimmed):
            param.sharding = tuple(trimmed)

    framework._param_creation_hooks.append(hook)
    try:
        yield
    finally:
        framework._param_creation_hooks.remove(hook)
