"""Multi-host distributed bootstrap.

Reference parity: the "NCCL2 mode" bootstrap — gen_nccl_id_op.cc:31 serves an
ncclUniqueId from trainer 0 over gRPC, then every trainer constructs
NCCLContextMap(nccl_id, num_trainers, trainer_id) (nccl_helper.h:92-118);
drivers read PADDLE_* env vars (trainer.py:148-196, fluid_benchmark.py:111).

TPU-native: jax.distributed.initialize(coordinator, num_processes,
process_id) plays the gen_nccl_id role (rank-0 coordinator, everyone else
dials in over DCN), after which jax.devices() spans all hosts and a mesh
built from them shards programs globally — XLA routes intra-slice collective
traffic over ICI and cross-slice over DCN.
"""

import os

import jax

__all__ = ["init_from_env", "initialize", "is_initialized", "ClusterEnv"]

_initialized = [False]


class ClusterEnv:
    """Parsed PADDLE_* environment (reference trainer.py:148-196)."""

    def __init__(self, env=None):
        e = env or os.environ
        self.training_role = e.get("PADDLE_TRAINING_ROLE", "TRAINER")
        self.trainer_id = int(e.get("PADDLE_TRAINER_ID", "0"))
        self.num_trainers = int(e.get("PADDLE_TRAINERS", "1"))
        # collective (nccl2-mode) bootstrap endpoint: rank 0's address
        self.coordinator = e.get(
            "PADDLE_COORDINATOR",
            e.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:7777"))
        # pserver mode
        self.pserver_endpoints = [
            p for p in e.get("PSERVERS", e.get("PADDLE_PSERVERS", "")).split(",")
            if p
        ]
        self.current_endpoint = e.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def is_pserver(self):
        return self.training_role == "PSERVER"


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               local_device_ids=None):
    """jax.distributed.initialize wrapper; safe to call once per process.

    On the CPU backend a cross-process collectives implementation must be
    selected BEFORE the backend initializes (gloo plays the NCCL role there;
    reference nccl_helper.h:92-118 builds NCCLContextMap the same way) —
    without it each process sees only its own devices and the "cluster"
    silently degenerates to num_processes independent single-process runs.
    """
    if _initialized[0]:
        return
    if num_processes is None or num_processes <= 1:
        _initialized[0] = True
        return
    try:
        if jax.config.jax_cpu_collectives_implementation is None:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # config knob absent in this jax — TPU-only deployment
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    if jax.process_count() != num_processes:
        raise RuntimeError(
            f"distributed bootstrap incomplete: jax.process_count()="
            f"{jax.process_count()} != num_processes={num_processes} "
            f"(backend initialized before initialize()?)")
    _initialized[0] = True


def init_from_env():
    """Bootstrap multi-host from PADDLE_* env vars; returns ClusterEnv."""
    env = ClusterEnv()
    if env.num_trainers > 1 and not env.is_pserver:
        initialize(
            coordinator_address=env.coordinator,
            num_processes=env.num_trainers,
            process_id=env.trainer_id,
        )
    return env


def is_initialized():
    return _initialized[0]
