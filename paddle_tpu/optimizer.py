"""Optimizers: emit optimizer ops into the program.

Reference parity: python/paddle/fluid/optimizer.py (Optimizer:36 base,
minimize:231 = append_backward + clip/regularization + optimization pass;
subclasses SGD/Momentum/Adagrad/Adam/Adamax/DecayedAdagrad at :257-557, plus
Adadelta/RMSProp/ModelAverage). Because the optimizer ops land in the same
traced program as forward/backward, the entire training step compiles to one
XLA computation — weight update fusion comes for free.
"""

import math

from .core.framework import (
    Parameter,
    Variable,
    default_main_program,
    default_startup_program,
)
from .backward import append_backward
from . import unique_name
from .clip import append_gradient_clip_ops, error_clip_callback
from .regularizer import append_regularization_ops

__all__ = [
    "SGD", "Momentum", "Adagrad", "Adam", "Adamax", "DecayedAdagrad",
    "Adadelta", "RMSProp", "Optimizer", "SGDOptimizer", "MomentumOptimizer",
    "AdagradOptimizer", "AdamOptimizer", "AdamaxOptimizer",
    "DecayedAdagradOptimizer", "AdadeltaOptimizer", "RMSPropOptimizer",
    "Ftrl", "FtrlOptimizer", "ProximalGD", "ProximalGDOptimizer",
    "ProximalAdagrad", "ProximalAdagradOptimizer", "ModelAverage",
]


class Optimizer:
    def __init__(self, learning_rate, regularization=None, LearningRateDecay=None):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError("learning rate should be float or Variable")
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = {}  # {accum_name: {param_name: var}}
        self.helper = None
        # program pair the current optimization pass targets (set by
        # _create_optimization_pass; falls back to the defaults)
        self._target_main = None
        self._target_startup = None

    @property
    def _main(self):
        return self._target_main or default_main_program()

    @property
    def _startup(self):
        return self._target_startup or default_startup_program()

    # -- learning rate ------------------------------------------------------
    def _create_global_learning_rate(self, program, startup_program):
        lr = self._learning_rate_map.get(program)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program] = self._learning_rate
            return
        name = unique_name.generate("learning_rate")
        var = program.global_block().create_var(
            name=name, shape=(1,), dtype="float32", persistable=True
        )
        startup_program.global_block().create_var(
            name=name, shape=(1,), dtype="float32", persistable=True
        )
        startup_program.global_block().append_op(
            "fill_constant",
            {},
            {"Out": [name]},
            {"shape": [1], "value": float(self._learning_rate), "dtype": "float32"},
        )
        self._learning_rate_map[program] = var

    def _global_learning_rate(self, program=None):
        if program is None:
            program = self._main
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = param.optimize_attr.get("learning_rate", 1.0) if param.optimize_attr else 1.0
        lr = self._global_learning_rate()
        if param_lr == 1.0:
            return lr
        block = self._main.global_block()
        scaled = block.create_var(
            name=unique_name.generate(param.name + "_lr"), shape=(1,), dtype="float32"
        )
        block.append_op(
            "scale", {"X": [lr]}, {"Out": [scaled]}, {"scale": float(param_lr)}
        )
        return scaled

    # -- accumulators -------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _add_accumulator(self, name, param, dtype="float32", fill_value=0.0, shape=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            raise Exception(f"Accumulator {name} already exists for parameter {param.name}")
        self._accumulators.setdefault(name, {})
        main = self._main
        startup = self._startup
        var_name = unique_name.generate(f"{param.name}_{name}")
        shape = list(shape if shape is not None else param.shape)
        var = main.global_block().create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True
        )
        startup.global_block().create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True
        )
        startup.global_block().append_op(
            "fill_constant",
            {},
            {"Out": [var_name]},
            {"shape": shape, "value": float(fill_value), "dtype": dtype},
        )
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- the optimization pass ---------------------------------------------
    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block):
        pass

    def _create_optimization_pass(self, parameters_and_grads, loss, startup_program=None):
        program = loss.block.program
        startup = startup_program or default_startup_program()
        self._target_main, self._target_startup = program, startup
        self._create_global_learning_rate(program, startup)
        block = program.global_block()
        self._create_accumulators(
            block, [p for p, g in parameters_and_grads if p.trainable]
        )
        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None or not param_and_grad[0].trainable:
                continue
            with program.optimized_guard(param_and_grad):
                optimize_ops.append(self._append_optimize_op(block, param_and_grad))
        self._finish_update(block)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        """append_backward + regularization + clip + optimizer ops
        (reference optimizer.py:231)."""
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads, self.regularization)
        optimize_ops = self._create_optimization_pass(params_grads, loss, startup_program)
        return optimize_ops, params_grads


# -- ZeRO-1 shard metadata ---------------------------------------------------
# Per optimizer-op type: the param-shaped accumulator slots, as
# (input_slot, output_slot) pairs. parallel.zero1 uses this to rewrite each
# update onto a 1/N shard of the parameter: the listed accumulators are
# stored shard-layout ([num_shards, shard_numel], zero-padded), everything
# else (LearningRate, Beta*Pow) stays replicated. Only ops listed here are
# sharded; an op type is eligible when its update is elementwise over the
# param AND numerically inert on zero-padded lanes (zero grad + zero accum
# must produce zero accum out and a finite ParamOut — the padded lanes are
# sliced away before the param write-back, but NaN/Inf there would trip
# FLAGS_debug_nans). ftrl and proximal_adagrad divide by a zero-initialized
# accumulator on padded lanes, so they stay on the replicated path.
ZERO1_SHARDABLE_SLOTS = {
    "sgd": [],
    "momentum": [("Velocity", "VelocityOut")],
    "adam": [("Moment1", "Moment1Out"), ("Moment2", "Moment2Out")],
    "adagrad": [("Moment", "MomentOut")],
    "adamax": [("Moment", "MomentOut"), ("InfNorm", "InfNormOut")],
    "decayed_adagrad": [("Moment", "MomentOut")],
    "adadelta": [("AvgSquaredGrad", "AvgSquaredGradOut"),
                 ("AvgSquaredUpdate", "AvgSquaredUpdateOut")],
    "rmsprop": [("MeanSquare", "MeanSquareOut"), ("Moment", "MomentOut")],
    "proximal_gd": [],
}


class SGDOptimizer(Optimizer):
    """reference optimizer.py:257"""

    def __init__(self, learning_rate, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            "sgd",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            {"ParamOut": [param_and_grad[0]]},
        )


class MomentumOptimizer(Optimizer):
    """reference optimizer.py:283"""

    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = bool(use_nesterov)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator(self._velocity_acc_str, param_and_grad[0])
        return block.append_op(
            "momentum",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Velocity": [velocity],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            {"ParamOut": [param_and_grad[0]], "VelocityOut": [velocity]},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class AdagradOptimizer(Optimizer):
    """reference optimizer.py:327"""

    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1.0e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            "adagrad",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            {"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            {"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    """reference optimizer.py:368"""

    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._beta1_pow_acc = None
        self._beta2_pow_acc = None

    def _create_accumulators(self, block, parameters):
        main = self._main
        startup = self._startup

        def global_acc(name, init):
            var_name = unique_name.generate(name)
            var = main.global_block().create_var(
                name=var_name, shape=(1,), dtype="float32", persistable=True
            )
            startup.global_block().create_var(
                name=var_name, shape=(1,), dtype="float32", persistable=True
            )
            startup.global_block().append_op(
                "fill_constant",
                {},
                {"Out": [var_name]},
                {"shape": [1], "value": float(init), "dtype": "float32"},
            )
            return var

        self._beta1_pow_acc = global_acc("beta1_pow_acc", self._beta1)
        self._beta2_pow_acc = global_acc("beta2_pow_acc", self._beta2)
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment1 = self._get_accumulator(self._moment1_acc_str, param_and_grad[0])
        moment2 = self._get_accumulator(self._moment2_acc_str, param_and_grad[0])
        return block.append_op(
            "adam",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Moment1": [moment1],
                "Moment2": [moment2],
                "Beta1Pow": [self._beta1_pow_acc],
                "Beta2Pow": [self._beta2_pow_acc],
            },
            {
                "ParamOut": [param_and_grad[0]],
                "Moment1Out": [moment1],
                "Moment2Out": [moment2],
            },
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )

    def _finish_update(self, block):
        """update beta1/beta2 power accumulators (reference :459-471)."""
        block.append_op(
            "scale",
            {"X": [self._beta1_pow_acc]},
            {"Out": [self._beta1_pow_acc]},
            {"scale": self._beta1},
        )
        block.append_op(
            "scale",
            {"X": [self._beta2_pow_acc]},
            {"Out": [self._beta2_pow_acc]},
            {"scale": self._beta2},
        )


class AdamaxOptimizer(Optimizer):
    """reference optimizer.py:473"""

    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._beta1_pow_acc = None

    def _create_accumulators(self, block, parameters):
        main = self._main
        startup = self._startup
        var_name = unique_name.generate("beta1_pow_acc")
        var = main.global_block().create_var(
            name=var_name, shape=(1,), dtype="float32", persistable=True
        )
        startup.global_block().create_var(
            name=var_name, shape=(1,), dtype="float32", persistable=True
        )
        startup.global_block().append_op(
            "fill_constant",
            {},
            {"Out": [var_name]},
            {"shape": [1], "value": float(self._beta1), "dtype": "float32"},
        )
        self._beta1_pow_acc = var
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        inf_norm = self._get_accumulator(self._inf_norm_acc_str, param_and_grad[0])
        return block.append_op(
            "adamax",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Moment": [moment],
                "InfNorm": [inf_norm],
                "Beta1Pow": [self._beta1_pow_acc],
            },
            {
                "ParamOut": [param_and_grad[0]],
                "MomentOut": [moment],
                "InfNormOut": [inf_norm],
            },
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )

    def _finish_update(self, block):
        block.append_op(
            "scale",
            {"X": [self._beta1_pow_acc]},
            {"Out": [self._beta1_pow_acc]},
            {"scale": self._beta1},
        )


class DecayedAdagradOptimizer(Optimizer):
    """reference optimizer.py:557"""

    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1.0e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            "decayed_adagrad",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            {"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            {"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    """reference optimizer.py:601"""

    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1.0e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        asg = self._get_accumulator(self._avg_squared_grad_acc_str, param_and_grad[0])
        asu = self._get_accumulator(self._avg_squared_update_acc_str, param_and_grad[0])
        return block.append_op(
            "adadelta",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "AvgSquaredGrad": [asg],
                "AvgSquaredUpdate": [asu],
            },
            {
                "ParamOut": [param_and_grad[0]],
                "AvgSquaredGradOut": [asg],
                "AvgSquaredUpdateOut": [asu],
            },
            {"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    """reference optimizer.py:683"""

    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"

    def __init__(self, learning_rate, rho=0.95, epsilon=1.0e-6, momentum=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum = self._get_accumulator(self._momentum_acc_str, param_and_grad[0])
        mean_square = self._get_accumulator(self._mean_square_acc_str, param_and_grad[0])
        return block.append_op(
            "rmsprop",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Moment": [momentum],
                "MeanSquare": [mean_square],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            {
                "ParamOut": [param_and_grad[0]],
                "MomentOut": [momentum],
                "MeanSquareOut": [mean_square],
            },
            {"epsilon": self._epsilon, "decay": self._rho, "momentum": self._momentum},
        )


class ModelAverage(Optimizer):
    """reference optimizer.py:818 — running average of parameters.

    Maintains sum accumulators updated each step; `apply()` context swaps
    averaged params in (for eval), `restore()` swaps back.
    """

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        super().__init__(0.0, **kwargs)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = []
        main = default_main_program()
        for p in main.global_block().all_parameters():
            if p.do_model_average is not False:
                self.params_grads.append((p, None))
        block = main.global_block()
        self._sums = {}
        self._steps = None
        self._create_accumulators(block, [p for p, g in self.params_grads])
        for p, g in self.params_grads:
            block.append_op(
                "sum",
                {"X": [self._sums[p.name], p]},
                {"Out": [self._sums[p.name]]},
            )

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._sums[p.name] = self._add_accumulator("sum_acc", p)

    def apply(self, executor, need_restore=True):
        import contextlib
        import numpy as np
        from .core.scope import global_scope

        @contextlib.contextmanager
        def _guard():
            scope = global_scope()
            backup = {}
            for p, _ in self.params_grads:
                backup[p.name] = scope.find_var(p.name)
                s = scope.find_var(self._sums[p.name].name)
                # steps approximated by sum count via accumulated scale
                backup_val = np.asarray(backup[p.name])
                avg = np.asarray(s)
                steps = max(1, getattr(self, "_n_steps", 1))
                scope.set_var(p.name, (avg / steps).astype(backup_val.dtype))
            try:
                yield
            finally:
                if need_restore:
                    for name, val in backup.items():
                        scope.set_var(name, val)

        return _guard()

    def restore(self, executor):
        pass


class FtrlOptimizer(Optimizer):
    """FTRL-proximal (reference operators/ftrl_op.cc; optimizer surface
    parity with the op library)."""

    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        sq = self._get_accumulator(self._squared_acc_str, param_and_grad[0])
        lin = self._get_accumulator(self._linear_acc_str, param_and_grad[0])
        return block.append_op(
            "ftrl",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "SquaredAccumulator": [sq],
                "LinearAccumulator": [lin],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            {
                "ParamOut": [param_and_grad[0]],
                "SquaredAccumOut": [sq],
                "LinearAccumOut": [lin],
            },
            {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class ProximalGDOptimizer(Optimizer):
    """Proximal gradient descent with l1/l2 regularization (reference
    operators/proximal_gd_op.cc; optimizer surface parity with the op
    library)."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "proximal_gd"
        self._l1 = l1
        self._l2 = l2

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            "proximal_gd",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            {"ParamOut": [param_and_grad[0]]},
            {"l1": self._l1, "l2": self._l2},
        )


class ProximalAdagradOptimizer(Optimizer):
    """Proximal Adagrad (reference operators/proximal_adagrad_op.cc)."""

    _moment_acc_str = "moment"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "proximal_adagrad"
        self._l1 = l1
        self._l2 = l2

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(
            self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            "proximal_adagrad",
            {
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            {"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            {"l1": self._l1, "l2": self._l2},
        )


# aliases (reference exposes both short and long names)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer
