"""Serving engine: dynamic batching onto a bucketed compile cache.

The device wins throughput when every dispatch is (a) large enough to
amortize the per-dispatch overhead and (b) a shape XLA has already
compiled. `Server` provides both: `submit(feed)` enqueues one request
into a thread-safe admission-controlled queue and returns a Future; a
batcher thread coalesces pending requests up to `max_batch` rows or
`max_wait_ms`, pads the coalesced batch to the bucket ladder
(serve/buckets.py), and round-robins the padded batches across replica
executors — one per accelerator device — whose compile caches were
AOT-warmed over every bucket before the server reported ready. Workers
slice each request's rows back out of the batch result and resolve its
Future, stamping queue/pad/dispatch/readback phase latencies plus
p50/p95/p99 SLO tracking into the monitor registry.

Zero-steady-state-compile contract: after `start()` returns, dispatches
of any admissible batch hit an already-compiled executable — asserted
by `stats()["steady_state_compiles"]` staying 0 (and by the monitor's
compile_cache_misses counter staying flat). It requires the feed vars'
non-batch dims to be fully specified (the usual `layers.data` case);
requests must match those dims exactly.
"""

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError

import numpy as np

from .. import monitor
from .. import trace as _trace
from ..core.framework import Program, Variable
from ..core.places import CPUPlace, TPUPlace
from ..core.scope import Scope, scope_guard
from ..executor import Executor, as_numpy
from ..trainer import check_and_get_place
from .buckets import bucket_for, ladder, pad_rows

__all__ = ["ServeConfig", "Server", "ModelSet", "ServeError",
           "ServerOverloaded", "ServerClosed", "ServerDraining",
           "UnknownModel", "SERVE_MS_BUCKETS"]

# serving latencies live well below training-step scale: extend the
# monitor's default ms ladder downward so sub-ms queue/pad phases and
# single-digit-ms p50s land in resolving buckets instead of one bin
SERVE_MS_BUCKETS = (0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 3.0, 5.0, 7.5, 10.0,
                    15.0, 20.0, 30.0, 50.0, 75.0, 100.0, 200.0, 500.0,
                    1000.0, 2000.0, 5000.0, float("inf"))


def _resolve(future, result=None, exc=None):
    """Resolve `future` if still pending; returns whether it was resolved.

    Clients own the Future and may cancel it (a `result(timeout)` caller
    giving up does exactly that), so a plain set_result/set_exception can
    raise InvalidStateError — which must never escape into the batcher or
    a worker thread."""
    try:
        if future.done():
            return False
        if exc is not None:
            future.set_exception(exc)
        else:
            future.set_result(result)
        return True
    except InvalidStateError:
        return False


class ServeError(RuntimeError):
    """Base class for serving-engine errors."""


class ServerOverloaded(ServeError):
    """Admission control rejected the request (queue at max_queue_rows)."""


class ServerClosed(ServeError):
    """The server was stopped before (or while) the request was served."""


class ServerDraining(ServerClosed):
    """The server is lame-duck: finishing queued/in-flight work but no
    longer admitting. Subclasses ServerClosed so every existing "server
    is going away" handler (HTTP 503, router failover) already does the
    right thing; the distinct type lets frontends add the
    `Connection: close` hint."""


class UnknownModel(ServeError):
    """The request named a model this server does not host — the HTTP
    frontend's 404 (deterministic, never retried by the fleet router)."""


class ServeConfig:
    """Tuning knobs for one Server.

    max_batch        largest batch (in rows) one dispatch carries; also
                     the top rung of the bucket ladder.
    max_wait_ms      how long the batcher holds an underfull batch open
                     for more requests before flushing it. The knob is
                     the latency/throughput trade: 0 serves every request
                     solo (lowest latency, worst QPS), larger values fill
                     buckets at light load.
    buckets          explicit bucket ladder (rows); None = powers of two
                     up to max_batch.
    max_queue_rows   admission-control bound on queued rows; submit()
                     raises ServerOverloaded beyond it (bounded
                     backpressure instead of unbounded latency).
                     None = 8 * max_batch.
    replicas         executor replicas the batcher round-robins over, one
                     per accelerator device (TPUPlace(i)); parameters
                     are copied to each replica's device at start().
    dispatch_depth   formed batches allowed in flight per replica before
                     the batcher blocks (keeps the device queue shallow
                     while still overlapping host batching with device
                     compute).
    slo_ms           latency objective; requests slower than this count
                     into serve_slo_violations_total. None = untracked.
    """

    def __init__(self, max_batch=8, max_wait_ms=2.0, buckets=None,
                 max_queue_rows=None, replicas=1, dispatch_depth=2,
                 slo_ms=None):
        self.max_batch = int(max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self.buckets = ladder(self.max_batch, buckets)
        self.max_queue_rows = (8 * self.max_batch if max_queue_rows is None
                               else int(max_queue_rows))
        if self.max_queue_rows < self.max_batch:
            raise ValueError(
                f"max_queue_rows {self.max_queue_rows} < max_batch "
                f"{self.max_batch}: the queue could never fill one batch")
        self.replicas = int(replicas)
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.dispatch_depth = max(1, int(dispatch_depth))
        self.slo_ms = None if slo_ms is None else float(slo_ms)


class _Request:
    __slots__ = ("feed", "rows", "future", "t_submit", "t_picked",
                 "tctx", "tparent")

    def __init__(self, feed, rows):
        self.feed = feed
        self.rows = rows
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.t_picked = None
        # trace identity, pre-allocated at submit() when tracing is on:
        # the batch span links to tctx long before the request span
        # itself is recorded (fan-in attribution survives coalescing)
        self.tctx = None
        self.tparent = None


class _RequestQueue:
    """Row-accounted FIFO with non-blocking admission control."""

    def __init__(self, max_rows):
        self._max_rows = max_rows
        self._dq = deque()
        self._rows = 0
        self._closed = False
        self._sealed = False
        self._cond = threading.Condition()

    @property
    def rows(self):
        with self._cond:
            return self._rows

    @property
    def drained(self):
        """True once sealed AND empty — the batcher's drain-exit signal."""
        with self._cond:
            return self._sealed and not self._dq

    def put(self, req):
        with self._cond:
            if self._closed:
                raise ServerClosed("server is stopped")
            if self._sealed:
                raise ServerDraining("server is draining")
            if self._rows + req.rows > self._max_rows:
                raise ServerOverloaded(
                    f"queue at {self._rows}/{self._max_rows} rows; "
                    f"request of {req.rows} rows rejected")
            self._dq.append(req)
            self._rows += req.rows
            self._cond.notify()

    def get(self, timeout):
        """Next request, or None on timeout (and on close/seal with an
        empty queue — the caller checks the stop/drain flags)."""
        deadline = time.perf_counter() + timeout
        with self._cond:
            while not self._dq:
                remaining = deadline - time.perf_counter()
                if self._closed or self._sealed or remaining <= 0:
                    return None
                self._cond.wait(remaining)
            req = self._dq.popleft()
            self._rows -= req.rows
            return req

    def seal(self):
        """Lame-duck admission stop: put() raises ServerDraining, but —
        unlike close() — everything already queued is still handed out,
        so a draining server SERVES its backlog instead of failing it."""
        with self._cond:
            self._sealed = True
            self._cond.notify_all()

    def close(self):
        """Stop admitting; hand back whatever is still queued."""
        with self._cond:
            self._closed = True
            drained = list(self._dq)
            self._dq.clear()
            self._rows = 0
            self._cond.notify_all()
        return drained


class _BoundedQueue:
    """Blocking bounded FIFO for formed batches (stdlib queue.Queue minus
    the task_done bookkeeping; kept tiny so dispatch depth stays visible)."""

    def __init__(self, depth):
        self._dq = deque()
        self._depth = depth
        self._closed = False
        self._cond = threading.Condition()

    def put(self, item):
        with self._cond:
            while len(self._dq) >= self._depth and not self._closed:
                self._cond.wait()
            if self._closed:
                raise ServerClosed("dispatch queue closed")
            self._dq.append(item)
            self._cond.notify_all()

    def get(self):
        """Next item; None once the queue is closed AND drained (in-flight
        batches enqueued before close() are still handed out)."""
        with self._cond:
            while not self._dq and not self._closed:
                self._cond.wait()
            if not self._dq:
                return None
            item = self._dq.popleft()
            self._cond.notify_all()
            return item

    def close(self):
        """Stop accepting items: wakes blocked put() (which then raises
        ServerClosed) and lets get() return None once empty."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain(self):
        """Pop and return everything still queued (post-join leftovers)."""
        with self._cond:
            items = list(self._dq)
            self._dq.clear()
            self._cond.notify_all()
            return items


class Server:
    """Batched low-latency inference over a (transpiled) inference Program.

        server = serve.Server(program, feed_names, fetch_list,
                              place=fluid.TPUPlace(0),
                              config=serve.ServeConfig(max_batch=16))
        server.start()                      # AOT-warms every bucket
        fut = server.submit({"x": one_example})
        y, = fut.result()
        server.stop()

    submit() accepts one example (arrays shaped like the feed var minus
    the batch axis) or a pre-batched group of rows (leading batch axis,
    up to max_batch); the Future resolves to the fetch list sliced back
    to exactly the submitted rows.
    """

    def __init__(self, program, feed_names, fetch_list, place=None,
                 scope=None, config=None, model=None):
        if not isinstance(program, Program):
            raise TypeError("program must be a Program")
        self.program = program
        # optional model name: when set, queue/latency/SLO series are
        # ALSO emitted with a {model=} label (the unlabeled aggregates
        # stay, so existing dashboards keep working) and stats() carries
        # a per-model block the fleet's SLO-weighted routing reads
        self.model = None if model is None else str(model)
        self.config = config or ServeConfig()
        self.place = check_and_get_place(place)
        self.scope = scope if scope is not None else Scope()
        self.feed_names = list(feed_names)
        self.fetch_list = [v if isinstance(v, Variable) else
                           program.global_block().var(str(v))
                           for v in fetch_list]
        gb = program.global_block()
        self._feed_vars = {}
        for n in self.feed_names:
            self._feed_vars[n] = gb.var(n)
        self._queue = _RequestQueue(self.config.max_queue_rows)
        self._dispatch_queues = []
        self._replicas = []       # [(executor, scope)]
        self._threads = []
        self._rr = 0
        self._stop = False
        self._ready = False
        self._draining = False
        self._batcher_thread = None
        self._warm_entries = 0
        self._lock = threading.Lock()
        # per-server tallies mirrored next to the process-global registry:
        # the registry series are unlabeled and shared, so stats() and
        # latency_percentiles() read these to stay correct when several
        # Servers live in one process
        self._own = {name: monitor.Counter(name) for name in
                     ("requests", "rejected", "rows", "padded_rows",
                      "slo_violations")}
        self._own_request_ms = monitor.Histogram(
            "serve_request_ms", buckets=SERVE_MS_BUCKETS)

    # -- construction helpers -------------------------------------------
    @classmethod
    def from_inference_model(cls, dirname, place=None, config=None):
        """Serve a `save_inference_model` directory."""
        from .. import io as io_mod

        place = check_and_get_place(place)
        scope = Scope()
        exe = Executor(place)
        with scope_guard(scope):
            program, feed_names, fetch_targets = io_mod.load_inference_model(
                dirname, exe)
        return cls(program, feed_names, fetch_targets, place=place,
                   scope=scope, config=config)

    @classmethod
    def from_infer_func(cls, infer_func, param_path, place=None,
                        config=None, transpile=True):
        """Build the inference program like Inferencer does, load params,
        and (by default) run the InferenceTranspiler's numeric folding
        before serving."""
        from .. import io as io_mod
        from .. import unique_name
        from ..core.framework import program_guard
        from ..transpiler import InferenceTranspiler

        place = check_and_get_place(place)
        program = Program()
        with program_guard(program):
            with unique_name.guard():
                targets = infer_func()
        if not isinstance(targets, (list, tuple)):
            targets = [targets]
        scope = Scope()
        exe = Executor(place)
        with scope_guard(scope):
            io_mod.load_params(exe, param_path, program)
        if transpile:
            InferenceTranspiler().transpile(program, place, scope=scope)
        gb = program.global_block()
        feed_names = [n for n, v in gb.vars.items()
                      if getattr(v, "is_data", False)]
        return cls(program, feed_names, targets, place=place, scope=scope,
                   config=config)

    # -- lifecycle ------------------------------------------------------
    def start(self, warm=True):
        """Build the replicas, AOT-precompile every bucket on each, and
        start the batcher/worker threads. The server reports ready only
        after warmup, so the first real request never eats a compile."""
        with self._lock:
            if self._threads:
                raise ServeError("server already started")
            if self._stop:
                raise ServerClosed("server was stopped")
            self._build_replicas()
            if warm:
                self._warmup()
            self._warm_entries = self._cache_entries()
            for i in range(self.config.replicas):
                q = _BoundedQueue(self.config.dispatch_depth)
                self._dispatch_queues.append(q)
                t = threading.Thread(target=self._worker, args=(i, q),
                                     name=f"serve-worker-{i}", daemon=True)
                self._threads.append(t)
            bt = threading.Thread(target=self._batcher, name="serve-batcher",
                                  daemon=True)
            self._batcher_thread = bt
            self._threads.append(bt)
            for t in self._threads:
                t.start()
            self._ready = True
            self._gauge("serve_ready").set(1)
        return self

    def __enter__(self):
        if not self._threads:
            self.start()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        return False

    def ready(self):
        return self._ready and not self._stop and not self._draining

    def state(self):
        """Lifecycle state: created -> serving -> (draining ->) stopped.
        The HTTP /healthz endpoint maps this straight onto health-probe
        answers, so the fleet router can tell lame-duck from dead."""
        if self._stop:
            return "stopped"
        if self._draining:
            return "draining"
        if self._ready:
            return "serving"
        return "created"

    def draining(self):
        return self._draining and not self._stop

    def drain(self, timeout=30.0):
        """Lame-duck shutdown: stop admitting (submit() raises
        ServerDraining), SERVE everything already queued, let workers
        finish every in-flight batch (the _BoundedQueue close/drain
        contract), then stop clean — the zero-dropped-request half of a
        rolling restart. Returns True when fully drained within
        `timeout`, False if threads are still busy (call again, or
        stop() to abort the stragglers)."""
        with self._lock:
            if self._stop:
                return True
            if not self._threads:
                raise ServeError("server not started")
            self._draining = True
        t0 = time.perf_counter()
        deadline = t0 + float(timeout)
        self._gauge("serve_draining",
                    help="1 while the server is lame-duck").set(1)
        # seal, don't close: queued requests are served, not failed
        self._queue.seal()
        bt = self._batcher_thread
        if bt is not None:
            bt.join(max(0.0, deadline - time.perf_counter()))
            if bt.is_alive():
                return False
        # batcher has flushed the backlog; closing lets each worker hand
        # out its remaining in-flight batches and exit on drained+closed
        for q in self._dispatch_queues:
            q.close()
        for t in self._threads:
            t.join(max(0.0, deadline - time.perf_counter()))
            if t.is_alive():
                return False
        # defensive: a worker that died mid-drain may strand a batch
        for q in self._dispatch_queues:
            for item in q.drain():
                self._fail_batch(item[0], ServerDraining("server drained"))
        with self._lock:
            self._stop = True
            self._ready = False
        reg = monitor.registry()
        reg.counter("serve_drains_total",
                    help="lame-duck drains completed").inc()
        self._gauge("serve_drain_duration_ms",
                    help="wall time of the last lame-duck drain").set(
            (time.perf_counter() - t0) * 1000.0)
        self._gauge("serve_draining").set(0)
        self._gauge("serve_ready").set(0)
        return True

    def stop(self):
        """Stop admitting, fail queued requests with ServerClosed, let
        already-dispatched batches finish, and join the threads. Any batch
        a dead or timed-out worker left behind is failed too — no Future
        handed out by submit() is ever stranded unresolved."""
        with self._lock:
            if self._stop:
                return
            self._stop = True
            self._ready = False
        for req in self._queue.close():
            _resolve(req.future, exc=ServerClosed("server stopped"))
        # closing wakes a batcher blocked in put() (it fails that batch)
        # and lets each worker drain its in-flight batches, then exit
        for q in self._dispatch_queues:
            q.close()
        for t in self._threads:
            t.join(timeout=30.0)
        for q in self._dispatch_queues:
            for item in q.drain():
                self._fail_batch(item[0], ServerClosed("server stopped"))
        self._gauge("serve_ready").set(0)

    def _replica_place(self, i):
        """Replica i's device: TPUPlace(i) walks the accelerator list (and
        on an all-CPU host, XLA's virtual host devices); a CPU server
        keeps every replica on the host place."""
        if isinstance(self.place, TPUPlace):
            return type(self.place)(
                (getattr(self.place, "device_id", 0) + i))
        return CPUPlace()

    def _build_replicas(self):
        """Replica 0 serves from the caller's scope; further replicas get
        a scope holding device-local copies of every persistable var (the
        round-robin fan-out — each replica owns one device end to end)."""
        import jax

        from ..core.places import jax_device_for

        persistables = [
            n for n, v in self.program.global_block().vars.items()
            if v.persistable and self.scope.find_var(n) is not None]
        for i in range(self.config.replicas):
            place = self._replica_place(i)
            if i == 0:
                scope = self.scope
            else:
                scope = Scope()
                dev = jax_device_for(place)
                for n in persistables:
                    scope.set_var(n, jax.device_put(
                        np.asarray(self.scope.find_var(n)), dev))
            self._replicas.append((Executor(place), scope))

    def _warmup(self):
        """One dummy dispatch per (replica, bucket): every admissible batch
        shape is compiled before the server reports ready."""
        t0 = time.perf_counter()
        for b in self.config.buckets:
            feed = {n: np.zeros((b,) + self._example_shape(n),
                                dtype=self._feed_dtype(n))
                    for n in self.feed_names}
            for exe, scope in self._replicas:
                outs = exe.run(self.program, feed=feed,
                               fetch_list=self.fetch_list, scope=scope,
                               return_numpy=False)
                for o in outs:  # fence: the executable must be built NOW
                    as_numpy(o)
        self._gauge(
            "serve_warmup_ms",
            help="AOT bucket-precompile wall time at server start").set(
            (time.perf_counter() - t0) * 1000.0)

    # -- request path ---------------------------------------------------
    def _example_shape(self, name):
        var = self._feed_vars[name]
        shape = list(var.shape or [])[1:]
        return tuple(1 if (d is None or d < 0) else int(d) for d in shape)

    def _feed_dtype(self, name):
        return self._feed_vars[name].dtype or "float32"

    def _normalize(self, feed):
        """-> ({name: [rows, ...] array}, rows). A value shaped like the
        feed var minus its batch axis counts as one row."""
        if not isinstance(feed, dict):
            raise ValueError("feed must be a dict of {feed_name: array}")
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise ValueError(f"feed missing {missing}")
        extra = [n for n in feed if n not in self._feed_vars]
        if extra:
            raise ValueError(f"unknown feed names {extra}")
        rows = None
        out = {}
        for n in self.feed_names:
            var = self._feed_vars[n]
            v = np.asarray(feed[n])
            rank = len(var.shape or [])
            if v.ndim == rank - 1:
                v = v[None, ...]
            elif v.ndim != rank:
                raise ValueError(
                    f"feed {n!r} rank {v.ndim} matches neither one example "
                    f"(rank {rank - 1}) nor a row batch (rank {rank})")
            if var.dtype is not None and str(v.dtype) != var.dtype:
                v = v.astype(var.dtype)
            if rows is None:
                rows = v.shape[0]
            elif v.shape[0] != rows:
                raise ValueError(
                    f"feed {n!r} has {v.shape[0]} rows, others have {rows}")
            out[n] = v
        if rows is None or rows < 1:
            raise ValueError("empty request")
        if rows > self.config.max_batch:
            raise ValueError(
                f"request of {rows} rows exceeds max_batch "
                f"{self.config.max_batch}; split it client-side")
        return out, rows

    def resolve_model(self, name=None):
        """-> self when `name` is this server's model (or None);
        UnknownModel otherwise — the single-model end of the multi-model
        HTTP contract."""
        if name is None or name == self.model:
            return self
        raise UnknownModel(
            f"unknown model {name!r}; this server hosts "
            f"{self.model!r}" if self.model else
            f"unknown model {name!r}; this server is unnamed")

    def submit(self, feed, model=None):
        """Enqueue one request; returns a concurrent.futures.Future that
        resolves to the fetch-list arrays sliced to the request's rows.
        Raises ServerOverloaded beyond max_queue_rows (bounded
        backpressure), ServerClosed after stop(), and UnknownModel when
        `model` names something this server does not host."""
        self.resolve_model(model)
        if self._stop:
            raise ServerClosed("server is stopped")
        if self._draining:
            raise ServerDraining("server is draining")
        if not self._ready:
            raise ServeError("server not started (call start() first)")
        vals, rows = self._normalize(feed)
        req = _Request(vals, rows)
        if _trace.enabled():
            # inherit the submitter's context (the HTTP handler's
            # serve.http span) so the whole lifecycle is ONE trace
            req.tparent = _trace.current()
            req.tctx = _trace.new_context(parent=req.tparent)
        reg = monitor.registry()
        try:
            self._queue.put(req)
        except ServerOverloaded:
            self._own["rejected"].inc()
            reg.counter("serve_rejected_total",
                        help="requests rejected by admission control").inc()
            if self.model is not None:
                reg.counter("serve_rejected_total", model=self.model).inc()
            _trace.maybe_dump("server_overloaded")
            raise
        self._own["requests"].inc()
        reg.counter("serve_requests_total",
                    help="requests admitted to the serve queue").inc()
        if self.model is not None:
            reg.counter("serve_requests_total", model=self.model).inc()
        self._set_queue_gauge()
        return req.future

    def infer(self, feed, timeout=None):
        """Blocking convenience: submit + result."""
        return self.submit(feed).result(timeout=timeout)

    # -- batcher / workers ----------------------------------------------
    def _batcher(self):
        held = None
        while True:
            req = held if held is not None else self._queue.get(timeout=0.05)
            held = None
            if req is None:
                # drain exit: the sealed queue is empty and nothing is
                # held — the backlog has been flushed, drain() can close
                # the dispatch queues
                if self._stop or (self._draining and self._queue.drained):
                    return
                continue
            if req.t_picked is None:
                req.t_picked = time.perf_counter()
            batch, rows = [req], req.rows
            # fairness: the batching window is anchored at the OLDEST
            # member's submit time, never re-opened. A request carried
            # over from a previous batch (held) or aged in the queue has
            # already spent its window — it ages AHEAD of fresh arrivals
            # and flushes at once (after a non-blocking greedy fill from
            # the backlog) instead of waiting out a fresh max_wait_ms,
            # which a steady trickle of full buckets could previously
            # impose on a held underfull remainder over and over.
            deadline = req.t_submit + self.config.max_wait_ms / 1000.0
            while rows < self.config.max_batch and not self._stop:
                remaining = deadline - time.perf_counter()
                nxt = self._queue.get(timeout=max(0.0, remaining))
                if nxt is None:
                    break
                if nxt.t_picked is None:
                    nxt.t_picked = time.perf_counter()
                if rows + nxt.rows > self.config.max_batch:
                    held = nxt  # opens the NEXT batch
                    break
                batch.append(nxt)
                rows += nxt.rows
            self._flush(batch, rows)
        # unreachable; stop() drains the queue

    def _flush(self, batch, rows):
        t0 = time.perf_counter()
        bucket = bucket_for(rows, self.config.buckets)
        feed = {}
        for n in self.feed_names:
            parts = [r.feed[n] for r in batch]
            feed[n] = parts[0] if len(parts) == 1 else \
                np.concatenate(parts, axis=0)
        feed = pad_rows(feed, rows, bucket)
        pad_s = time.perf_counter() - t0
        reg = monitor.registry()
        reg.counter("serve_batches_total", help="batches dispatched",
                    bucket=str(bucket)).inc()
        self._own["rows"].inc(rows)
        reg.counter("serve_rows_total", help="request rows served").inc(rows)
        self._own["padded_rows"].inc(bucket - rows)
        reg.counter("serve_padded_rows_total",
                    help="ladder padding rows dispatched").inc(bucket - rows)
        reg.histogram("serve_batch_rows", help="rows per dispatched batch",
                      buckets=self.config.buckets).observe(rows)
        # the batch left the request queue: keep the depth gauge live for
        # /metrics scrapes, not just high-water marks from submit()
        self._set_queue_gauge()
        if self._stop:
            self._fail_batch(batch, ServerClosed("server stopped"))
            return
        q = self._dispatch_queues[self._rr]
        self._rr = (self._rr + 1) % len(self._dispatch_queues)
        try:
            # t0 anchors the serve.pad span; workers tolerate bare
            # 5-tuples (tests construct them directly)
            q.put((batch, feed, bucket, rows, pad_s, t0))
        except ServerClosed as e:
            self._fail_batch(batch, e)

    @staticmethod
    def _fail_batch(batch, exc):
        for r in batch:
            _resolve(r.future, exc=exc)

    def _worker(self, idx, q):
        exe, scope = self._replicas[idx]
        while True:
            item = q.get()
            if item is None:
                return
            batch, feed, bucket, rows, pad_s = item[:5]
            t_pad = item[5] if len(item) > 5 else None
            # fan-in span: ONE dispatch serves N coalesced requests, so
            # the batch span LINKS to every request's context instead of
            # parenting under any one of them; the executor's step span
            # parents under it via the attached thread-local context
            links = [r.tctx for r in batch if r.tctx is not None] \
                if _trace.enabled() else None
            bspan = _trace.span("serve.batch", kind="serve", links=links,
                                bucket=bucket, rows=rows, replica=idx)
            try:
                with bspan:
                    t0 = time.perf_counter()
                    outs = exe.run(self.program, feed=feed,
                                   fetch_list=self.fetch_list, scope=scope,
                                   return_numpy=False)
                    dispatch_s = time.perf_counter() - t0
                    t1 = time.perf_counter()
                    host = [np.asarray(as_numpy(o)) for o in outs]
                    readback_s = time.perf_counter() - t1
            except BaseException as e:  # noqa: BLE001 — fail the futures
                self._fail_batch(batch, e)
                continue
            offset = 0
            done = time.perf_counter()
            try:
                for r in batch:
                    res = [h[offset:offset + r.rows] for h in host]
                    offset += r.rows
                    # _resolve: a client-cancelled Future (result(timeout)
                    # expired) must not kill this worker thread
                    if _resolve(r.future, result=res):
                        self._record_request(r, pad_s, dispatch_s,
                                             readback_s, done, replica=idx,
                                             batch_ctx=bspan.ctx,
                                             t_pad=t_pad, t_dispatch=t0,
                                             t_readback=t1)
            except BaseException as e:  # noqa: BLE001 — fail the futures
                self._fail_batch(batch, e)

    def _gauge(self, name, help=""):
        return monitor.registry().gauge(name, help=help)

    def _set_queue_gauge(self):
        rows = self._queue.rows
        self._gauge("serve_queue_rows",
                    help="rows currently queued").set(rows)
        if self.model is not None:
            monitor.registry().gauge("serve_queue_rows",
                                     model=self.model).set(rows)

    def _record_request(self, req, pad_s, dispatch_s, readback_s, done,
                        replica, batch_ctx=None, t_pad=None,
                        t_dispatch=None, t_readback=None):
        reg = monitor.registry()
        total_ms = (done - req.t_submit) * 1000.0
        queue_ms = ((req.t_picked or req.t_submit) - req.t_submit) * 1000.0
        self._own_request_ms.observe(total_ms)
        reg.histogram("serve_request_ms",
                      help="submit-to-result request latency",
                      buckets=SERVE_MS_BUCKETS).observe(total_ms)
        if self.model is not None:
            reg.histogram("serve_request_ms", buckets=SERVE_MS_BUCKETS,
                          model=self.model).observe(total_ms)
        for phase, ms in (("queue", queue_ms), ("pad", pad_s * 1000.0),
                          ("dispatch", dispatch_s * 1000.0),
                          ("readback", readback_s * 1000.0)):
            reg.histogram("serve_request_phase_ms",
                          help="per-phase request latency",
                          buckets=SERVE_MS_BUCKETS,
                          phase=phase).observe(ms)
        reg.counter("serve_replica_requests_total",
                    help="requests served per replica",
                    replica=str(replica)).inc()
        slo = self.config.slo_ms
        violated = slo is not None and total_ms > slo
        if violated:
            self._own["slo_violations"].inc()
            reg.counter("serve_slo_violations_total",
                        help="requests exceeding ServeConfig.slo_ms").inc()
            if self.model is not None:
                reg.counter("serve_slo_violations_total",
                            model=self.model).inc()
        if req.tctx is not None and _trace.enabled():
            # retroactive lifecycle spans under the identity allocated at
            # submit(): root request span (linked to the batch that
            # carried it) + queue/pad/dispatch/readback children
            picked = req.t_picked or req.t_submit
            ctx = _trace.record(
                "serve.request", req.t_submit, done, kind="serve",
                ctx=req.tctx, parent=req.tparent,
                links=[batch_ctx] if batch_ctx is not None else None,
                attrs={"rows": req.rows, "replica": replica,
                       "total_ms": round(total_ms, 3),
                       "slo_violated": violated})
            _trace.record("serve.queue", req.t_submit, picked,
                          kind="serve", parent=ctx)
            if t_pad is not None:
                _trace.record("serve.pad", t_pad, t_pad + pad_s,
                              kind="serve", parent=ctx)
            if t_dispatch is not None:
                _trace.record("serve.dispatch", t_dispatch,
                              t_dispatch + dispatch_s, kind="serve",
                              parent=ctx)
            if t_readback is not None:
                _trace.record("serve.readback", t_readback,
                              t_readback + readback_s, kind="serve",
                              parent=ctx)
        if violated:
            _trace.maybe_dump("serve_slo")

    # -- visibility -----------------------------------------------------
    def _cache_entries(self):
        return sum(exe.compile_cache_info()["entries"]
                   for exe, _ in self._replicas)

    def _cache_aggregate(self):
        """Summed compile-cache counters across this server's executors.
        fresh compiles = L1 misses not satisfied by the L2 (an L2 hit —
        local file or fetched from the compile service — deserialized
        instead of compiling). The autoscale drill asserts a scale-out
        replica shows compile_cache_misses == 0 and remote hits > 0."""
        agg = {"l1_misses": 0, "l2_hits": 0, "l2_remote_hits": 0,
               "l2_remote_misses": 0, "l2_puts": 0, "l2_fallbacks": 0}
        for exe, _ in self._replicas:
            info = exe.compile_cache_info()
            l2 = info.get("l2") or {}
            agg["l1_misses"] += info.get("misses", 0)
            agg["l2_hits"] += l2.get("hits", 0)
            agg["l2_remote_hits"] += l2.get("remote_hits", 0)
            agg["l2_remote_misses"] += l2.get("remote_misses", 0)
            agg["l2_puts"] += l2.get("puts", 0)
            agg["l2_fallbacks"] += l2.get("fallbacks", 0)
        agg["misses"] = max(0, agg["l1_misses"] - agg["l2_hits"])
        return agg

    def latency_percentiles(self, *ps):
        """{p: ms} over requests served by THIS server (the registry's
        serve_request_ms series is shared process-wide)."""
        ps = ps or (50, 95, 99)
        return self._own_request_ms.percentiles(*ps)

    def stats(self):
        """One scrape of the serving metrics: counts, latency percentiles,
        SLO violations, and the zero-steady-state-compile check. All values
        are scoped to this server instance, matching compile_entries, even
        when several Servers share the process-global registry."""
        pct = self.latency_percentiles(50, 95, 99)
        rows = self._own["rows"].value
        padded = self._own["padded_rows"].value
        cache = self._cache_aggregate()
        models = {}
        if self.model is not None:
            models[self.model] = {
                "slo_ms": self.config.slo_ms,
                "queue_rows": self._queue.rows,
                "requests": self._own["requests"].value,
                "p99_ms": pct[99],
                "slo_violations": self._own["slo_violations"].value,
            }
        return {
            "model": self.model,
            "models": models,
            "ready": self.ready(),
            "state": self.state(),
            "draining": self.draining(),
            "replicas": self.config.replicas,
            "buckets": list(self.config.buckets),
            "max_wait_ms": self.config.max_wait_ms,
            "requests": self._own["requests"].value,
            "rejected": self._own["rejected"].value,
            "rows": rows,
            "padded_rows": padded,
            "pad_fraction": (padded / (rows + padded)) if rows else 0.0,
            "queue_rows": self._queue.rows,
            "p50_ms": pct[50], "p95_ms": pct[95], "p99_ms": pct[99],
            "slo_ms": self.config.slo_ms,
            "slo_violations": self._own["slo_violations"].value,
            "compile_entries": self._cache_entries(),
            "steady_state_compiles":
                self._cache_entries() - self._warm_entries,
            "compile_cache_misses": cache["misses"],
            "compile_cache": cache,
        }


class ModelSet:
    """N named one-shot Servers behind one frontend surface.

    The multi-model contract for the classic batcher: each model keeps
    its own Server (own queue, buckets, compile caches, SLO), and the
    set dispatches `submit(feed, model=...)` by name — the same surface
    the HTTP frontend and fleet router speak, so a ModelSet drops in
    anywhere a Server does. For iteration-level scheduling across
    models inside ONE step loop, use serve.continuous.ContinuousServer.
    """

    def __init__(self, servers, default=None):
        if not servers:
            raise ValueError("ModelSet needs at least one server")
        self.servers = dict(servers)
        for name, srv in self.servers.items():
            if srv.model is None:
                srv.model = str(name)
        self.default = str(default) if default is not None \
            else next(iter(self.servers))
        if self.default not in self.servers:
            raise ValueError(f"default {self.default!r} not in servers")

    @property
    def models(self):
        return self.servers

    def resolve_model(self, name=None):
        if name is None:
            return self.servers[self.default]
        srv = self.servers.get(str(name))
        if srv is None:
            raise UnknownModel(
                f"unknown model {name!r}; hosting "
                f"{sorted(self.servers)}")
        return srv

    def submit(self, feed, model=None):
        return self.resolve_model(model).submit(feed)

    def infer(self, feed, model=None, timeout=None):
        return self.submit(feed, model=model).result(timeout=timeout)

    # -- lifecycle (fan-out) --------------------------------------------
    def start(self, warm=True):
        for srv in self.servers.values():
            srv.start(warm=warm)
        return self

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        return False

    def stop(self):
        for srv in self.servers.values():
            srv.stop()

    def drain(self, timeout=30.0):
        ok = True
        for srv in self.servers.values():
            ok = srv.drain(timeout=timeout) and ok
        return ok

    def ready(self):
        return all(srv.ready() for srv in self.servers.values())

    def draining(self):
        return any(srv.draining() for srv in self.servers.values())

    def state(self):
        """Worst-of for /healthz: serving only when EVERY model serves;
        draining while any drains; otherwise the first non-serving
        member's state."""
        states = [srv.state() for srv in self.servers.values()]
        if all(s == "serving" for s in states):
            return "serving"
        if any(s == "draining" for s in states):
            return "draining"
        for s in states:
            if s != "serving":
                return s
        return "serving"

    def stats(self):
        per_model = {n: srv.stats() for n, srv in self.servers.items()}
        models = {}
        for n, st in per_model.items():
            models.update(st.get("models") or
                          {n: {"slo_ms": st.get("slo_ms"),
                               "queue_rows": st.get("queue_rows"),
                               "requests": st.get("requests"),
                               "p99_ms": st.get("p99_ms"),
                               "slo_violations":
                                   st.get("slo_violations")}})
        return {
            "ready": self.ready(),
            "state": self.state(),
            "draining": self.draining(),
            "default_model": self.default,
            "queue_rows": sum(st["queue_rows"]
                              for st in per_model.values()),
            "requests": sum(st["requests"] for st in per_model.values()),
            "rejected": sum(st["rejected"] for st in per_model.values()),
            "slo_violations": sum(st["slo_violations"]
                                  for st in per_model.values()),
            "steady_state_compiles": sum(st["steady_state_compiles"]
                                         for st in per_model.values()),
            "compile_entries": sum(st["compile_entries"]
                                   for st in per_model.values()),
            "models": models,
        }
