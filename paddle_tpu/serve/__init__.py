"""paddle_tpu.serve: batched low-latency inference serving.

Wraps an inference Program (ideally after InferenceTranspiler folding)
behind `Server.submit(feed) -> Future`. A batcher thread coalesces
concurrent requests, pads them to a fixed bucket ladder so every
dispatch hits an executable the warmup phase already compiled, and
round-robins batches across per-device replica executors. Latency
phases and p50/p95/p99 land in the monitor registry.

    from paddle_tpu import serve
    server = serve.Server.from_inference_model("model_dir")
    with server:                       # start() AOT-warms every bucket
        y, = server.submit({"x": example}).result()

`python -m paddle_tpu serve --model-dir model_dir` runs the same engine
behind a stdlib HTTP frontend (or a synthetic-load selftest), and
`paddle_tpu.serve.fleet` runs N such replicas behind a fault-tolerant
router (health-checked least-queue routing, retries, graceful drain).

Multi-model: `ModelSet` hosts N named one-shot Servers behind one
submit/stats surface; `serve.continuous.ContinuousServer` hosts N named
models inside ONE iteration-level step loop (requests join and leave a
running batch every model step — autoregressive decode without
head-of-line blocking). Both speak the same HTTP "model" field and
per-model SLO metrics the fleet router and autoscaler consume.
"""

from . import continuous, fleet
from .buckets import bucket_for, ladder, pad_rows
from .continuous import ContinuousConfig, ContinuousServer
from .engine import (SERVE_MS_BUCKETS, ModelSet, ServeConfig, ServeError,
                     Server, ServerClosed, ServerDraining,
                     ServerOverloaded, UnknownModel)
from .http import make_http_server, serve_http

__all__ = [
    "Server", "ServeConfig", "ServeError", "ServerOverloaded",
    "ServerClosed", "ServerDraining", "UnknownModel", "ModelSet",
    "ContinuousServer", "ContinuousConfig", "SERVE_MS_BUCKETS",
    "ladder", "bucket_for", "pad_rows",
    "serve_http", "make_http_server",
    "fleet", "continuous",
]
