"""paddle_tpu.serve: batched low-latency inference serving.

Wraps an inference Program (ideally after InferenceTranspiler folding)
behind `Server.submit(feed) -> Future`. A batcher thread coalesces
concurrent requests, pads them to a fixed bucket ladder so every
dispatch hits an executable the warmup phase already compiled, and
round-robins batches across per-device replica executors. Latency
phases and p50/p95/p99 land in the monitor registry.

    from paddle_tpu import serve
    server = serve.Server.from_inference_model("model_dir")
    with server:                       # start() AOT-warms every bucket
        y, = server.submit({"x": example}).result()

`python -m paddle_tpu serve --model-dir model_dir` runs the same engine
behind a stdlib HTTP frontend (or a synthetic-load selftest).
"""

from .buckets import bucket_for, ladder, pad_rows
from .engine import (SERVE_MS_BUCKETS, ServeConfig, ServeError, Server,
                     ServerClosed, ServerOverloaded)
from .http import serve_http

__all__ = [
    "Server", "ServeConfig", "ServeError", "ServerOverloaded",
    "ServerClosed", "SERVE_MS_BUCKETS",
    "ladder", "bucket_for", "pad_rows",
    "serve_http",
]
