"""paddle_tpu.serve: batched low-latency inference serving.

Wraps an inference Program (ideally after InferenceTranspiler folding)
behind `Server.submit(feed) -> Future`. A batcher thread coalesces
concurrent requests, pads them to a fixed bucket ladder so every
dispatch hits an executable the warmup phase already compiled, and
round-robins batches across per-device replica executors. Latency
phases and p50/p95/p99 land in the monitor registry.

    from paddle_tpu import serve
    server = serve.Server.from_inference_model("model_dir")
    with server:                       # start() AOT-warms every bucket
        y, = server.submit({"x": example}).result()

`python -m paddle_tpu serve --model-dir model_dir` runs the same engine
behind a stdlib HTTP frontend (or a synthetic-load selftest), and
`paddle_tpu.serve.fleet` runs N such replicas behind a fault-tolerant
router (health-checked least-queue routing, retries, graceful drain).
"""

from . import fleet
from .buckets import bucket_for, ladder, pad_rows
from .engine import (SERVE_MS_BUCKETS, ServeConfig, ServeError, Server,
                     ServerClosed, ServerDraining, ServerOverloaded)
from .http import make_http_server, serve_http

__all__ = [
    "Server", "ServeConfig", "ServeError", "ServerOverloaded",
    "ServerClosed", "ServerDraining", "SERVE_MS_BUCKETS",
    "ladder", "bucket_for", "pad_rows",
    "serve_http", "make_http_server",
    "fleet",
]
