"""Iteration-level (continuous) batching + multi-model serving.

scheduler.py  ContinuousServer: the step loop — admission into free
              slots, one warmed model step over the active slots,
              eviction on completion; weighted least-lag across N
              hosted models.
slots.py      SlotBank: device-resident per-request decode state at a
              fixed-capacity slot ladder.
interop.py    Opara-style inter-op parallelism: dispatch independent
              dataflow branches of an inference program concurrently.
"""

from .interop import InterOpRunner, independent_branches
from .scheduler import ContinuousConfig, ContinuousServer
from .slots import SlotBank

__all__ = ["ContinuousConfig", "ContinuousServer", "SlotBank",
           "InterOpRunner", "independent_branches"]
