"""Decode state slots: the persistent per-request memory of a
continuous batcher.

A one-shot batcher owns a request for exactly one dispatch; an
iteration-level scheduler owns it for K model steps, and between steps
the request's decode state (the recurrent feeds the next step consumes,
the token prefix produced so far, the per-slot step counter and RNG
seed) has to live SOMEWHERE the next step can reach without a host
round-trip per value. `SlotBank` is that somewhere: one device-resident
array per feed var, shaped [capacity + 1, *example_shape], where row i
is slot i's current value and the extra row is a scratch lane that
padding reads from and writes to.

The bank is addressed by a fixed-capacity slot ladder: a step over k
active slots gathers the smallest ladder rung >= k lanes (pad lanes
point at the scratch row), so every gather/step/scatter shape the
scheduler can ever issue is known at start() and AOT-warmable — the
slot-count analog of serve/buckets.py's row-count ladder, preserving
the zero-steady-state-compile contract. Gather and scatter move rows
verbatim (no arithmetic), so a value fed back through the bank is
bitwise the value the model fetched — the foundation of the decode
parity guarantee.
"""

import numpy as np

from ..buckets import ladder

__all__ = ["SlotBank"]


class SlotBank:
    """Fixed-capacity per-slot state arrays plus slot bookkeeping.

    var_specs maps feed name -> (example_shape, dtype). Every feed var
    of the model lives in the bank — recurrent state vars get scattered
    back each step, static per-request feeds (conditioning inputs) are
    written once at admission and only ever gathered.
    """

    def __init__(self, capacity, var_specs, slot_buckets=None):
        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.scratch = self.capacity  # the pad lane's row index
        self.rungs = ladder(self.capacity, slot_buckets)
        self.names = list(var_specs)
        self._specs = {n: (tuple(int(d) for d in shape), str(dtype))
                       for n, (shape, dtype) in var_specs.items()}
        self._state = {}
        for n, (shape, dtype) in self._specs.items():
            self._state[n] = jax.device_put(
                jnp.zeros((self.capacity + 1,) + shape, dtype=dtype))
        self._free = list(range(self.capacity - 1, -1, -1))  # pop() -> 0
        self._active = []  # sorted slot ids in use
        self.steps = np.zeros(self.capacity, dtype=np.int64)
        self.seeds = np.zeros(self.capacity, dtype=np.uint32)
        self.requests = [None] * self.capacity
        # token prefix: per-slot list of per-step output row tuples,
        # stacked into [steps, ...] arrays when the request completes
        self._prefix = [None] * self.capacity

    # -- slot bookkeeping ------------------------------------------------
    @property
    def free_slots(self):
        return len(self._free)

    def active_slots(self):
        """Sorted tuple of in-use slot ids — the deterministic lane
        order every step gathers and scatters in."""
        return tuple(self._active)

    def alloc(self, request, seed=0):
        """Claim a slot for `request`; None when the bank is full."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._active.append(slot)
        self._active.sort()
        self.steps[slot] = 0
        self.seeds[slot] = np.uint32(seed)
        self.requests[slot] = request
        self._prefix[slot] = []
        return slot

    def release(self, slot):
        self._active.remove(slot)
        self._free.append(slot)
        self.requests[slot] = None
        self._prefix[slot] = None
        self.steps[slot] = 0

    # -- device-resident state ------------------------------------------
    def write_row(self, slot, rows):
        """Stage one request's initial feed values into its slot (the
        admission write; a single-lane scatter, warmed at start)."""
        jnp = self._jnp
        idx = np.asarray([slot], dtype=np.int32)
        for n, v in rows.items():
            shape, dtype = self._specs[n]
            row = np.asarray(v, dtype=dtype).reshape((1,) + shape)
            self._state[n] = self._state[n].at[idx].set(jnp.asarray(row))

    def gather(self, idx):
        """{name: [len(idx), ...]} device arrays for the given lane
        indices (pad lanes pass self.scratch)."""
        idx = self._jnp.asarray(np.asarray(idx, dtype=np.int32))
        return {n: a[idx] for n, a in self._state.items()}

    def scatter(self, idx, values):
        """Write fetched next-state rows back into the bank. `values`
        maps feed name -> [len(idx), ...]; pad lanes must index the
        scratch row so their garbage lands nowhere observable."""
        jnp = self._jnp
        idx = jnp.asarray(np.asarray(idx, dtype=np.int32))
        for n, v in values.items():
            self._state[n] = self._state[n].at[idx].set(jnp.asarray(v))

    def lane_index(self, bucket):
        """[bucket] lane->slot index array: active slots first, scratch
        for the pad lanes."""
        idx = np.full(bucket, self.scratch, dtype=np.int32)
        active = self._active
        idx[:len(active)] = active
        return idx

    def rng_rows(self, idx):
        """Deterministic per-(slot, step) RNG key rows, uint32 [n, 2]:
        (seed, step). A request replayed solo sees the identical key
        sequence, so stochastic decodes stay parity-comparable."""
        idx = np.asarray(idx)
        rows = np.zeros((len(idx), 2), dtype=np.uint32)
        for lane, slot in enumerate(idx):
            if slot < self.capacity:
                rows[lane, 0] = self.seeds[slot]
                rows[lane, 1] = np.uint32(self.steps[slot])
        return rows

    # -- token prefix ----------------------------------------------------
    def append_outputs(self, slot, out_rows):
        """Append this step's fetched output rows to the slot's prefix."""
        self._prefix[slot].append(out_rows)

    def take_prefix(self, slot):
        """[steps, ...] stacked arrays, one per output fetch, in fetch
        order — the completed request's result."""
        steps = self._prefix[slot]
        n_out = len(steps[0]) if steps else 0
        return [np.stack([s[i] for s in steps], axis=0)
                for i in range(n_out)]

    # -- warmup ----------------------------------------------------------
    def warm(self):
        """Compile every gather/scatter shape the scheduler can issue:
        one lane count per ladder rung, plus the single-lane admission
        write. Run before serving so no step ever compiles."""
        for b in self.rungs:
            idx = np.full(b, self.scratch, dtype=np.int32)
            got = self.gather(idx)
            self.scatter(idx, got)
        zero = {n: np.zeros(shape, dtype=dtype)
                for n, (shape, dtype) in self._specs.items()}
        self.write_row(0, zero)  # slot 0 is zeros anyway
