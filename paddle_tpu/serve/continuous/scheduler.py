"""Iteration-level (continuous) batching: requests join and leave a
RUNNING batch at every model step.

The one-shot batcher (serve/engine.py) owns a request for exactly one
dispatch — fine for fixed-shape inference, pathological for
autoregressive decode: a K-step stream either holds the server for K
dispatches while short requests queue behind it, or the client drives
the loop and eats K round trips. `ContinuousServer` schedules at
iteration granularity instead (Orca-style): each turn of the step loop
admits pending requests into free state slots, gathers every active
slot into one batch, runs ONE model step, scatters the next-state rows
back, and evicts the requests that just produced their last token —
no drain-the-batch barrier anywhere. A short request admitted while a
long stream is mid-decode rides the very next step.

Shapes come from the slot ladder (slots.py): a step over k active
slots pads to the smallest ladder rung >= k, so after start() warms
every (model, rung) pair no step ever compiles — the PR-5/PR-15/PR-19
zero-steady-state-compile contract, now over slot counts instead of
row counts.

Multi-model: one server hosts N named models, each with its own
Executor (own compile cache), scope, slot bank and SLO target. The
step loop picks the model to service by weighted least-lag: the model
whose time since last service is largest relative to its SLO goes
first, so a 10 ms-SLO model is stepped ~10x as often as a 100 ms one
under contention and a cold model cannot starve a hot one.

A model step:
    feed   = bank.gather(lane_index)          # slot rows, pad=scratch
    outs   = exe.run(program, feed, fetches)  # warmed executable
    bank.scatter(lane_index, next_state)      # state feeds round-trip
    evict slots whose step counter hit the request's K

Gather/scatter move rows verbatim, so a K-step decode through the
running batch is bitwise identical to the same request replayed solo —
the decode-parity test pins this.
"""

import threading
import time
from collections import deque

import numpy as np

from ... import monitor
from ...core.framework import Program, Variable
from ...core.scope import Scope
from ...executor import Executor, as_numpy
from ...trainer import check_and_get_place
from ..buckets import bucket_for
from ..engine import (SERVE_MS_BUCKETS, ServeError, ServerClosed,
                      ServerDraining, ServerOverloaded, UnknownModel,
                      _resolve)
from .interop import InterOpRunner, independent_branches
from .slots import SlotBank

__all__ = ["ContinuousConfig", "ContinuousServer"]


class ContinuousConfig:
    """Tuning knobs for one ContinuousServer.

    max_slots        decode state slots per model — the widest step batch
                     and the cap on concurrently-decoding requests.
    slot_buckets     explicit slot ladder; None = powers of two.
    max_pending      admission bound on queued-but-unslotted requests per
                     model (ServerOverloaded beyond it). None = 8x slots.
    max_steps        hard cap on any request's step count.
    default_slo_ms   SLO for models that don't declare one; also the
                     least-lag weight for those models.
    idle_wait_ms     step-loop sleep when no model has work.
    """

    def __init__(self, max_slots=8, slot_buckets=None, max_pending=None,
                 max_steps=4096, default_slo_ms=100.0, idle_wait_ms=2.0):
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.slot_buckets = slot_buckets
        self.max_pending = (8 * self.max_slots if max_pending is None
                            else int(max_pending))
        self.max_steps = int(max_steps)
        self.default_slo_ms = float(default_slo_ms)
        self.idle_wait_ms = float(idle_wait_ms)


class _CRequest:
    __slots__ = ("feed", "steps", "seed", "future", "t_submit", "t_join")

    def __init__(self, feed, steps, seed):
        from concurrent.futures import Future

        self.feed = feed
        self.steps = steps
        self.seed = seed
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.t_join = None


class _Model:
    """One hosted model: program + executor + scope + slot bank + SLO."""

    def __init__(self, name, program, feed_names, fetch_list, state,
                 place, scope, slo_ms, rng_feed, interop, config):
        if not isinstance(program, Program):
            raise TypeError("program must be a Program")
        self.name = name
        self.program = program
        self.place = place
        self.scope = scope if scope is not None else Scope()
        self.exe = Executor(place)
        self.slo_ms = slo_ms
        self.rng_feed = rng_feed
        self.config = config
        gb = program.global_block()
        self.feed_names = list(feed_names)
        self._feed_vars = {n: gb.var(n) for n in self.feed_names}
        if rng_feed is not None and rng_feed not in self._feed_vars:
            raise ValueError(f"rng_feed {rng_feed!r} not in feed_names")
        # output fetches (the per-step token row the prefix accumulates)
        self.out_vars = [v if isinstance(v, Variable) else gb.var(str(v))
                         for v in fetch_list]
        out_names = [v.name for v in self.out_vars]
        # state map: feed name -> fetch name round-tripped each step
        self.state = dict(state or {})
        for fn, gn in self.state.items():
            if fn not in self._feed_vars:
                raise ValueError(f"state feed {fn!r} not in feed_names")
            if not gb.has_var_recursive(gn):
                raise ValueError(f"state fetch {gn!r} not in program")
        # combined fetch list: outputs first, then state fetches that
        # are not already outputs
        self.fetch_vars = list(self.out_vars)
        for gn in self.state.values():
            if gn not in out_names and gn not in \
                    [v.name for v in self.fetch_vars]:
                self.fetch_vars.append(gb.var(gn))
        self._fetch_pos = {v.name: i for i, v in enumerate(self.fetch_vars)}
        self.n_out = len(self.out_vars)
        # the bank holds EVERY feed var except the host-computed rng key
        specs = {}
        for n in self.feed_names:
            if n == rng_feed:
                continue
            specs[n] = (self._example_shape(n), self._feed_dtype(n))
        self.bank = SlotBank(config.max_slots, specs,
                             slot_buckets=config.slot_buckets)
        self.pending = deque()
        self.runner = None
        if interop:
            groups = independent_branches(
                program, self.feed_names,
                [v.name for v in self.fetch_vars])
            if len(groups) > 1:
                self.runner = InterOpRunner(
                    self.exe, program, self.scope, self.fetch_vars,
                    groups, gauge_label=f"interop:{name}")
        self.last_service_t = None
        self.steps_total = 0
        self.warm_entries = 0
        # per-model tallies next to the process-global registry series
        # (same idiom as Server._own)
        self._own = {n: monitor.Counter(n) for n in
                     ("requests", "rejected", "completed",
                      "slo_violations")}
        self._own_request_ms = monitor.Histogram(
            f"serve_request_ms[{name}]", buckets=SERVE_MS_BUCKETS)

    # mirrors Server's shape helpers so serve/http._json_feed can build
    # feeds against a resolved model exactly like against a Server
    def _example_shape(self, name):
        var = self._feed_vars[name]
        shape = list(var.shape or [])[1:]
        return tuple(1 if (d is None or d < 0) else int(d) for d in shape)

    def _feed_dtype(self, name):
        return self._feed_vars[name].dtype or "float32"

    def normalize_row(self, feed):
        """One example per request — a continuous slot holds ONE
        sequence. Accepts the example shaped like the feed var minus the
        batch axis, or with a leading axis of exactly 1."""
        if not isinstance(feed, dict):
            raise ValueError("feed must be a dict of {feed_name: array}")
        out = {}
        for n in self.feed_names:
            if n == self.rng_feed:
                continue
            if n not in feed:
                raise ValueError(f"feed missing [{n!r}]")
            shape, dtype = self._example_shape(n), self._feed_dtype(n)
            v = np.asarray(feed[n])
            if v.shape == (1,) + shape:
                v = v[0]
            elif v.shape != shape:
                raise ValueError(
                    f"feed {n!r} shape {v.shape} matches neither one "
                    f"example {shape} nor [1, *example]")
            out[n] = v.astype(dtype) if str(v.dtype) != dtype else v
        extra = [n for n in feed
                 if n not in self._feed_vars or n == self.rng_feed]
        if extra:
            raise ValueError(f"unknown feed names {extra}")
        return out

    def cache_entries(self):
        return self.exe.compile_cache_info()["entries"]

    def run_step(self, feed):
        """Device arrays in fetch_vars order for one warmed step."""
        if self.runner is not None:
            return self.runner.run(feed)
        return self.exe.run(self.program, feed=feed,
                            fetch_list=self.fetch_vars, scope=self.scope,
                            return_numpy=False)

    def queue_depth(self):
        return len(self.pending) + len(self.bank.active_slots())


class ContinuousServer:
    """N named models, one iteration-level step loop.

        srv = ContinuousServer(place=fluid.CPUPlace())
        srv.add_model("chat", prog, ["x"], [y], state={"x": y.name},
                      slo_ms=50.0)
        srv.start()                          # warms every (model, rung)
        fut = srv.submit({"x": row}, model="chat", steps=16)
        tokens, = fut.result()               # [16, *out_shape]
        srv.stop()

    submit() takes ONE example per request (a slot holds one sequence);
    the Future resolves to per-fetch arrays stacked over the K steps.
    steps=1 is plain one-shot inference through the same machinery.
    """

    is_continuous = True

    def __init__(self, place=None, config=None):
        self.place = check_and_get_place(place)
        self.config = config or ContinuousConfig()
        self.models = {}
        self.default_model = None
        self._cond = threading.Condition()
        self._thread = None
        self._stop_flag = False
        self._ready = False
        self._draining = False
        self._drained = threading.Event()

    # -- model registry --------------------------------------------------
    def add_model(self, name, program, feed_names, fetch_list, state=None,
                  slo_ms=None, scope=None, rng_feed=None, interop=False):
        """Host `name` on this server. `state` maps feed name -> fetch
        name round-tripped between steps; feeds not in `state` are
        static per-request conditioning. Must be called before start()."""
        if self._ready or self._thread is not None:
            raise ServeError("add_model() must precede start()")
        if name in self.models:
            raise ServeError(f"model {name!r} already hosted")
        m = _Model(str(name), program, feed_names, fetch_list, state,
                   self.place, scope,
                   float(slo_ms) if slo_ms is not None
                   else self.config.default_slo_ms,
                   rng_feed, interop, self.config)
        self.models[m.name] = m
        if self.default_model is None:
            self.default_model = m.name
        return m

    def resolve_model(self, name=None):
        """-> the hosted _Model; UnknownModel on a name this server does
        not host (the HTTP 404 path)."""
        if not self.models:
            raise ServeError("no models hosted (call add_model first)")
        if name is None:
            return self.models[self.default_model]
        m = self.models.get(str(name))
        if m is None:
            raise UnknownModel(
                f"unknown model {name!r}; hosting "
                f"{sorted(self.models)}")
        return m

    # -- lifecycle -------------------------------------------------------
    def start(self, warm=True, loop=True):
        """Warm every (model, slot-rung) executable plus the bank's
        gather/scatter shapes, then start the step loop. After this no
        admissible step compiles. `loop=False` skips the background
        thread: the caller drives step_once() instead — tests and
        drills use it to make join/leave ordering deterministic."""
        if self._ready:
            raise ServeError("server already started")
        if self._stop_flag:
            raise ServerClosed("server was stopped")
        if not self.models:
            raise ServeError("no models hosted (call add_model first)")
        for m in self.models.values():
            if warm:
                self._warm_model(m)
            m.warm_entries = m.cache_entries()
        self._ready = True
        if loop:
            self._thread = threading.Thread(target=self._loop,
                                            name="serve-continuous",
                                            daemon=True)
            self._thread.start()
        self._gauge("serve_ready").set(1)
        return self

    def _warm_model(self, m):
        t0 = time.perf_counter()
        m.bank.warm()
        for b in m.bank.rungs:
            idx = np.full(b, m.bank.scratch, dtype=np.int32)
            feed = m.bank.gather(idx)
            if m.rng_feed is not None:
                feed[m.rng_feed] = m.bank.rng_rows(idx)
            if m.runner is not None:
                m.runner.warm(feed)
            else:
                for o in m.exe.run(m.program, feed=feed,
                                   fetch_list=m.fetch_vars, scope=m.scope,
                                   return_numpy=False):
                    as_numpy(o)  # fence: compiled NOW
            if m.state:
                m.bank.scatter(idx, {fn: m.bank.gather(idx)[fn]
                                     for fn in m.state})
        self._gauge("serve_warmup_ms", model=m.name,
                    help="AOT slot-rung precompile wall time").set(
            (time.perf_counter() - t0) * 1000.0)

    def __enter__(self):
        if not self._ready:
            self.start()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        return False

    def ready(self):
        return self._ready and not self._stop_flag and not self._draining

    def draining(self):
        return self._draining and not self._stop_flag

    def state(self):
        if self._stop_flag:
            return "stopped"
        if self._draining:
            return "draining"
        if self._ready:
            return "serving"
        return "created"

    def drain(self, timeout=30.0):
        """Lame-duck: stop admitting, finish every pending and in-slot
        request (each to its full K steps), then stop clean."""
        if self._stop_flag:
            return True
        if not self._ready:
            raise ServeError("server not started")
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        self._gauge("serve_draining").set(1)
        if self._thread is None:
            # loopless (step_once-driven) mode: run the backlog down
            # inline — same semantics, synchronous
            deadline = time.perf_counter() + float(timeout)
            while self._has_work() and time.perf_counter() < deadline:
                self.step_once()
            ok = not self._has_work()
            if ok:
                self._drained.set()
        else:
            ok = self._drained.wait(timeout=float(timeout))
        if ok:
            with self._cond:
                self._stop_flag = True
                self._ready = False
                self._cond.notify_all()
            t = self._thread
            if t is not None:
                t.join(timeout=10.0)
            monitor.registry().counter(
                "serve_drains_total",
                help="lame-duck drains completed").inc()
        self._gauge("serve_draining").set(0)
        self._gauge("serve_ready").set(0)
        return ok

    def stop(self):
        """Stop now: fail pending and in-slot requests with
        ServerClosed, join the loop."""
        with self._cond:
            if self._stop_flag:
                return
            self._stop_flag = True
            self._ready = False
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
        for m in self.models.values():
            while m.pending:
                _resolve(m.pending.popleft().future,
                         exc=ServerClosed("server stopped"))
            for slot in list(m.bank.active_slots()):
                req = m.bank.requests[slot]
                if req is not None:
                    _resolve(req.future,
                             exc=ServerClosed("server stopped"))
                m.bank.release(slot)
        self._gauge("serve_ready").set(0)

    # -- request path ----------------------------------------------------
    def submit(self, feed, model=None, steps=1, seed=0):
        """Enqueue one sequence; the Future resolves to the model's
        fetch-list arrays stacked over the K steps ([K, *example])."""
        m = self.resolve_model(model)
        if self._stop_flag:
            raise ServerClosed("server is stopped")
        if self._draining:
            raise ServerDraining("server is draining")
        if not self._ready:
            raise ServeError("server not started (call start() first)")
        steps = int(steps)
        if not 1 <= steps <= self.config.max_steps:
            raise ValueError(
                f"steps must be in [1, {self.config.max_steps}], "
                f"got {steps}")
        vals = m.normalize_row(feed)
        req = _CRequest(vals, steps, int(seed))
        reg = monitor.registry()
        with self._cond:
            if len(m.pending) >= self.config.max_pending:
                m._own["rejected"].inc()
                reg.counter("serve_rejected_total",
                            help="requests rejected by admission "
                                 "control").inc()
                reg.counter("serve_rejected_total",
                            model=m.name).inc()
                raise ServerOverloaded(
                    f"model {m.name!r} pending at "
                    f"{len(m.pending)}/{self.config.max_pending}")
            m.pending.append(req)
            self._cond.notify_all()
        m._own["requests"].inc()
        reg.counter("serve_requests_total",
                    help="requests admitted to the serve queue").inc()
        reg.counter("serve_requests_total", model=m.name).inc()
        self._queue_gauges(m)
        return req.future

    def infer(self, feed, model=None, steps=1, seed=0, timeout=None):
        return self.submit(feed, model=model, steps=steps,
                           seed=seed).result(timeout=timeout)

    # -- step loop -------------------------------------------------------
    def _loop(self):
        while True:
            with self._cond:
                while not self._stop_flag and not self._has_work():
                    if self._draining:
                        self._drained.set()
                        return
                    self._cond.wait(self.config.idle_wait_ms / 1000.0)
                if self._stop_flag:
                    return
            self._admit()
            m = self._pick()
            if m is not None:
                self._step(m)

    def _has_work(self):
        return any(m.pending or m.bank.active_slots()
                   for m in self.models.values())

    def _admit(self):
        """Join protocol: move pending requests into free slots. Runs
        every loop turn, so a request admitted while other slots are
        mid-decode rides the very next step."""
        now = time.perf_counter()
        for m in self.models.values():
            while m.bank.free_slots:
                with self._cond:
                    if not m.pending:
                        break
                    req = m.pending.popleft()
                slot = m.bank.alloc(req, seed=req.seed)
                m.bank.write_row(slot, req.feed)
                req.t_join = now
                self._queue_gauges(m)

    def _pick(self):
        """Weighted least-lag: the model whose time since last service
        is largest relative to its SLO is stepped next."""
        now = time.perf_counter()
        best, best_score = None, None
        for m in self.models.values():
            if not m.bank.active_slots():
                continue
            anchor = m.last_service_t
            if anchor is None:
                anchor = min(
                    (m.bank.requests[s].t_submit
                     for s in m.bank.active_slots()
                     if m.bank.requests[s] is not None),
                    default=now)
            score = ((now - anchor) * 1000.0) / m.slo_ms
            if best_score is None or score > best_score:
                best, best_score = m, score
        return best

    def _step(self, m):
        active = m.bank.active_slots()
        bucket = bucket_for(len(active), m.bank.rungs)
        idx = m.bank.lane_index(bucket)
        feed = m.bank.gather(idx)
        if m.rng_feed is not None:
            feed[m.rng_feed] = m.bank.rng_rows(idx)
        try:
            outs = m.run_step(feed)
            if m.state:
                m.bank.scatter(
                    idx, {fn: outs[m._fetch_pos[gn]]
                          for fn, gn in m.state.items()})
            host = [np.asarray(as_numpy(o)) for o in outs[:m.n_out]]
        except BaseException as e:  # noqa: BLE001 — fail the slots
            for slot in list(active):
                req = m.bank.requests[slot]
                if req is not None:
                    _resolve(req.future, exc=e)
                m.bank.release(slot)
            m.last_service_t = time.perf_counter()
            return
        reg = monitor.registry()
        reg.counter("serve_model_steps_total",
                    help="continuous scheduler steps per model",
                    model=m.name).inc()
        reg.counter("serve_batches_total", help="batches dispatched",
                    bucket=str(bucket)).inc()
        done = time.perf_counter()
        for lane, slot in enumerate(active):
            req = m.bank.requests[slot]
            if req is None:
                continue
            m.bank.append_outputs(slot, [h[lane] for h in host])
            m.bank.steps[slot] += 1
            if m.bank.steps[slot] >= req.steps:
                # leave protocol: eviction on completion frees the slot
                # for the next _admit, mid-stream for everyone else
                result = m.bank.take_prefix(slot)
                m.bank.release(slot)
                if _resolve(req.future, result=result):
                    self._record(m, req, done)
        m.last_service_t = time.perf_counter()
        m.steps_total += 1

    # -- metrics ---------------------------------------------------------
    def _gauge(self, name, help="", **labels):
        return monitor.registry().gauge(name, help=help, **labels)

    def _queue_gauges(self, m):
        rows = m.queue_depth()
        self._gauge("serve_queue_rows",
                    help="rows currently queued").set(
            sum(mm.queue_depth() for mm in self.models.values()))
        self._gauge("serve_queue_rows", model=m.name).set(rows)

    def _record(self, m, req, done):
        reg = monitor.registry()
        total_ms = (done - req.t_submit) * 1000.0
        m._own["completed"].inc()
        m._own_request_ms.observe(total_ms)
        reg.histogram("serve_request_ms",
                      help="submit-to-result request latency",
                      buckets=SERVE_MS_BUCKETS).observe(total_ms)
        reg.histogram("serve_request_ms", buckets=SERVE_MS_BUCKETS,
                      model=m.name).observe(total_ms)
        if m.slo_ms is not None and total_ms > m.slo_ms:
            m._own["slo_violations"].inc()
            reg.counter("serve_slo_violations_total",
                        help="requests exceeding their model's "
                             "slo_ms").inc()
            reg.counter("serve_slo_violations_total",
                        model=m.name).inc()

    # -- visibility ------------------------------------------------------
    def step_once(self):
        """One synchronous turn of the scheduler — admit, pick, step.
        Public so tests and drills drive join/leave deterministically
        (the background loop does exactly this)."""
        self._admit()
        m = self._pick()
        if m is not None:
            self._step(m)
        return m.name if m is not None else None

    def model_stats(self, name):
        m = self.resolve_model(name)
        pct = m._own_request_ms.percentiles(50, 95, 99)
        return {
            "slo_ms": m.slo_ms,
            "queue_rows": m.queue_depth(),
            "pending": len(m.pending),
            "active_slots": len(m.bank.active_slots()),
            "slots": m.bank.capacity,
            "slot_buckets": list(m.bank.rungs),
            "requests": m._own["requests"].value,
            "completed": m._own["completed"].value,
            "rejected": m._own["rejected"].value,
            "steps": m.steps_total,
            "p50_ms": pct[50], "p95_ms": pct[95], "p99_ms": pct[99],
            "slo_violations": m._own["slo_violations"].value,
            "compile_entries": m.cache_entries(),
            "steady_state_compiles": m.cache_entries() - m.warm_entries,
            "interop_branches": (len(m.runner.groups)
                                 if m.runner is not None else 1),
        }

    def stats(self):
        per_model = {n: self.model_stats(n) for n in self.models}
        entries = sum(s["compile_entries"] for s in per_model.values())
        warm = sum(m.warm_entries for m in self.models.values())
        return {
            "ready": self.ready(),
            "state": self.state(),
            "draining": self.draining(),
            "continuous": True,
            "default_model": self.default_model,
            "queue_rows": sum(s["queue_rows"]
                              for s in per_model.values()),
            "requests": sum(s["requests"] for s in per_model.values()),
            "rejected": sum(s["rejected"] for s in per_model.values()),
            "slo_violations": sum(s["slo_violations"]
                                  for s in per_model.values()),
            "p99_ms": max((s["p99_ms"] for s in per_model.values()
                           if s["p99_ms"] == s["p99_ms"]), default=None),
            "compile_entries": entries,
            "steady_state_compiles": entries - warm,
            "models": per_model,
        }
