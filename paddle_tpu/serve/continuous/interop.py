"""Opara-style inter-op parallelism for inference programs.

Opara (PAPERS.md: arXiv 2312.10351) observes that an inference graph
usually contains branches with no data dependence on each other —
parallel heads, mixture experts, multi-task towers — and that running
them as one sequential program leaves the overlap on the table. The
PR-13 dataflow graph already exposes exactly this structure: two fetch
targets whose backward closures over the SSA def-use edges are disjoint
can be dispatched as independent sub-steps.

`independent_branches` partitions a program's fetch targets into such
groups. `InterOpRunner` dispatches one executor call per group without
fencing between them — jax dispatch is asynchronous, so the branches'
device work overlaps; the caller fences once when it reads the results
back. Each per-branch executable is a separate compile-cache entry
(XLA dead-code-eliminates the other branches), so the runner warms
every (branch, shape) pair up front and the zero-steady-state-compile
contract holds unchanged.

Measured overlap is reported through the existing overlap-efficiency
gauge (`fleet_overlap_efficiency`, obs/timeline.overlap_efficiency):
the critical branch plays the "compute" role, the off-critical-path
branch time is the "comm" to hide under it.
"""

import time

from ... import monitor
from ...analysis.dataflow import build_graph

__all__ = ["independent_branches", "InterOpRunner"]


def _closure(graph, start):
    """All node indices reachable backward from `start` over preds
    (every edge kind — any ordering constraint couples the branches)."""
    seen = set()
    stack = [start]
    while stack:
        i = stack.pop()
        if i in seen:
            continue
        seen.add(i)
        stack.extend(p for p in graph.preds[i] if p not in seen)
    return seen


def _def_node(graph, name):
    """Index of the node producing the final version of `name`, or None
    when no op writes it (a passthrough feed)."""
    best = None
    for node in graph.nodes:
        if name in node.writes:
            best = node.idx
    return best


def independent_branches(program, feed_names, fetch_names):
    """Partition fetch targets into dataflow-independent groups.

    Returns a list of lists of POSITIONS into `fetch_names`, in first-
    appearance order. Fetches whose backward closures share any op are
    grouped together; a single group means the program has no inter-op
    parallelism to exploit.
    """
    graph = build_graph(program, feed_names=feed_names)
    closures = []
    for name in fetch_names:
        d = _def_node(graph, str(name))
        closures.append(_closure(graph, d) if d is not None else set())
    groups = []  # [(node_set, [positions])]
    for pos, cl in enumerate(closures):
        merged = None
        for g in groups:
            if g[0] & cl:
                if merged is None:
                    g[0].update(cl)
                    g[1].append(pos)
                    merged = g
                else:  # this fetch bridges two groups: fold them
                    merged[0].update(g[0])
                    merged[1].extend(g[1])
                    g[0].clear()
                    g[1].clear()
        if merged is None:
            groups.append([set(cl), [pos]])
    return [sorted(g[1]) for g in groups if g[1]]


class InterOpRunner:
    """Dispatch a program's independent fetch branches concurrently.

    Drop-in for the single `exe.run(...)` a serving step makes: run()
    returns device arrays aligned with `fetch_vars`, but issues one
    donated sub-step per branch back to back, overlapping their device
    work. `gauge_label` names the fleet_overlap_efficiency series this
    runner reports under.
    """

    def __init__(self, exe, program, scope, fetch_vars, groups,
                 gauge_label="interop"):
        self.exe = exe
        self.program = program
        self.scope = scope
        self.fetch_vars = list(fetch_vars)
        self.groups = [list(g) for g in groups]
        self.gauge_label = gauge_label
        # per-branch solo cost (ms), measured during warm(); the serial
        # estimate sum(costs) vs the measured overlapped wall time is
        # what the efficiency gauge joins
        self.branch_cost_ms = [None] * len(self.groups)
        self.last_efficiency = None

    @property
    def parallel(self):
        return len(self.groups) > 1

    def run(self, feed):
        """Device arrays in fetch_vars order; branches dispatched
        without an intermediate fence."""
        from ...executor import as_numpy

        outs = [None] * len(self.fetch_vars)
        t0 = time.perf_counter()
        parts = []
        for g in self.groups:
            res = self.exe.run(self.program, feed=feed,
                               fetch_list=[self.fetch_vars[i] for i in g],
                               scope=self.scope, return_numpy=False)
            parts.append((g, res))
        for g, res in parts:
            for i, o in zip(g, res):
                outs[i] = o
        if self.parallel and all(c is not None for c in self.branch_cost_ms):
            for o in outs:  # fence: the overlap window ends here
                as_numpy(o)
            self._report((time.perf_counter() - t0) * 1000.0)
        return outs

    def _report(self, measured_ms):
        from ...obs.timeline import overlap_efficiency

        critical = max(self.branch_cost_ms)
        hidden = sum(self.branch_cost_ms) - critical
        eff = overlap_efficiency(critical, hidden, measured_ms)
        if eff is None:
            return
        self.last_efficiency = eff
        monitor.registry().gauge(
            "fleet_overlap_efficiency",
            help="fraction of off-critical-path work hidden under the "
                 "critical path",
            replica=self.gauge_label).set(eff)

    def warm(self, feed):
        """Compile every branch at this feed shape and (re)measure the
        per-branch solo cost the efficiency gauge needs. Two passes:
        the first eats the compile, the second times the executable."""
        from ...executor import as_numpy

        for bi, g in enumerate(self.groups):
            fetches = [self.fetch_vars[i] for i in g]
            for o in self.exe.run(self.program, feed=feed,
                                  fetch_list=fetches, scope=self.scope,
                                  return_numpy=False):
                as_numpy(o)
            t0 = time.perf_counter()
            for o in self.exe.run(self.program, feed=feed,
                                  fetch_list=fetches, scope=self.scope,
                                  return_numpy=False):
                as_numpy(o)
            self.branch_cost_ms[bi] = (time.perf_counter() - t0) * 1000.0
