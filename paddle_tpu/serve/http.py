"""Stdlib HTTP frontend for a Server — no framework dependency.

Endpoints:
    POST /v1/infer   {"inputs": {name: nested-list}}  ->
                     {"outputs": [nested-list, ...]}  (sliced to the
                     request's rows; 429 on backpressure rejection,
                     503 before ready / after stop)
    GET  /healthz    200 "ok" once warmup finished, 503 otherwise
    GET  /stats      Server.stats() as JSON
    GET  /metrics    Prometheus text exposition of the monitor registry

ThreadingHTTPServer gives one thread per connection; each handler
thread parks on its request's Future, so concurrent connections batch
together inside the engine exactly like in-process submitters.
"""

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import monitor
from .. import trace as _trace
from .engine import ServeError, ServerClosed, ServerOverloaded

__all__ = ["serve_http", "make_http_server"]


def _json_feed(payload, server):
    if not isinstance(payload, dict):
        raise ValueError('body must be a JSON object {"inputs": {...}}')
    inputs = payload.get("inputs")
    if not isinstance(inputs, dict):
        raise ValueError('body must be {"inputs": {name: array}}')
    return {n: np.asarray(v, dtype=server._feed_dtype(n))
            if n in server._feed_vars else np.asarray(v)
            for n, v in inputs.items()}


class _Handler(BaseHTTPRequestHandler):
    # the Server instance is attached to the HTTPServer by the factory
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _reply(self, code, body, content_type="application/json"):
        data = body if isinstance(body, bytes) else body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _reply_json(self, code, obj):
        self._reply(code, json.dumps(obj))

    def do_GET(self):
        engine = self.server.engine
        if self.path == "/healthz":
            if engine.ready():
                self._reply(200, "ok\n", content_type="text/plain")
            else:
                self._reply(503, "warming\n", content_type="text/plain")
        elif self.path == "/stats":
            self._reply_json(200, engine.stats())
        elif self.path == "/metrics":
            self._reply(200, monitor.registry().exposition(),
                        content_type="text/plain; version=0.0.4")
        else:
            self._reply_json(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        engine = self.server.engine
        if self.path != "/v1/infer":
            self._reply_json(404, {"error": f"no route {self.path}"})
            return
        # root span of the request's trace: submit() runs inside it, so
        # the engine's serve.request span (and everything under it)
        # inherits this span's trace id — HTTP accept through readback
        # reconstructs as one trace from a flight-recorder dump
        with _trace.span("serve.http", kind="serve", path=self.path) as sp:
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
                feed = _json_feed(payload, engine)
                fut = engine.submit(feed)
            except ServerOverloaded as e:
                sp.set(status=429)
                self._reply_json(429, {"error": str(e)})
                return
            except ServerClosed as e:
                sp.set(status=503)
                self._reply_json(503, {"error": str(e)})
                return
            except (ValueError, ServeError) as e:
                sp.set(status=400)
                self._reply_json(400, {"error": str(e)})
                return
            try:
                outs = fut.result()
            except ServerClosed as e:
                sp.set(status=503)
                self._reply_json(503, {"error": str(e)})
                return
            except Exception as e:  # noqa: BLE001 — surface model errors
                sp.set(status=500)
                self._reply_json(500, {"error": f"{type(e).__name__}: {e}"})
                return
            sp.set(status=200)
            self._reply_json(200, {
                "outputs": [np.asarray(o).tolist() for o in outs]})


def make_http_server(engine, host="127.0.0.1", port=8000):
    """A ThreadingHTTPServer bound to (host, port), serving `engine`.
    Caller owns serve_forever()/shutdown() (tests run it in a thread)."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    httpd.engine = engine
    return httpd


def serve_http(engine, host="127.0.0.1", port=8000):
    """Blocking frontend: serve until KeyboardInterrupt, then stop both."""
    httpd = make_http_server(engine, host, port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        httpd.server_close()
        engine.stop()
