"""Stdlib HTTP frontend for a Server — no framework dependency.

Endpoints:
    POST /v1/infer    {"inputs": {name: nested-list},
                       "model": "name"?, "steps": K?, "seed": s?}  ->
                      {"outputs": [nested-list, ...]}  (sliced to the
                      request's rows). "model" picks a hosted model on a
                      multi-model engine (ModelSet / ContinuousServer);
                      omitted = the engine's default model; an unknown
                      name is 404 (deterministic — the fleet router
                      never retries it). "steps"/"seed" drive a K-step
                      decode on a continuous engine (400 on a one-shot
                      engine). Failure mapping is load-balancer
                      shaped: 503 + Retry-After on backpressure
                      rejection (ServerOverloaded — the replica is
                      healthy but full, come back), 503 +
                      Connection: close when stopping/draining
                      (ServerClosed/ServerDraining — stop reusing this
                      replica), 400 on malformed requests, 500 on model
                      errors. The fleet router retries 503s on another
                      replica; 4xx/500 are deterministic and pass through.
    POST /admin/drain flip the engine to lame-duck (202 {"state":
                      "draining"}): in-flight and queued requests finish,
                      new submits 503, and — when the factory was told
                      shutdown_on_drain — the HTTP server itself exits
                      after the drain completes (clean rolling-restart
                      exit).
    GET  /healthz     200 "ok" while serving; 503 "draining" (with
                      Connection: close) while lame-duck; 503
                      "warming"/"stopped" otherwise
    GET  /stats       Server.stats() as JSON
    GET  /metrics     Prometheus text exposition of the monitor registry

ThreadingHTTPServer gives one thread per connection; each handler
thread parks on its request's Future, so concurrent connections batch
together inside the engine exactly like in-process submitters.

Cross-process tracing: a router in front of N replicas sends
X-PTrace-Trace/X-PTrace-Span headers; the handler attaches them as the
parent context, so the replica's serve.http -> serve.request -> batch
spans land in the ROUTER's trace id and one request reconstructs end to
end across processes from the two flight recorders.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import monitor
from .. import trace as _trace
from .engine import (ServeError, ServerClosed, ServerDraining,
                     ServerOverloaded, UnknownModel)

__all__ = ["serve_http", "make_http_server", "TRACE_HEADER",
           "SPAN_HEADER"]

TRACE_HEADER = "X-PTrace-Trace"
SPAN_HEADER = "X-PTrace-Span"

_HEX16 = frozenset("0123456789abcdef")


def _remote_ctx(headers):
    """SpanContext from propagation headers, or None (absent/garbage —
    a malformed header must never fail the request it rode in on)."""
    tid = (headers.get(TRACE_HEADER) or "").strip().lower()
    sid = (headers.get(SPAN_HEADER) or "").strip().lower()
    if len(tid) == 16 and len(sid) == 16 \
            and set(tid) <= _HEX16 and set(sid) <= _HEX16:
        return _trace.SpanContext(tid, sid)
    return None


def _json_feed(payload, server):
    if not isinstance(payload, dict):
        raise ValueError('body must be a JSON object {"inputs": {...}}')
    inputs = payload.get("inputs")
    if not isinstance(inputs, dict):
        raise ValueError('body must be {"inputs": {name: array}}')
    return {n: np.asarray(v, dtype=server._feed_dtype(n))
            if n in server._feed_vars else np.asarray(v)
            for n, v in inputs.items()}


class _Handler(BaseHTTPRequestHandler):
    # the Server instance is attached to the HTTPServer by the factory
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _reply(self, code, body, content_type="application/json",
               headers=None):
        data = body if isinstance(body, bytes) else body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
            if k.lower() == "connection" and v.lower() == "close":
                # the header alone is advisory; actually drop keep-alive
                self.close_connection = True
        self.end_headers()
        self.wfile.write(data)

    def _reply_json(self, code, obj, headers=None):
        self._reply(code, json.dumps(obj), headers=headers)

    def do_GET(self):
        engine = self.server.engine
        if self.path == "/healthz":
            state = engine.state()
            if state == "serving":
                self._reply(200, "ok\n", content_type="text/plain")
            elif state == "draining":
                self._reply(503, "draining\n", content_type="text/plain",
                            headers={"Connection": "close"})
            else:
                self._reply(503, f"{state if state == 'stopped' else 'warming'}\n",
                            content_type="text/plain")
        elif self.path == "/stats":
            self._reply_json(200, engine.stats())
        elif self.path == "/metrics":
            self._reply(200, monitor.registry().exposition(),
                        content_type="text/plain; version=0.0.4")
        else:
            self._reply_json(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        engine = self.server.engine
        if self.path == "/admin/drain":
            self._drain()
            return
        if self.path != "/v1/infer":
            self._reply_json(404, {"error": f"no route {self.path}"})
            return
        # root span of the request's trace: submit() runs inside it, so
        # the engine's serve.request span (and everything under it)
        # inherits this span's trace id — HTTP accept through readback
        # reconstructs as one trace from a flight-recorder dump. When a
        # fleet router sent propagation headers, parent under ITS span
        # instead: the whole fleet hop becomes one cross-process trace.
        remote = _remote_ctx(self.headers) if _trace.enabled() else None
        with _trace.attach(remote) if remote is not None else _noop_cm():
            self._infer(engine)

    def _infer(self, engine):
        with _trace.span("serve.http", kind="serve", path=self.path) as sp:
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
                model = payload.get("model") \
                    if isinstance(payload, dict) else None
                if model is not None and not isinstance(model, str):
                    raise ValueError('"model" must be a string')
                # resolve first: feed dtypes/shapes come from the NAMED
                # model, and an unknown name must 404 before any feed
                # parsing can turn it into a 400
                target = engine.resolve_model(model)
                feed = _json_feed(payload, target)
                steps = payload.get("steps")
                if getattr(engine, "is_continuous", False):
                    fut = engine.submit(
                        feed, model=model,
                        steps=1 if steps is None else int(steps),
                        seed=int(payload.get("seed", 0)))
                elif steps is not None and int(steps) != 1:
                    raise ValueError(
                        '"steps" needs a continuous engine '
                        '(serve.continuous.ContinuousServer)')
                else:
                    fut = engine.submit(feed, model=model)
            except UnknownModel as e:
                sp.set(status=404)
                self._reply_json(404, {"error": str(e)})
                return
            except ServerOverloaded as e:
                # full, not broken: tell the client (or router) to retry
                # elsewhere / later — one batching window is the honest
                # earliest time this replica could admit again
                sp.set(status=503)
                cfg = getattr(engine, "config", None)
                wait_ms = getattr(cfg, "max_wait_ms", None)
                if wait_ms is None:
                    wait_ms = getattr(cfg, "idle_wait_ms", 1000.0)
                retry_s = max(1, int(-(-wait_ms // 1000.0)))
                self._reply_json(503, {"error": str(e)},
                                 headers={"Retry-After": str(retry_s)})
                return
            except ServerDraining as e:
                sp.set(status=503)
                self._reply_json(503, {"error": str(e)},
                                 headers={"Connection": "close"})
                return
            except ServerClosed as e:
                sp.set(status=503)
                self._reply_json(503, {"error": str(e)},
                                 headers={"Connection": "close"})
                return
            except (ValueError, ServeError) as e:
                sp.set(status=400)
                self._reply_json(400, {"error": str(e)})
                return
            try:
                outs = fut.result()
            except ServerClosed as e:
                sp.set(status=503)
                self._reply_json(503, {"error": str(e)},
                                 headers={"Connection": "close"})
                return
            except Exception as e:  # noqa: BLE001 — surface model errors
                sp.set(status=500)
                self._reply_json(500, {"error": f"{type(e).__name__}: {e}"})
                return
            sp.set(status=200)
            self._reply_json(200, {
                "outputs": [np.asarray(o).tolist() for o in outs]})

    def _drain(self):
        """Kick the lame-duck drain on a background thread and answer
        immediately: the caller polls /healthz ("draining" -> connection
        refused / "stopped") instead of holding a socket open for the
        whole backlog."""
        engine = self.server.engine
        httpd = self.server
        already = engine.state() in ("draining", "stopped")

        def _run():
            engine.drain()
            if getattr(httpd, "shutdown_on_drain", False):
                httpd.shutdown()

        if not already:
            threading.Thread(target=_run, name="serve-drain",
                             daemon=True).start()
        self._reply_json(202, {"state": "draining", "already": already},
                         headers={"Connection": "close"})


class _noop_cm:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


def make_http_server(engine, host="127.0.0.1", port=8000,
                     shutdown_on_drain=False):
    """A ThreadingHTTPServer bound to (host, port), serving `engine`.
    Caller owns serve_forever()/shutdown() (tests run it in a thread).
    With shutdown_on_drain, a completed /admin/drain also shuts the HTTP
    loop down, so a CLI replica process exits clean after its drain."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    httpd.engine = engine
    httpd.shutdown_on_drain = shutdown_on_drain
    return httpd


def serve_http(engine, host="127.0.0.1", port=8000,
               shutdown_on_drain=False):
    """Blocking frontend: serve until KeyboardInterrupt (or a completed
    /admin/drain when shutdown_on_drain), then stop both."""
    httpd = make_http_server(engine, host, port,
                             shutdown_on_drain=shutdown_on_drain)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        httpd.server_close()
        engine.stop()
