"""Autoscaler: hold a latency target by resizing the serving fleet.

The control loop watches two signals the router already produces —

  * a WINDOWED p99 of router-side request latency (successive diffs of
    the `fleet_request_ms` histogram, not the since-boot percentiles),
  * total queued rows across routable replicas (the same depth the
    dispatch policy spreads against);

and holds `target_p99_ms` with the standard guards against flapping:

  hysteresis   scale-out arms when p99 > target (or the queue passes
               `high_queue_rows`); scale-in only arms when p99 is
               BELOW target * hysteresis AND the queue is empty —
               the dead band between the two thresholds holds steady
  breach/calm  consecutive-round counters: one hot tick (a compile
  rounds       stall, a probe hiccup) never spawns a process, one calm
               tick never kills one
  cooldowns    independent scale-out / scale-in refractory periods, so
               capacity added for a surge gets a chance to absorb it
               before the loop reconsiders
  bounds       min_replicas <= fleet <= max_replicas, always

Scale-out spawns replica processes through a pluggable spawner and
registers them on the router's membership (the unified epoch-fenced
MembershipTable — the same join/TTL/reap contract elastic training
uses); the prober grants routability on the first passing probe. With
FLAGS_compile_service wired to the replicas, spin-up is pure
deserialization: the new replica fetches every compiled executable by
digest and reports compile_cache_misses == 0.

Scale-in NEVER kills: it picks a victim (LIFO over surge capacity),
runs `Router.drain()` — LAME_DUCK, finish the backlog, exit — and only
then reaps the process, so no accepted request is lost. The green_gate
autoscale drill proves the whole loop against real processes under a
`load_spike` chaos surge.
"""

import math
import os
import subprocess
import threading
import time

from ... import monitor
from .membership import DEGRADED, HEALTHY
from .policy import scale_in_victim

__all__ = ["AutoscalerConfig", "Autoscaler", "ProcessReplicaSpawner"]


class AutoscalerConfig:
    """`model_targets` maps model name -> per-model p99 target (ms).
    Each named model gets its OWN latency window over the router's
    fleet_request_ms{model=} series; a breach on ANY of them arms
    scale-out even while the aggregate window sits under target — a
    high-traffic cold model can no longer mask a hot one. Scale-in
    additionally requires every named model calm (below its target *
    hysteresis)."""

    def __init__(self, target_p99_ms=500.0, high_queue_rows=None,
                 min_replicas=1, max_replicas=4, scale_step=1,
                 breach_rounds=2, calm_rounds=6, hysteresis=0.5,
                 cooldown_out_s=5.0, cooldown_in_s=30.0,
                 interval_s=1.0, drain_timeout_s=60.0,
                 model_targets=None):
        self.model_targets = {str(k): float(v) for k, v in
                              (model_targets or {}).items()}
        if any(v <= 0 for v in self.model_targets.values()):
            raise ValueError("model_targets values must be > 0")
        self.target_p99_ms = float(target_p99_ms)
        self.high_queue_rows = (None if high_queue_rows is None
                                else float(high_queue_rows))
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_step = int(scale_step)
        self.breach_rounds = int(breach_rounds)
        self.calm_rounds = int(calm_rounds)
        self.hysteresis = float(hysteresis)
        self.cooldown_out_s = float(cooldown_out_s)
        self.cooldown_in_s = float(cooldown_in_s)
        self.interval_s = float(interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        if self.target_p99_ms <= 0:
            raise ValueError("target_p99_ms must be > 0")
        if not 0 < self.hysteresis <= 1.0:
            raise ValueError("hysteresis must be in (0, 1]")
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")
        if self.scale_step < 1 or self.breach_rounds < 1 \
                or self.calm_rounds < 1:
            raise ValueError("scale_step/breach_rounds/calm_rounds "
                             "must be >= 1")


def _window_p99(edges, prev, cur, p=0.99):
    """p99 over the requests BETWEEN two cumulative histogram snapshots
    (monitor.Histogram.snapshot()["buckets"]); None when the window is
    empty. Linear interpolation inside the winning bucket; the +Inf
    bucket conservatively reports its finite lower edge."""
    def key(edge):
        return "+Inf" if math.isinf(edge) else edge

    counts, total = [], 0
    for edge in edges:
        c = cur.get(key(edge), 0) - (prev or {}).get(key(edge), 0)
        counts.append((edge, c - total))
        total = c
    if total <= 0:
        return None
    rank = p * total
    seen = 0.0
    lo = 0.0
    for edge, n in counts:
        if n > 0:
            if seen + n >= rank:
                if math.isinf(edge):
                    return lo
                frac = (rank - seen) / n
                return lo + (edge - lo) * frac
            seen += n
        if not math.isinf(edge):
            lo = edge
    return lo


class Autoscaler:
    """The loop. `router` needs .membership, .prober, .latency_window()
    and .drain(); `spawner` needs .spawn_many(n) -> [(name, endpoint)]
    and .stop(name) -> exit code (ProcessReplicaSpawner, or a fake in
    tests). tick() is public and synchronous so tests drive the state
    machine with an injected clock instead of sleeping."""

    def __init__(self, router, spawner, config=None, clock=None):
        self.router = router
        self.spawner = spawner
        self.config = config if config is not None else AutoscalerConfig()
        self._clock = clock if clock is not None else time.monotonic
        self._prev_window = None
        self._prev_model_windows = {}  # model -> last cumulative counts
        self._breach = 0
        self._calm = 0
        self._last_out = None
        self._last_in = None
        self._spawned = []  # names we scaled out, oldest first
        self._stop = threading.Event()
        self._thread = None
        self.last_p99 = None
        self.last_queue = 0.0
        self.last_model_p99 = {}   # model -> windowed p99 (or None)
        self.last_hot_models = []  # models breaching their target now
        self.scale_outs = 0
        self.scale_ins = 0
        self.drain_reports = []

    # -- loop -----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=30.0)
        self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must not die
                pass
            self._stop.wait(self.config.interval_s)

    # -- one control round ----------------------------------------------
    def _signals(self):
        edges, cum = self.router.latency_window()
        p99 = _window_p99(edges, self._prev_window, cum)
        self._prev_window = dict(cum)
        routable = [r for r in self.router.membership.replicas()
                    if r.state in (HEALTHY, DEGRADED)]
        queue = sum(r.queue_rows for r in routable)
        return p99, queue, routable

    def _model_signals(self):
        """{model: windowed p99 or None} for every model in
        model_targets, each over its OWN fleet_request_ms{model=}
        series — one hot model stays visible through any amount of
        cold-model traffic in the aggregate window."""
        out = {}
        for model in self.config.model_targets:
            edges, cum = self.router.latency_window(model=model)
            out[model] = _window_p99(
                edges, self._prev_model_windows.get(model), cum)
            self._prev_model_windows[model] = dict(cum)
        return out

    def tick(self):
        cfg = self.config
        now = self._clock()
        p99, queue, routable = self._signals()
        model_p99 = self._model_signals()
        hot_models = [m for m, v in model_p99.items()
                      if v is not None and v > cfg.model_targets[m]]
        self.last_p99, self.last_queue = p99, queue
        self.last_model_p99 = model_p99
        self.last_hot_models = hot_models
        live = len(routable)
        hot = (p99 is not None and p99 > cfg.target_p99_ms) or \
            (cfg.high_queue_rows is not None
             and queue >= cfg.high_queue_rows) or bool(hot_models)
        models_calm = all(
            v is None or v <= cfg.model_targets[m] * cfg.hysteresis
            for m, v in model_p99.items())
        cold = queue == 0 and models_calm and \
            (p99 is None or p99 <= cfg.target_p99_ms * cfg.hysteresis)
        if hot:
            self._breach += 1
            self._calm = 0
        elif cold:
            self._calm += 1
            self._breach = 0
        else:
            # the hysteresis dead band: neither counter advances
            self._breach = self._calm = 0
        self._gauges(p99, live)
        if self._breach >= cfg.breach_rounds and live < cfg.max_replicas \
                and self._cooled(self._last_out, cfg.cooldown_out_s, now):
            self._scale_out(min(cfg.scale_step,
                                cfg.max_replicas - live), now)
        elif self._calm >= cfg.calm_rounds and live > cfg.min_replicas \
                and self._cooled(self._last_in, cfg.cooldown_in_s, now):
            self._scale_in(routable, now)

    @staticmethod
    def _cooled(last, cooldown_s, now):
        return last is None or now - last >= cooldown_s

    def _scale_out(self, n, now):
        for name, endpoint in self.spawner.spawn_many(n):
            # membership join = the unified table's epoch-fenced JOIN;
            # the prober grants routability on the first passing probe
            self.router.membership.heartbeat(name, endpoint)
            self._spawned.append(name)
            self.scale_outs += 1
            monitor.registry().counter(
                "fleet_autoscaler_scale_outs_total",
                help="replicas spawned by the autoscaler").inc()
        self._last_out = now
        self._breach = 0

    def _scale_in(self, routable, now):
        victim = scale_in_victim(routable, prefer=self._spawned)
        if victim is None:
            return
        report = self.router.drain(
            victim, timeout_s=self.config.drain_timeout_s)
        # a cleanly drained replica exits on its own (shutdown_on_drain);
        # give it that exit before reaping, or stop() SIGTERMs a process
        # that is mid-teardown and records a bogus -15
        rc = None
        waiter = getattr(self.spawner, "wait", None)
        if report.get("drained") and waiter is not None:
            rc = waiter(victim, timeout_s=30.0)
        if rc is None:
            rc = self.spawner.stop(victim)
        report["exit_code"] = rc
        self.drain_reports.append(report)
        if victim in self._spawned:
            self._spawned.remove(victim)
        self.router.membership.remove(victim)
        self.scale_ins += 1
        monitor.registry().counter(
            "fleet_autoscaler_scale_ins_total",
            help="replicas drained away by the autoscaler").inc()
        self._last_in = now
        self._calm = 0

    def _gauges(self, p99, live):
        reg = monitor.registry()
        reg.gauge("fleet_autoscaler_routable_replicas",
                  help="routable replicas the autoscaler sees").set(live)
        if p99 is not None:
            reg.gauge("fleet_autoscaler_window_p99_ms",
                      help="windowed router p99 driving scale "
                           "decisions").set(p99)
        for m, v in self.last_model_p99.items():
            if v is not None:
                reg.gauge("fleet_autoscaler_window_p99_ms",
                          model=m).set(v)

    def describe(self):
        return {"p99_ms": self.last_p99, "queue_rows": self.last_queue,
                "model_p99_ms": dict(self.last_model_p99),
                "hot_models": list(self.last_hot_models),
                "breach_rounds": self._breach, "calm_rounds": self._calm,
                "scale_outs": self.scale_outs,
                "scale_ins": self.scale_ins,
                "spawned": list(self._spawned)}


class ProcessReplicaSpawner:
    """Spawn `paddle_tpu fleet replica` subprocesses for scale-out.

    `argv_base` is the full replica command line minus --name/--port-
    file (e.g. [sys.executable, "-m", "paddle_tpu", "fleet", "replica",
    "--model-dir", ..., "--place", "cpu", "--port", "0",
    "--compile-service", host_port]). Each spawn appends a unique name
    and a port file, waits for the replica to bind, and returns
    (name, endpoint).

    per_replica_cache gives every replica its OWN --cache-dir under
    `workdir` — a fresh host's L2 starts empty, so warm start must come
    through fetch_compiled, never a shared filesystem (this is what the
    drill's compile_cache_misses == 0 assertion actually proves).
    """

    def __init__(self, argv_base, workdir, name_prefix="as", env=None,
                 per_replica_cache=False, start_timeout_s=180.0):
        self.argv_base = list(argv_base)
        self.workdir = str(workdir)
        self.name_prefix = name_prefix
        self.env = dict(env) if env is not None else None
        self.per_replica_cache = bool(per_replica_cache)
        self.start_timeout_s = float(start_timeout_s)
        self.procs = {}      # name -> Popen
        self.endpoints = {}  # name -> host:port
        self.exit_codes = {}
        self._seq = 0
        self._lock = threading.Lock()

    def _next_name(self):
        with self._lock:
            name = f"{self.name_prefix}{self._seq}"
            self._seq += 1
            return name

    def _launch(self, name):
        os.makedirs(self.workdir, exist_ok=True)
        port_file = os.path.join(self.workdir, f"{name}.port")
        try:
            os.unlink(port_file)
        except OSError:
            pass
        argv = self.argv_base + ["--name", name, "--port-file", port_file]
        if self.per_replica_cache:
            argv += ["--cache-dir",
                     os.path.join(self.workdir, f"cache-{name}")]
        proc = subprocess.Popen(argv, env=self.env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)
        self.procs[name] = proc
        return name, port_file

    def _await_port(self, name, port_file):
        deadline = time.monotonic() + self.start_timeout_s
        while time.monotonic() < deadline:
            if os.path.exists(port_file):
                try:
                    port = int(open(port_file).read().strip() or 0)
                except ValueError:
                    port = 0
                if port:
                    endpoint = f"127.0.0.1:{port}"
                    self.endpoints[name] = endpoint
                    return name, endpoint
            proc = self.procs.get(name)
            if proc is not None and proc.poll() is not None:
                raise RuntimeError(
                    f"replica {name} exited rc={proc.returncode} "
                    "before binding")
            time.sleep(0.1)
        raise RuntimeError(f"replica {name} did not bind within "
                           f"{self.start_timeout_s}s")

    def spawn_many(self, n):
        """Start n replicas CONCURRENTLY (their interpreter+jax imports
        overlap), then wait for every port file; -> [(name, endpoint)].
        A replica that fails to bind is reaped and skipped — scale-out
        returns what actually came up."""
        launched = [self._launch(self._next_name()) for _ in range(n)]
        out = []
        for name, port_file in launched:
            try:
                out.append(self._await_port(name, port_file))
            except RuntimeError:
                self.stop(name, timeout_s=5.0)
        return out

    def spawn(self):
        return self.spawn_many(1)[0]

    def wait(self, name, timeout_s=30.0):
        """Wait for a replica to exit on its own (the post-drain path);
        returns its exit code, or None if it is still running."""
        proc = self.procs.get(name)
        if proc is None:
            return self.exit_codes.get(name)
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return None
        self.exit_codes[name] = rc
        return rc

    def stop(self, name, timeout_s=30.0):
        """Reap one replica process (AFTER Router.drain() — SIGTERM here
        triggers the replica's graceful drain path as a backstop).
        Returns the exit code, or None if it had to be killed."""
        proc = self.procs.get(name)
        if proc is None:
            return self.exit_codes.get(name)
        if proc.poll() is None:
            try:
                proc.terminate()
                proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)
            except OSError:
                pass
        self.exit_codes[name] = proc.returncode
        return proc.returncode

    def stop_all(self, timeout_s=30.0):
        for name in list(self.procs):
            self.stop(name, timeout_s=timeout_s)
        return dict(self.exit_codes)
