"""Dispatch policy: which routable replica gets the next request.

Least-queue-depth over the healthy set, falling back to degraded
replicas only when no healthy one is eligible. queue depth comes from
the last /stats probe (the engine's row-accounted admission queue), so
the policy naturally spreads load away from a replica whose batcher is
falling behind — the same signal its own admission control would
eventually 503 on. Ties rotate deterministically so equal replicas
share load instead of the dict-order replica eating it all.

Multi-model: when the request names a model, the pick is SLO-weighted
over the replicas HOSTING that model (a replica's /stats advertises a
"models" block; one without the block predates multi-model and is
assumed to serve everything). Each candidate scores
queue_rows + p99/slo lag on that model, so a replica running the named
model hot against its own SLO loses the pick even when its queue is
level with the rest.
"""

import threading

from .membership import HEALTHY

__all__ = ["LeastQueueDepthPolicy", "scale_in_victim"]


def scale_in_victim(candidates, prefer=()):
    """Which routable replica the autoscaler should drain next.

    Prefer the most recently autoscaled-up replica that is still
    routable (LIFO: the baseline fleet outlives the surge capacity);
    otherwise the shallowest queue loses — draining the replica with the
    least backlog finishes fastest and strands the least work behind a
    LAME_DUCK. Returns a name or None."""
    names = {r.name: r for r in candidates}
    for name in reversed(list(prefer)):
        if name in names:
            return name
    if not names:
        return None
    return min(sorted(names.values(), key=lambda r: r.name),
               key=lambda r: r.queue_rows).name


def _hosts_model(replica, model):
    """Does this replica serve `model`? A replica whose /stats never
    advertised a "models" block predates multi-model — treat it as
    serving everything (backward compatible with old replicas)."""
    models = (replica.stats or {}).get("models")
    if not models:
        return True
    return model in models


def _model_lag(replica, model):
    """p99/slo pressure of `model` on this replica, in queue-row-
    comparable units: 0 when unknown, p99_ms / slo_ms otherwise. A
    replica at 2x its SLO on the named model scores as two phantom
    queued rows per SLO of lag."""
    st = ((replica.stats or {}).get("models") or {}).get(model)
    if not st:
        return 0.0
    p99, slo = st.get("p99_ms"), st.get("slo_ms")
    if p99 is None or p99 != p99 or not slo:
        return 0.0
    return float(p99) / float(slo)


class LeastQueueDepthPolicy:
    def __init__(self):
        self._lock = threading.Lock()
        self._ticket = 0

    def pick(self, candidates, exclude=(), model=None):
        """-> Replica or None. `candidates` come from
        Membership.candidates() (already routable); `exclude` holds the
        names this request already tried; `model` (optional) restricts
        to replicas hosting it and weights the pick by that model's
        SLO lag."""
        eligible = [r for r in candidates if r.name not in exclude]
        if model is not None:
            hosting = [r for r in eligible if _hosts_model(r, model)]
            # nobody advertises the model: fall back to the full pool
            # and let the replica answer 404 (deterministic, unretried)
            eligible = hosting or eligible
        if not eligible:
            return None
        healthy = [r for r in eligible if r.state == HEALTHY]
        pool = healthy or eligible

        def score(r):
            s = r.queue_rows
            if model is not None:
                s += _model_lag(r, model)
            return s

        best = min(score(r) for r in pool)
        ties = sorted((r for r in pool if score(r) == best),
                      key=lambda r: r.name)
        with self._lock:
            self._ticket += 1
            return ties[self._ticket % len(ties)]
