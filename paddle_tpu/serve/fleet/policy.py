"""Dispatch policy: which routable replica gets the next request.

Least-queue-depth over the healthy set, falling back to degraded
replicas only when no healthy one is eligible. queue depth comes from
the last /stats probe (the engine's row-accounted admission queue), so
the policy naturally spreads load away from a replica whose batcher is
falling behind — the same signal its own admission control would
eventually 503 on. Ties rotate deterministically so equal replicas
share load instead of the dict-order replica eating it all.
"""

import threading

from .membership import HEALTHY

__all__ = ["LeastQueueDepthPolicy", "scale_in_victim"]


def scale_in_victim(candidates, prefer=()):
    """Which routable replica the autoscaler should drain next.

    Prefer the most recently autoscaled-up replica that is still
    routable (LIFO: the baseline fleet outlives the surge capacity);
    otherwise the shallowest queue loses — draining the replica with the
    least backlog finishes fastest and strands the least work behind a
    LAME_DUCK. Returns a name or None."""
    names = {r.name: r for r in candidates}
    for name in reversed(list(prefer)):
        if name in names:
            return name
    if not names:
        return None
    return min(sorted(names.values(), key=lambda r: r.name),
               key=lambda r: r.queue_rows).name


class LeastQueueDepthPolicy:
    def __init__(self):
        self._lock = threading.Lock()
        self._ticket = 0

    def pick(self, candidates, exclude=()):
        """-> Replica or None. `candidates` come from
        Membership.candidates() (already routable); `exclude` holds the
        names this request already tried."""
        eligible = [r for r in candidates if r.name not in exclude]
        if not eligible:
            return None
        healthy = [r for r in eligible if r.state == HEALTHY]
        pool = healthy or eligible
        best = min(r.queue_rows for r in pool)
        ties = sorted((r for r in pool if r.queue_rows == best),
                      key=lambda r: r.name)
        with self._lock:
            self._ticket += 1
            return ties[self._ticket % len(ties)]
