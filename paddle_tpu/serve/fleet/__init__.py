"""paddle_tpu.serve.fleet — fault-tolerant multi-replica serving.

One Router load-balances POST /v1/infer over N serve.http replicas:

    from paddle_tpu.serve import fleet

    router = fleet.Router({"r0": "127.0.0.1:8001",
                           "r1": "127.0.0.1:8002",
                           "r2": "127.0.0.1:8003"})
    with router:                       # health probing runs
        status, headers, body = router.route(payload)
        router.drain("r1")             # lame-duck + wait for exit

Membership tracks healthy / degraded / dead / lame_duck per replica
(active /healthz + /stats probes, per-replica circuit breakers), with
liveness riding the elastic master's TTL'd epoch-fenced MembershipTable
— ONE membership primitive serves elastic training and the fleet;
routing picks least-queue-depth and owns failures — 503s and
transient transport faults retry on another replica under a per-request
deadline and a fleet-wide retry budget, with optional hedging. Killing
one of N replicas mid-load loses zero accepted requests; draining one
finishes its backlog and exits clean (rolling restarts drop nothing).

An Autoscaler holds a latency target by spawning/draining replica
processes (hysteresis, cooldowns, min/max bounds; scale-in reuses
Router.drain so nothing accepted is lost), and FLAGS_compile_service
makes scale-out warm: new replicas fetch serialized executables by
digest instead of compiling (compile_cache_misses == 0 on joiners).

`python -m paddle_tpu fleet replica|router ...` runs either half as a
process; `make_fleet_http` is the router's own HTTP frontend.
"""

from .autoscaler import Autoscaler, AutoscalerConfig, ProcessReplicaSpawner
from .health import HealthProber, http_fetch
from .membership import (DEAD, DEGRADED, HEALTHY, LAME_DUCK, STATE_VALUES,
                         CircuitBreaker, Membership, Replica)
from .policy import LeastQueueDepthPolicy, scale_in_victim
from .router import (FleetConfig, Router, http_transport, make_fleet_http,
                     serve_fleet)

__all__ = [
    "HEALTHY", "DEGRADED", "DEAD", "LAME_DUCK", "STATE_VALUES",
    "CircuitBreaker", "Replica", "Membership",
    "HealthProber", "http_fetch",
    "LeastQueueDepthPolicy", "scale_in_victim",
    "Autoscaler", "AutoscalerConfig", "ProcessReplicaSpawner",
    "FleetConfig", "Router", "http_transport", "make_fleet_http",
    "serve_fleet",
]
