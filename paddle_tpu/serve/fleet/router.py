"""Fleet router: failure ownership for N interchangeable replicas.

The replica-level independence argument cuts both ways: if replicas are
interchangeable, the CLIENT should never see one die — the router owns
the failure. One request through `route()` gets:

  dispatch   least-queue-depth pick over routable replicas, gated by
             each replica's circuit breaker (a half-open breaker admits
             exactly one probe request)
  retry      a 503 answer (overloaded / draining) or a transient
             transport fault (resilience.errors.is_transient — resets,
             refused connects, timeouts) moves the request to ANOTHER
             replica; deterministic answers (2xx/4xx/5xx-non-503) pass
             through untouched
  deadline   a wall-clock budget per request; retries never start work
             the deadline cannot pay for (504 once it expires)
  budget     a fleet-wide RetryBudget: each admitted request deposits
             `ratio` tokens, each retry spends one — a partial outage
             cannot multiply offered load into a total one
  hedge      optionally, if the first attempt hasn't answered within
             hedge_ms, a second replica races it and the first answer
             wins (p99 tail insurance, bounded by the same budget-free
             single extra request)

Tracing: route() opens a `fleet.request` span with one `fleet.attempt`
child per try; the attempt's context rides the X-PTrace-* headers into
the replica, whose serve.http -> serve.request -> batch spans land in
the SAME trace id — a router-level dump reconstructs one request across
processes.
"""

import http.client
import json
import queue
import threading
import time

from ... import monitor
from ... import trace as _trace
from ...resilience.errors import is_transient
from ...resilience.retry import RetryBudget
from ..http import SPAN_HEADER, TRACE_HEADER
from .health import HealthProber, http_fetch
from .membership import DEAD, LAME_DUCK, Membership
from .policy import LeastQueueDepthPolicy

__all__ = ["FleetConfig", "Router", "make_fleet_http", "serve_fleet"]


class FleetConfig:
    """Tuning knobs for one Router.

    probe_interval_s     health-probe sweep cadence; also how fast a
                         dead replica leaves the routable set
    heartbeat_ttl_s      membership lease for heartbeat-registered
                         replicas (silence past this -> dead)
    breaker_failures     consecutive failures (request or probe) that
                         open a replica's circuit breaker
    breaker_cooldown_s   open-breaker cooldown before half-opening
    request_deadline_ms  wall-clock SLO per routed request; retries stop
                         when it cannot be met (504 past it)
    attempt_timeout_ms   per-attempt transport timeout; None = whatever
                         of the deadline remains (set it lower so one
                         wedged replica costs an attempt, not the SLO)
    max_attempts         tries per request including the first
    retry_budget_ratio / retry_budget_burst
                         fleet-wide retry token bucket (see RetryBudget)
    hedge_ms             fire a second replica if the first attempt is
                         silent this long; None = no hedging
    degraded_queue_rows / degraded_p99_ms
                         probe thresholds demoting healthy -> degraded
    """

    def __init__(self, probe_interval_s=0.5, heartbeat_ttl_s=10.0,
                 breaker_failures=3, breaker_cooldown_s=2.0,
                 request_deadline_ms=30000.0, attempt_timeout_ms=None,
                 max_attempts=3, retry_budget_ratio=0.2,
                 retry_budget_burst=16, hedge_ms=None,
                 degraded_queue_rows=None, degraded_p99_ms=None):
        self.probe_interval_s = float(probe_interval_s)
        self.heartbeat_ttl_s = float(heartbeat_ttl_s)
        self.breaker_failures = int(breaker_failures)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.request_deadline_ms = float(request_deadline_ms)
        self.attempt_timeout_ms = (None if attempt_timeout_ms is None
                                   else float(attempt_timeout_ms))
        self.max_attempts = int(max_attempts)
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {max_attempts}")
        self.retry_budget_ratio = float(retry_budget_ratio)
        self.retry_budget_burst = float(retry_budget_burst)
        self.hedge_ms = None if hedge_ms is None else float(hedge_ms)
        self.degraded_queue_rows = degraded_queue_rows
        self.degraded_p99_ms = degraded_p99_ms


def http_transport(endpoint, path, body, headers, timeout_s):
    """POST over a fresh connection -> (status, headers, body). Fresh on
    purpose: after a replica dies, a pooled keep-alive socket would turn
    the first post-death request into a confusing reset mid-reuse; a
    fresh connect turns it into an immediate, classifiable refusal."""
    host, port = endpoint.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout_s)
    try:
        conn.request("POST", path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _err_body(msg):
    return json.dumps({"error": msg}).encode("utf-8")


# connection-scoped headers that must not ride through the router: the
# router re-frames the body (Content-Length) and owns its own client
# connections (Connection/Keep-Alive); end-to-end ones (Content-Type,
# model metadata, Retry-After) pass through
_HOP_BY_HOP = frozenset({
    "connection", "content-length", "date", "keep-alive",
    "proxy-authenticate", "proxy-authorization", "server", "te",
    "trailer", "transfer-encoding", "upgrade"})


def _end_to_end(upstream_headers):
    return {k: v for k, v in (upstream_headers or {}).items()
            if k.lower() not in _HOP_BY_HOP}


class _attach_maybe:
    """attach(ctx) when tracing gave us one, no-op otherwise."""

    __slots__ = ("_cm",)

    def __init__(self, ctx):
        self._cm = _trace.attach(ctx) if ctx is not None else None

    def __enter__(self):
        if self._cm is not None:
            self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        if self._cm is not None:
            return self._cm.__exit__(*exc)
        return False


class Router:
    """Load balancer + failure owner over registered Server replicas.

        router = Router({"r0": "127.0.0.1:8001", "r1": "127.0.0.1:8002"})
        router.start()                          # health probing begins
        status, headers, body = router.route(payload_bytes)
        report = router.drain("r0")             # lame-duck + wait
        router.stop()

    `replicas` maps name -> "host:port" of a serve.http frontend; more
    join later via heartbeat() (HTTP /admin/register) or a `discover`
    source (e.g. MasterClient.lookup over the master's TTL registry).
    """

    def __init__(self, replicas=None, config=None, fetch=None,
                 transport=None, discover=None):
        self.config = config or FleetConfig()
        cfg = self.config
        self.membership = Membership(
            heartbeat_ttl_s=cfg.heartbeat_ttl_s,
            breaker_failures=cfg.breaker_failures,
            breaker_cooldown_s=cfg.breaker_cooldown_s)
        self.policy = LeastQueueDepthPolicy()
        self.budget = RetryBudget(ratio=cfg.retry_budget_ratio,
                                  burst=cfg.retry_budget_burst)
        self._fetch = fetch if fetch is not None else http_fetch
        self.transport = (transport if transport is not None
                          else http_transport)
        self.prober = HealthProber(
            self.membership, interval_s=cfg.probe_interval_s,
            fetch=self._fetch, discover=discover,
            degraded_queue_rows=cfg.degraded_queue_rows,
            degraded_p99_ms=cfg.degraded_p99_ms)
        for name, endpoint in (replicas or {}).items():
            self.membership.add(name, endpoint)
        # per-router tallies next to the registry series (same idiom as
        # Server._own: two routers in one process must not conflate)
        self._own = {n: monitor.Counter(n) for n in
                     ("requests", "retries", "hedges", "hedge_wins",
                      "failures", "budget_exhausted",
                      "deadline_exceeded")}
        from ..engine import SERVE_MS_BUCKETS

        self._own_request_ms = monitor.Histogram(
            "fleet_request_ms", buckets=SERVE_MS_BUCKETS)
        # per-model router-side latency, keyed lazily by the model names
        # actually seen on the wire; the autoscaler windows each series
        # independently so one hot model is visible through a cold one
        self._own_model_ms = {}
        self._model_lock = threading.Lock()

    # -- lifecycle ------------------------------------------------------
    def start(self):
        self.prober.tick()  # synchronous first sweep: routable at return
        self.prober.start()
        return self

    def stop(self):
        self.prober.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        return False

    def heartbeat(self, name, endpoint):
        return self.membership.heartbeat(name, endpoint)

    # -- request path ---------------------------------------------------
    def _counter(self, own_name, reg_name, help_):
        self._own[own_name].inc()
        monitor.registry().counter(reg_name, help=help_).inc()

    def _acquire(self, exclude, model=None):
        """Next replica per policy whose breaker admits a request."""
        skip = set(exclude)
        while True:
            rep = self.policy.pick(self.membership.candidates(skip),
                                   model=model)
            if rep is None:
                return None
            if rep.breaker.try_acquire():
                return rep
            skip.add(rep.name)

    def _attempt_timeout(self, remaining_s):
        cap = self.config.attempt_timeout_ms
        if cap is None:
            return remaining_s
        return min(remaining_s, cap / 1000.0)

    def _send(self, rep, body, headers, timeout_s, attempt, parent_ctx,
              hedge):
        hdrs = dict(headers or {})
        with _attach_maybe(parent_ctx):
            with _trace.span("fleet.attempt", kind="fleet",
                             replica=rep.name, attempt=attempt,
                             hedge=hedge) as sp:
                if sp.ctx is not None:
                    hdrs[TRACE_HEADER] = sp.ctx.trace_id
                    hdrs[SPAN_HEADER] = sp.ctx.span_id
                status, rh, rb = self.transport(
                    rep.endpoint, "/v1/infer", body, hdrs, timeout_s)
                sp.set(status=status)
                return status, rh, rb

    def _hedged(self, rep, body, headers, timeout_s, parent_ctx, tried,
                model=None):
        """Race a second replica against a silent first attempt; first
        answer (success OR failure) wins, the loser is reaped off-path so
        its breaker outcome still lands. The loser's name goes into
        `tried` — it still holds the request in flight, so a later retry
        must not resend to it. Total wait stays within timeout_s: the
        post-hedge wait is what remains of it after the hedge_ms spent
        listening for the first attempt."""
        results = queue.Queue()

        def fire(r, hedge):
            try:
                results.put((r, self._send(r, body, headers, timeout_s,
                                           0, parent_ctx, hedge), None))
            except Exception as e:  # noqa: BLE001 — classified by caller
                results.put((r, None, e))

        fired = 1
        second = None
        t0 = time.perf_counter()
        threading.Thread(target=fire, args=(rep, False),
                         name="fleet-send", daemon=True).start()
        try:
            winner = results.get(timeout=self.config.hedge_ms / 1000.0)
        except queue.Empty:
            second = self._acquire(set(tried) | {rep.name}, model=model)
            if second is not None:
                self._counter("hedges", "fleet_hedges_total",
                              "hedged (raced) requests fired")
                fired += 1
                threading.Thread(target=fire, args=(second, True),
                                 name="fleet-hedge", daemon=True).start()
            remaining = timeout_s - (time.perf_counter() - t0)
            try:
                winner = results.get(timeout=max(0.0, remaining))
            except queue.Empty:
                if second is not None:
                    tried.add(second.name)  # silent, but still in flight
                raise TimeoutError(
                    f"no answer from {rep.name} within {timeout_s:.3f}s "
                    f"(hedged={fired > 1})") from None
        if fired > 1:
            w_rep, w_out, w_err = winner
            loser = second if w_rep is rep else rep
            tried.add(loser.name)
            if w_rep is not rep and w_err is None:
                self._counter("hedge_wins", "fleet_hedge_wins_total",
                              "hedged requests answered by the hedge")

            def reap(expected):
                for _ in range(expected):
                    try:
                        r, out, err = results.get(timeout=timeout_s + 1.0)
                    except queue.Empty:
                        return
                    if err is None and out[0] != 503:
                        r.breaker.record_success()
                    else:
                        r.breaker.record_failure()

            threading.Thread(target=reap, args=(fired - 1,),
                             name="fleet-reap", daemon=True).start()
        return winner

    def route(self, body, headers=None, model=None):
        """Route one POST /v1/infer body -> (status, headers, body).
        `model` (the request's "model" field, extracted by the frontend)
        weights the replica pick by that model's SLO lag and labels the
        latency observation."""
        cfg = self.config
        t_start = time.perf_counter()
        deadline = t_start + cfg.request_deadline_ms / 1000.0
        self._counter("requests", "fleet_router_requests_total",
                      "requests accepted by the fleet router")
        self.budget.on_request()
        tried = set()
        attempts = 0
        last = (503, {}, _err_body("no routable replica"))
        with _trace.span("fleet.request", kind="fleet") as fsp:
            while True:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    self._counter("deadline_exceeded",
                                  "fleet_deadline_exceeded_total",
                                  "requests past their routing deadline")
                    last = (504, {}, _err_body("request deadline "
                                               "exceeded"))
                    break
                rep = self._acquire(tried, model=model)
                if rep is None:
                    break
                attempts += 1
                timeout_s = self._attempt_timeout(remaining)
                out, err = None, None
                try:
                    if attempts == 1 and cfg.hedge_ms is not None:
                        rep, out, err = self._hedged(
                            rep, body, headers, timeout_s, fsp.ctx,
                            tried, model=model)
                    else:
                        out = self._send(rep, body, headers, timeout_s,
                                         attempts - 1, fsp.ctx, False)
                except Exception as e:  # noqa: BLE001 — classified below
                    err = e
                if err is None and out[0] != 503:
                    # deterministic answer (2xx/4xx/500): the replica is
                    # functioning — pass it through, close the breaker
                    rep.breaker.record_success()
                    status, rh, rb = out
                    fsp.set(status=status, attempts=attempts,
                            replica=rep.name)
                    self._observe(t_start, model)
                    out_headers = _end_to_end(rh)
                    out_headers["X-Fleet-Replica"] = rep.name
                    out_headers["X-Fleet-Attempts"] = str(attempts)
                    return status, out_headers, rb
                if err is not None and not is_transient(err):
                    # programmer/config error on OUR side of the wire —
                    # retrying elsewhere cannot change it
                    self._counter("failures", "fleet_router_failures_total",
                                  "requests the router could not place")
                    fsp.set(status=502, error=type(err).__name__)
                    self._observe(t_start, model)
                    return 502, {"X-Fleet-Attempts": str(attempts)}, \
                        _err_body(f"{type(err).__name__}: {err}")
                # retryable: 503 from the replica or a transient fault
                rep.breaker.record_failure()
                if isinstance(err, ConnectionRefusedError):
                    # nothing listening: don't wait for the prober
                    self.membership.set_state(rep, DEAD, error=err)
                tried.add(rep.name)
                last = out if out is not None else \
                    (503, {}, _err_body(f"transient: {err}"))
                if attempts >= cfg.max_attempts:
                    break
                if not self.budget.try_spend():
                    self._counter("budget_exhausted",
                                  "fleet_retry_budget_exhausted_total",
                                  "retries refused by the fleet-wide "
                                  "retry budget")
                    break
                self._counter("retries", "fleet_router_retries_total",
                              "requests retried on another replica")
            status, rh, rb = last
            self._counter("failures", "fleet_router_failures_total",
                          "requests the router could not place")
            fsp.set(status=status, attempts=attempts)
            self._observe(t_start, model)
            out_headers = {"X-Fleet-Attempts": str(attempts)}
            for k in ("Retry-After", "Connection"):
                if k in rh:
                    out_headers[k] = rh[k]
            return status, out_headers, rb

    def _observe(self, t_start, model=None):
        ms = (time.perf_counter() - t_start) * 1000.0
        self._own_request_ms.observe(ms)
        from ..engine import SERVE_MS_BUCKETS

        monitor.registry().histogram(
            "fleet_request_ms", help="router-side request latency",
            buckets=SERVE_MS_BUCKETS).observe(ms)
        if model is not None:
            self._model_hist(model).observe(ms)
            monitor.registry().histogram(
                "fleet_request_ms", buckets=SERVE_MS_BUCKETS,
                model=str(model)).observe(ms)

    def _model_hist(self, model):
        """Per-model router-side latency histogram (lazily created; the
        autoscaler windows these for per-model scale signals)."""
        from ..engine import SERVE_MS_BUCKETS

        with self._model_lock:
            h = self._own_model_ms.get(model)
            if h is None:
                h = monitor.Histogram(
                    f"fleet_request_ms[{model}]",
                    buckets=SERVE_MS_BUCKETS)
                self._own_model_ms[model] = h
            return h

    # -- draining -------------------------------------------------------
    def drain(self, name, timeout_s=30.0, poll_interval_s=0.1):
        """Lame-duck one replica: stop dispatching to it NOW, tell it to
        drain, and wait until it reports stopped (or its listener goes
        away — the clean rolling-restart exit). Returns a report dict."""
        rep = self.membership.get(name)  # KeyError on unknown name
        t0 = time.perf_counter()
        self.membership.set_state(rep, LAME_DUCK)
        monitor.registry().counter(
            "fleet_drains_total", help="replica drains initiated").inc()
        try:
            self.transport(rep.endpoint, "/admin/drain", b"{}",
                           {"Content-Type": "application/json"}, 5.0)
        except OSError as e:
            self.membership.set_state(rep, DEAD, error=e)
            raise
        exited, state, stats = False, None, None
        deadline = t0 + float(timeout_s)
        while time.perf_counter() < deadline:
            try:
                state, stats = self._fetch(rep.endpoint)
            except OSError:
                exited = True  # listener gone: drained AND exited clean
                break
            if state == "stopped":
                break
            time.sleep(poll_interval_s)
        duration_ms = (time.perf_counter() - t0) * 1000.0
        monitor.registry().gauge(
            "fleet_drain_duration_ms",
            help="wall time of the last replica drain").set(duration_ms)
        self.membership.set_state(rep, DEAD, error="drained")
        return {"replica": name, "drained": exited or state == "stopped",
                "exited": exited, "duration_ms": duration_ms,
                "final_state": state, "final_stats": stats}

    # -- visibility -----------------------------------------------------
    def latency_percentiles(self, *ps):
        ps = ps or (50, 95, 99)
        return self._own_request_ms.percentiles(*ps)

    def latency_window(self, model=None):
        """(bucket_edges, cumulative_counts) of the router-side request
        latency histogram — aggregate, or one model's series when
        `model` is given (empty counts for a model never seen). The
        autoscaler diffs successive snapshots for a WINDOWED p99 — the
        cumulative percentiles answer "since boot", which is useless as
        a control signal once history piles up."""
        if model is None:
            hist = self._own_request_ms
        else:
            with self._model_lock:
                hist = self._own_model_ms.get(model)
            if hist is None:
                return self._own_request_ms.buckets, {}
        snap = hist.snapshot()
        return hist.buckets, snap["buckets"]

    def models_seen(self):
        """Model names that have crossed this router (for per-model
        autoscaler windows and dashboards)."""
        with self._model_lock:
            return sorted(self._own_model_ms)

    def stats(self):
        pct = self.latency_percentiles(50, 95, 99)
        return {
            "replicas": self.membership.describe(),
            "membership_epoch": self.membership.epoch,
            "healthy_replicas": self.membership.healthy_count(),
            "requests": self._own["requests"].value,
            "retries": self._own["retries"].value,
            "hedges": self._own["hedges"].value,
            "hedge_wins": self._own["hedge_wins"].value,
            "failures": self._own["failures"].value,
            "budget_exhausted": self._own["budget_exhausted"].value,
            "deadline_exceeded": self._own["deadline_exceeded"].value,
            "retry_budget_tokens": self.budget.tokens,
            "p50_ms": pct[50], "p95_ms": pct[95], "p99_ms": pct[99],
            "models": {
                m: {"p99_ms": self._model_hist(m).percentiles(99)[99]}
                for m in self.models_seen()},
        }


# -- HTTP frontend ------------------------------------------------------
def make_fleet_http(router, host="127.0.0.1", port=8100):
    """Router HTTP frontend, mirroring the replica surface:
    POST /v1/infer (routed), POST /admin/register {"name","endpoint"},
    POST /admin/drain {"replica"}, GET /healthz /stats /metrics."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _RouterHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def _reply(self, code, body, content_type="application/json",
                   headers=None):
            data = body if isinstance(body, bytes) \
                else body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
                if k.lower() == "connection" and v.lower() == "close":
                    # the header alone is advisory; actually drop keep-alive
                    self.close_connection = True
            self.end_headers()
            self.wfile.write(data)

        def _json(self, code, obj, headers=None):
            self._reply(code, json.dumps(obj), headers=headers)

        def do_GET(self):
            if self.path == "/healthz":
                if self.server.router.membership.candidates():
                    self._reply(200, "ok\n", content_type="text/plain")
                else:
                    self._reply(503, "no routable replicas\n",
                                content_type="text/plain")
            elif self.path == "/stats":
                self._json(200, self.server.router.stats())
            elif self.path == "/metrics":
                self._reply(200, monitor.registry().exposition(),
                            content_type="text/plain; version=0.0.4")
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            rt = self.server.router
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            if self.path == "/v1/infer":
                # best-effort "model" extraction off the wire body: a
                # malformed body still routes (the replica owns the 400)
                model = None
                try:
                    payload = json.loads(body or b"{}")
                    if isinstance(payload, dict):
                        m = payload.get("model")
                        if isinstance(m, str) and m:
                            model = m
                except ValueError:
                    pass
                status, hdrs, rbody = rt.route(body, headers={
                    "Content-Type": "application/json"}, model=model)
                # route() forwards the replica's Content-Type; lift it
                # out so _reply doesn't emit the header twice
                ctype = hdrs.pop("Content-Type", "application/json")
                self._reply(status, rbody, content_type=ctype,
                            headers=hdrs)
            elif self.path == "/admin/register":
                try:
                    payload = json.loads(body or b"{}")
                    rep = rt.heartbeat(str(payload["name"]),
                                       str(payload["endpoint"]))
                except (ValueError, KeyError, TypeError) as e:
                    self._json(400, {"error": f"bad registration: {e}"})
                    return
                self._json(200, {"registered": rep.name,
                                 "state": rep.state})
            elif self.path == "/admin/drain":
                # validate first: a malformed request is a 400, and 404
                # stays reserved for "well-formed but unknown replica"
                try:
                    payload = json.loads(body or b"{}")
                except ValueError as e:
                    self._json(400, {"error": f"bad drain request: {e}"})
                    return
                name = payload.get("replica") \
                    if isinstance(payload, dict) else None
                if not isinstance(name, str) or not name:
                    self._json(400, {"error":
                                     'body must be {"replica": "<name>"}'})
                    return
                try:
                    report = rt.drain(name)
                except KeyError:
                    self._json(404, {"error": f"unknown replica: {name!r}"})
                    return
                except (ValueError, TypeError, OSError) as e:
                    self._json(500, {"error": str(e)})
                    return
                self._json(200, report)
            else:
                self._json(404, {"error": f"no route {self.path}"})

    httpd = ThreadingHTTPServer((host, port), _RouterHandler)
    httpd.daemon_threads = True
    httpd.router = router
    return httpd


def serve_fleet(router, host="127.0.0.1", port=8100):
    """Blocking router frontend: serve until KeyboardInterrupt."""
    httpd = make_fleet_http(router, host, port)
    router.start()
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        httpd.server_close()
        router.stop()
