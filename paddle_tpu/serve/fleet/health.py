"""Health probing: the router's active view of every replica.

Each tick, every known replica gets a GET /healthz (and, while serving,
a GET /stats) over a fresh connection. The answers drive the membership
state machine:

    connection refused            -> dead immediately (nothing listens —
                                     a SIGKILLed replica shows up here
                                     within one probe interval)
    timeout / reset / other error -> breaker failure; dead after K
                                     consecutive (a wedge is ambiguous,
                                     a refused connect is not)
    503 "draining"                -> lame_duck (finishing its backlog)
    503 warming/stopped           -> dead (alive but not serving)
    200 + stats                   -> healthy, or degraded when the queue
                                     is deep, p99 exceeds the objective,
                                     or post-warmup compiles appeared

The prober also expires heartbeat TTLs and, when a `discover` source is
wired (the master's TTL registry via MasterClient.lookup), folds newly
registered replicas into membership — so a fleet can grow without
touching the router.

`tick()` is public and synchronous: tests drive the state machine
deterministically with an injected `fetch` instead of sleeping through
probe intervals.
"""

import http.client
import json
import threading

from ... import monitor
from .membership import DEAD, DEGRADED, HEALTHY, LAME_DUCK

__all__ = ["HealthProber", "http_fetch"]


def http_fetch(endpoint, timeout=2.0):
    """Probe one replica: -> (healthz_state, stats_or_None). healthz
    body text is the state ("ok", "draining", "warming", "stopped");
    raises OSError family on transport failure. Fresh connections on
    purpose: a probe must measure connectability, and a draining replica
    answers with Connection: close anyway."""
    host, port = endpoint.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        state = resp.read().decode("utf-8", "replace").strip() or "unknown"
        if resp.status == 200:
            state = "ok"
    finally:
        conn.close()
    stats = None
    if state == "ok":
        conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
        try:
            conn.request("GET", "/stats")
            resp = conn.getresponse()
            if resp.status == 200:
                stats = json.loads(resp.read().decode("utf-8"))
        finally:
            conn.close()
    return state, stats


class HealthProber:
    def __init__(self, membership, interval_s=0.5, fetch=None,
                 discover=None, degraded_queue_rows=None,
                 degraded_p99_ms=None):
        self.membership = membership
        self.interval_s = float(interval_s)
        self.fetch = fetch if fetch is not None else http_fetch
        self.discover = discover  # () -> {name: endpoint} or None
        self.degraded_queue_rows = degraded_queue_rows
        self.degraded_p99_ms = degraded_p99_ms
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-prober", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)
        self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the prober must not die
                pass
            self._stop.wait(self.interval_s)

    # -- one probing round ----------------------------------------------
    def tick(self):
        ms = self.membership
        if self.discover is not None:
            try:
                for name, endpoint in (self.discover() or {}).items():
                    ms.heartbeat(name, endpoint)
            except Exception:  # noqa: BLE001 — discovery is best-effort
                pass
        ms.expire()
        for rep in ms.replicas():
            self._probe(rep)
        monitor.registry().counter(
            "fleet_probe_rounds_total",
            help="health-probe sweeps over the fleet").inc()

    def _probe(self, rep):
        ms = self.membership
        try:
            state, stats = self.fetch(rep.endpoint)
        except ConnectionRefusedError as e:
            # unambiguous: nothing is listening. One probe interval is
            # all it takes for a SIGKILLed replica to leave the fleet.
            rep.breaker.record_failure()
            if rep.state != DEAD:
                ms.set_state(rep, DEAD, error=e)
            rep.last_probe = None
            return
        except Exception as e:  # noqa: BLE001 — timeout/reset/URL errors
            rep.breaker.record_failure()
            if rep.breaker.consecutive_failures \
                    >= rep.breaker.failure_threshold \
                    and rep.state != DEAD:
                ms.set_state(rep, DEAD, error=e)
            rep.last_probe = None
            return
        rep.last_probe = (state, stats)
        if state == "draining":
            if rep.state != LAME_DUCK:
                ms.set_state(rep, LAME_DUCK)
            return
        if state != "ok":
            # responsive but not serving (warming / stopped)
            if rep.state != DEAD:
                ms.set_state(rep, DEAD, error=f"healthz: {state}")
            return
        rep.breaker.record_success()
        if stats:
            rep.stats = stats
        want = HEALTHY
        if rep.state == LAME_DUCK:
            # a drain is router-initiated; a passing probe does not
            # un-drain a replica
            return
        if stats and self._degraded(rep, stats):
            want = DEGRADED
        if rep.state != want:
            ms.set_state(rep, want)

    def _degraded(self, rep, stats):
        try:
            if self.degraded_queue_rows is not None and \
                    float(stats.get("queue_rows") or 0) \
                    >= self.degraded_queue_rows:
                return True
            if self.degraded_p99_ms is not None:
                p99 = stats.get("p99_ms")
                if p99 is not None and float(p99) == float(p99) \
                        and float(p99) > self.degraded_p99_ms:
                    return True
            # multi-model replicas advertise per-model SLOs: a replica
            # blowing ONE hosted model's SLO by 2x is degraded even when
            # its aggregate p99 (diluted by the other models) looks fine
            for mst in (stats.get("models") or {}).values():
                mp99, slo = mst.get("p99_ms"), mst.get("slo_ms")
                if mp99 is not None and slo and \
                        float(mp99) == float(mp99) and \
                        float(mp99) > 2.0 * float(slo):
                    return True
            compiles = float(stats.get("steady_state_compiles") or 0)
        except (TypeError, ValueError):
            return False
        # "recompiling" means the count is RISING. steady_state_compiles
        # is cumulative (it never decreases), so treating any nonzero
        # value as degraded would pin a replica degraded forever after
        # its first post-warmup compile; compare against the previous
        # probe instead, and recover within one round of it going flat.
        prev = rep.compiles_seen
        rep.compiles_seen = compiles
        if prev is None:
            return compiles > 0
        return compiles > prev
