"""Fleet membership: who the replicas are and whether to send them work.

Liveness rides the elastic master's `MembershipTable`
(parallel/master.py): ONE TTL'd, epoch-fenced membership primitive
serves both control planes — elastic trainers and the serving fleet.
The fleet carries no TTL arithmetic of its own: a heartbeat refreshes a
table lease, a lapse IS a leave (the table bumps its epoch and the next
beat must re-JOIN under a strictly newer one, so a zombie can never
resurrect an epoch the fleet already moved past), and `expire()` merely
translates reaped leases into replica state.

The router owns this state; replicas only report. Each replica carries:

  state      healthy    probes pass, load nominal      -> routable
             degraded   probes pass, but the queue is
                        deep / p99 over objective /
                        post-warmup compiles observed  -> routable last
             dead       probes fail, TTL expired, or
                        refused connections            -> not routable
             lame_duck  draining by request            -> not routable
  breaker    a per-replica circuit breaker: K consecutive request
             failures open it (requests stop even if a probe hasn't run
             yet); after a cooldown it half-opens and admits exactly ONE
             probe request — success recloses, failure reopens.

Membership is the single writer of the fleet gauges
(`fleet_healthy_replicas`, per-replica `fleet_replica_state`), so a
scrape of the router answers "how much capacity is live" without
touching any replica.
"""

import threading
import time

from ... import monitor
from ...parallel.master import MembershipTable

__all__ = ["HEALTHY", "DEGRADED", "DEAD", "LAME_DUCK", "CircuitBreaker",
           "Replica", "Membership", "STATE_VALUES"]

HEALTHY = "healthy"
DEGRADED = "degraded"
DEAD = "dead"
LAME_DUCK = "lame_duck"

# gauge encoding for fleet_replica_state{replica=...}
STATE_VALUES = {DEAD: 0, DEGRADED: 1, HEALTHY: 2, LAME_DUCK: 3}

_ROUTABLE = (HEALTHY, DEGRADED)


class CircuitBreaker:
    """closed -> (K consecutive failures) -> open -> (cooldown) ->
    half_open -> one probe -> closed | open.

    try_acquire() is the dispatch-time gate: always True while closed,
    False while open (until the cooldown elapses, when it transitions to
    half_open and hands out exactly one probe slot), False while a
    half-open probe is already in flight. The clock is injectable so
    tests step time instead of sleeping."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold=3, cooldown_s=2.0, clock=None):
        self.failure_threshold = int(failure_threshold)
        if self.failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.cooldown_s = float(cooldown_s)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._open_until = 0.0
        self._probing = False

    @property
    def state(self):
        with self._lock:
            if self._state == self.OPEN \
                    and self._clock() >= self._open_until:
                return self.HALF_OPEN  # would half-open on next acquire
            return self._state

    @property
    def consecutive_failures(self):
        with self._lock:
            return self._failures

    def try_acquire(self):
        """May a request be dispatched through this breaker right now?"""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() < self._open_until:
                    return False
                self._state = self.HALF_OPEN
                self._probing = True
                return True  # THE probe slot
            # half-open: one probe at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self):
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0
            self._probing = False

    def record_failure(self):
        with self._lock:
            self._failures += 1
            if self._state == self.HALF_OPEN \
                    or self._failures >= self.failure_threshold:
                self._state = self.OPEN
                self._open_until = self._clock() + self.cooldown_s
                self._probing = False


class Replica:
    """One backend Server's view from the router."""

    def __init__(self, name, endpoint, via_heartbeat=False, breaker=None):
        self.name = name
        self.endpoint = endpoint
        self.via_heartbeat = via_heartbeat
        self.state = DEAD  # unproven until the first probe/heartbeat
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.stats = {}
        self.last_heartbeat = None
        self.last_probe = None
        self.last_error = None
        # cumulative steady_state_compiles at the last probe: the prober
        # degrades on a RISING count, recovers when it goes flat
        self.compiles_seen = None

    @property
    def queue_rows(self):
        try:
            return float(self.stats.get("queue_rows") or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def __repr__(self):
        return (f"Replica({self.name!r}, {self.endpoint!r}, "
                f"state={self.state!r})")


class Membership:
    def __init__(self, heartbeat_ttl_s=10.0, breaker_failures=3,
                 breaker_cooldown_s=2.0, clock=None):
        self.heartbeat_ttl_s = float(heartbeat_ttl_s)
        self.breaker_failures = int(breaker_failures)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._replicas = {}  # name -> Replica
        # the SAME TTL'd, epoch-fenced table the elastic master serves
        # trainers with; Replica objects keep the serving-side
        # annotations (breaker, probe stats, routability state) the
        # trainer plane has no use for — liveness lives in the table.
        # All table calls run under self._lock (the table itself is
        # unsynchronized by contract).
        self.table = MembershipTable(clock=self._clock)

    @property
    def epoch(self):
        """Monotonic membership epoch: bumps on every join, leave, and
        TTL lapse (the elastic trainer plane's generation fence)."""
        with self._lock:
            return self.table.epoch

    def _make_breaker(self):
        return CircuitBreaker(failure_threshold=self.breaker_failures,
                              cooldown_s=self.breaker_cooldown_s,
                              clock=self._clock)

    def add(self, name, endpoint, via_heartbeat=False, state=DEAD):
        """Register (or re-endpoint) a replica; static adds start DEAD
        and earn routability from the first successful probe. Static
        registrations hold a non-expiring table lease — only
        heartbeat-registered replicas ride the TTL."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                rep = Replica(name, endpoint, via_heartbeat=via_heartbeat,
                              breaker=self._make_breaker())
                rep.state = state
                self._replicas[name] = rep
            else:
                rep.endpoint = endpoint
            if name not in self.table:
                ttl = (self.heartbeat_ttl_s if via_heartbeat
                       else float("inf"))
                self.table.join(name, endpoint, ttl=ttl)
        self._update_gauges()
        return rep

    def heartbeat(self, name, endpoint):
        """A replica said hello: refresh its table lease (registering it
        on the first beat). A heartbeat proves the process is alive, not
        that it serves — routability still comes from the prober. A beat
        from a replica whose lease already lapsed cannot refresh the old
        lease: the table reaped it (epoch moved), so it re-JOINs under a
        strictly newer epoch."""
        rep = self.add(name, endpoint, via_heartbeat=True)
        with self._lock:
            rep.via_heartbeat = True
            rep.last_heartbeat = self._clock()
            m = self.table.get(name)
            if m is None or m["ttl"] == float("inf"):
                # lapsed, or promoted from a static registration: take a
                # fresh TTL'd lease (a new epoch — never resurrect)
                self.table.join(name, endpoint,
                                ttl=self.heartbeat_ttl_s)
            else:
                self.table.heartbeat(name, self.table.epoch)
        return rep

    def remove(self, name):
        with self._lock:
            self._replicas.pop(name, None)
            self.table.leave(name)
        self._update_gauges()

    def get(self, name):
        with self._lock:
            return self._replicas[name]

    def replicas(self):
        with self._lock:
            return list(self._replicas.values())

    def candidates(self, exclude=()):
        """Replicas routing may consider (the breaker gate is applied at
        dispatch, where the half-open single-probe slot is consumed)."""
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.state in _ROUTABLE and r.name not in exclude]

    def set_state(self, rep, state, error=None):
        if state not in STATE_VALUES:
            raise ValueError(f"unknown replica state {state!r}")
        with self._lock:
            rep.state = state
            rep.last_error = error
        self._update_gauges()

    def expire(self):
        """Replicas whose table lease lapsed go dead — the no-goodbye
        death path. The TTL bookkeeping itself lives in the shared
        MembershipTable: the reap bumps the membership epoch, and the
        zombie's next beat re-joins under a newer one."""
        changed = False
        with self._lock:
            for name in self.table.reap():
                rep = self._replicas.get(name)
                if rep is not None and rep.state != DEAD:
                    rep.state = DEAD
                    rep.last_error = "heartbeat TTL expired"
                    changed = True
        if changed:
            self._update_gauges()

    def healthy_count(self):
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.state == HEALTHY)

    def _update_gauges(self):
        reg = monitor.registry()
        with self._lock:
            reps = list(self._replicas.values())
        reg.gauge("fleet_healthy_replicas",
                  help="replicas in state=healthy").set(
            sum(1 for r in reps if r.state == HEALTHY))
        reg.gauge("fleet_routable_replicas",
                  help="replicas routing may pick "
                       "(healthy + degraded)").set(
            sum(1 for r in reps if r.state in _ROUTABLE))
        for r in reps:
            reg.gauge("fleet_replica_state",
                      help="0=dead 1=degraded 2=healthy 3=lame_duck",
                      replica=r.name).set(STATE_VALUES[r.state])

    def describe(self):
        """JSON-able membership snapshot for the router's /stats."""
        with self._lock:
            reps = list(self._replicas.values())
        return {r.name: {
            "endpoint": r.endpoint,
            "state": r.state,
            "breaker": r.breaker.state,
            "consecutive_failures": r.breaker.consecutive_failures,
            "queue_rows": r.queue_rows,
            "p99_ms": r.stats.get("p99_ms"),
            "via_heartbeat": r.via_heartbeat,
            "last_error": (str(r.last_error)
                           if r.last_error is not None else None),
        } for r in reps}
