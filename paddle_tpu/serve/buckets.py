"""Bucket ladder: the fixed set of batch shapes a server dispatches.

XLA compiles one executable per input shape; a serving path that padded
every batch to exactly its row count would compile max_batch distinct
executables on demand — each a multi-hundred-ms stall in the latency
tail. The ladder quantizes instead: requests coalesce to the SMALLEST
ladder rung that fits, so after warmup (which AOT-compiles every rung)
no dispatch ever leaves the compile cache. The default ladder is powers
of two up to max_batch — log2(max_batch)+1 executables buy zero
steady-state compiles at a worst-case 2x padding overhead.
"""

import numpy as np

__all__ = ["ladder", "bucket_for", "pad_rows"]


def ladder(max_batch, buckets=None):
    """The sorted tuple of batch buckets ending at max_batch.

    `buckets=None` gives the power-of-two ladder (1, 2, 4, ..., max_batch,
    with max_batch appended when it is not itself a power of two); an
    explicit iterable is validated, deduplicated and capped instead."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if buckets is None:
        rungs = []
        b = 1
        while b < max_batch:
            rungs.append(b)
            b *= 2
        rungs.append(max_batch)
        return tuple(rungs)
    rungs = sorted({int(b) for b in buckets})
    if not rungs or rungs[0] < 1:
        raise ValueError(f"buckets must be positive ints, got {buckets}")
    if rungs[-1] > max_batch:
        raise ValueError(
            f"bucket {rungs[-1]} exceeds max_batch {max_batch}")
    if rungs[-1] != max_batch:
        rungs.append(max_batch)
    return tuple(rungs)


def bucket_for(rows, rungs):
    """Smallest rung that fits `rows`, or None when rows exceed the top."""
    for b in rungs:
        if rows <= b:
            return b
    return None


def pad_rows(feed, rows, bucket):
    """Zero-pad every feed array's leading (batch) axis from rows to
    bucket. Returns the same dict when bucket == rows (no copy)."""
    if bucket == rows:
        return feed
    if bucket < rows:
        raise ValueError(f"bucket {bucket} < rows {rows}")
    out = {}
    for name, v in feed.items():
        v = np.asarray(v)
        if v.shape[0] != rows:
            raise ValueError(
                f"feed {name!r} leading axis {v.shape[0]} != rows {rows}")
        pad = np.zeros((bucket - rows,) + v.shape[1:], dtype=v.dtype)
        out[name] = np.concatenate([v, pad], axis=0)
    return out
