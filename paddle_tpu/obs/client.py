"""Obs push client: the in-process side of fleet collection.

A background thread samples, every FLAGS_obs_push_interval_s seconds:

  * the process-global metrics registry (monitor.registry().export()),
  * the NEW tail of the step journal (FLAGS_monitor_journal) and health
    ledger (FLAGS_health_ledger) since the previous push — byte-offset
    incremental reads that follow rotation (`<path>.1`) without losing
    the records written between the last read and the roll,
  * any flight-recorder dump directories that appeared since the last
    push (trace.last_dump()), shipped as {dir, manifest} so the
    collector can later merge the chrome traces on the manifests' clock
    anchors,

and POSTs them to the collector (`/v1/obs/push`) stamped with the
process identity labels {job, role, replica, pid, epoch} plus a fresh
{perf_counter, epoch} clock anchor and a monotone `seq` number (the
collector's zero-drop accounting).

Failure contract: observability must never take the workload down. Push
errors are counted (obs_push_failures_total) and retried on the next
tick; the thread is a daemon; stop() sends one final push (flushing the
remaining journal tail) with a short timeout and swallows its errors.

maybe_start(role) is the one-line wiring hook used by the Trainer /
resilience session, serve fleet replicas (`--obs`), the router and the
elastic master: a no-op returning None unless FLAGS_obs_push names a
collector endpoint.
"""

import json
import os
import threading
import time

from .. import flags
from .. import monitor

__all__ = ["ObsClient", "JsonlTail", "maybe_start"]

flags.define(
    "obs_push", str, "",
    "Fleet collector endpoint (host:port) this process pushes "
    "observability snapshots to (POST /v1/obs/push). Empty = fleet "
    "collection off; obs.maybe_start() is then a no-op.")
flags.define(
    "obs_push_interval_s", float, 1.0,
    "Seconds between obs push snapshots (metrics export + journal/"
    "health tails + new trace-dump manifests).")
flags.define(
    "obs_job", str, "paddle",
    "`job` identity label stamped on obs push payloads — one collector "
    "can aggregate several jobs side by side.")
flags.define(
    "obs_role", str, "",
    "`role` identity label on obs pushes (trainer / replica / router / "
    "master). Empty = whatever role the wiring hook passes.")
flags.define(
    "obs_replica", str, "",
    "`replica` identity label on obs pushes. Empty = <role>-<pid>, "
    "which is unique but unstable across restarts; fleet CLIs pass "
    "their replica name.")


def _flag_or_empty(name):
    """flags.get tolerating a flag whose defining module (e.g.
    health.ledger) has not been imported by this process."""
    try:
        return flags.get(name)
    except KeyError:
        return ""


class JsonlTail:
    """Incremental byte-offset reader over a rotating JSONL file
    (monitor journal / health ledger idiom: writer rolls the file to
    `<path>.1` via os.replace when it outgrows the cap).

    read_new() returns only records appended since the previous call.
    Rotation is detected as the file shrinking below our offset; the
    remainder of the rolled segment (`.1`) is drained from the old
    offset before restarting at byte 0 of the fresh file — no sample is
    lost across a roll. Torn trailing lines (a writer mid-append, or a
    roll mid-line) are left for the next read on the live file and
    skipped with a count on the sealed one."""

    def __init__(self, path_fn):
        self._path_fn = path_fn if callable(path_fn) else (lambda: path_fn)
        self._offset = 0
        self.torn = 0

    def _parse(self, data, complete_only):
        recs, consumed = [], 0
        end = len(data)
        if complete_only:
            end = data.rfind("\n") + 1
        for line in data[:end].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                self.torn += 1
                continue
            if isinstance(rec, dict):
                recs.append(rec)
        consumed = end
        return recs, consumed

    def read_new(self):
        path = self._path_fn()
        if not path:
            return []
        recs = []
        try:
            size = os.path.getsize(path)
        except OSError:
            return []
        if size < self._offset:
            # the writer rolled: finish the sealed segment first
            try:
                with open(path + ".1", "r") as f:
                    f.seek(self._offset)
                    rolled, _ = self._parse(f.read(),
                                            complete_only=False)
                    recs.extend(rolled)
            except OSError:
                pass
            self._offset = 0
        try:
            with open(path, "r") as f:
                f.seek(self._offset)
                data = f.read()
        except OSError:
            return recs
        fresh, consumed = self._parse(data, complete_only=True)
        recs.extend(fresh)
        self._offset += consumed
        return recs


class ObsClient:
    """Periodic push loop; one per process. start()/stop() or use as a
    context manager."""

    def __init__(self, endpoint=None, job=None, role=None, replica=None,
                 interval_s=None, timeout_s=2.0):
        self.endpoint = endpoint or flags.get("obs_push")
        if not self.endpoint:
            raise ValueError("ObsClient needs a collector endpoint "
                             "(FLAGS_obs_push or endpoint=)")
        role = role or flags.get("obs_role") or "proc"
        self.labels = {
            "job": job or flags.get("obs_job"),
            "role": role,
            "replica": (replica or flags.get("obs_replica")
                        or f"{role}-{os.getpid()}"),
            "pid": os.getpid(),
        }
        self.interval_s = float(interval_s if interval_s is not None
                                else flags.get("obs_push_interval_s"))
        self.timeout_s = float(timeout_s)
        self._journal = JsonlTail(lambda: _flag_or_empty("monitor_journal"))
        self._health = JsonlTail(lambda: _flag_or_empty("health_ledger"))
        self._seq = 0               # last ACKED sequence number
        self.failures = 0
        # tails consumed by a FAILED push are re-buffered here and ride
        # the next attempt — a transient collector outage must not lose
        # samples (capped so a long outage degrades, not OOMs)
        self._pend_journal = []
        self._pend_health = []
        self._pend_dumps = []
        self._pend_cap = 4096
        self._sent_dumps = set()
        self._stop = threading.Event()
        self._thread = None

    # -- payload --------------------------------------------------------
    def _new_trace_dumps(self):
        from .. import trace

        out = []
        last = trace.last_dump()
        if last and last not in self._sent_dumps:
            try:
                with open(os.path.join(last, "manifest.json")) as f:
                    manifest = json.load(f)
            except (OSError, ValueError):
                return out
            self._sent_dumps.add(last)
            out.append({"dir": os.path.abspath(last),
                        "manifest": manifest})
        return out

    def build_payload(self):
        """Snapshot everything new since the last ACKED push. The seq is
        last_acked + 1: a failed attempt retries under the SAME number
        (the collector treats seq <= last-seen as a retransmit), so only
        genuinely missing snapshots count as dropped."""
        journal = self._pend_journal + self._journal.read_new()
        health = self._pend_health + self._health.read_new()
        dumps = self._pend_dumps + self._new_trace_dumps()
        self._pend_journal, self._pend_health, self._pend_dumps = \
            [], [], []
        labels = dict(self.labels)
        labels["epoch"] = time.time()
        return {
            "v": 1,
            "seq": self._seq + 1,
            "labels": labels,
            "clock": {"perf_counter": time.perf_counter(),
                      "epoch": time.time()},
            "metrics": monitor.registry().export(),
            "journal": journal,
            "health": health,
            "trace_dumps": dumps,
        }

    def push_once(self):
        """One snapshot -> collector. Returns True on a 200 ack; never
        raises (observability must not break the workload)."""
        import http.client

        payload = self.build_payload()
        try:
            host, port = self.endpoint.rsplit(":", 1)
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=self.timeout_s)
            try:
                conn.request(
                    "POST", "/v1/obs/push", json.dumps(payload),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
            finally:
                conn.close()
        except (OSError, ValueError):
            ok = False
        if ok:
            self._seq = payload["seq"]
        else:
            self.failures += 1
            self._pend_journal = (payload["journal"]
                                  + self._pend_journal)[-self._pend_cap:]
            self._pend_health = (payload["health"]
                                 + self._pend_health)[-self._pend_cap:]
            self._pend_dumps = payload["trace_dumps"] + self._pend_dumps
            if monitor.enabled():
                monitor.registry().counter(
                    "obs_push_failures_total",
                    help="obs snapshots that failed to reach the "
                         "collector (retried next tick)").inc()
        return ok

    # -- lifecycle ------------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.push_once()

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="obs-push", daemon=True)
        self._thread.start()
        return self

    def stop(self, final_push=True):
        """Stop the loop; by default flush one final snapshot so the
        collector sees the terminal journal tail and last trace dump."""
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=self.timeout_s + self.interval_s)
        if final_push:
            self.push_once()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def maybe_start(role, replica=None, endpoint=None):
    """Start a push client when FLAGS_obs_push (or endpoint=) names a
    collector; returns the started ObsClient or None. Never raises —
    the workload must come up even with a bad obs config."""
    endpoint = endpoint or flags.get("obs_push")
    if not endpoint:
        return None
    try:
        return ObsClient(endpoint=endpoint, role=role,
                         replica=replica).start()
    except (ValueError, OSError):
        return None
