"""Clock-aligned merge of per-process observability artifacts.

Every process in a fleet keeps its own step journal (monitor/journal.py),
health ledger and flight-recorder rings; this module merges them into ONE
global view:

  merge_step_timeline   per-process step journals -> a global step
                        timeline with per-step cross-replica skew and
                        straggler attribution (slowest replica per step,
                        consecutive-straggler detection — the signal the
                        collector publishes as fleet_straggler{replica=})
  merge_chrome_traces   flight-recorder dumps -> one chrome trace with a
                        DISTINCT pid lane per process (the manifest's
                        real pid), all lanes re-anchored onto one global
                        epoch timeline via each manifest's
                        perf_counter<->epoch clock anchor
  overlap_efficiency    the PR-13 static schedule costs (analytic
                        compute/comm split) joined with a MEASURED step
                        time -> fraction of collective time hidden under
                        compute (the headline overlap metric of
                        PAPERS.md 2004.13336)

Clock model: journal `ts` stamps are each process's own time.time();
hosts skew. A push payload samples {perf_counter, epoch} at send time and
the collector samples its own epoch at receive time, so
clock_offset(clock, ref_epoch) maps a process's epoch stamps onto the
collector's clock (network delay is the residual error — milliseconds,
versus the seconds NTP-less hosts drift). Span t0/t1 are perf_counter
seconds; epoch_of() converts them through the same anchor.
"""

from ..trace.export import chrome_events

__all__ = ["epoch_of", "clock_offset", "hist_quantile",
           "merge_step_timeline", "merge_chrome_traces",
           "overlap_efficiency", "format_timeline"]


def epoch_of(t, clock):
    """perf_counter seconds -> epoch seconds through a {perf_counter,
    epoch} anchor sampled together (trace manifest / push payload)."""
    return float(t) - float(clock["perf_counter"]) + float(clock["epoch"])


def clock_offset(clock, ref_epoch):
    """Seconds to ADD to a process's epoch stamps to land them on the
    reference clock: the reference's epoch sample (collector receive
    time) minus the process's own epoch sample taken at the same instant
    (push time). None/missing anchor -> 0.0 (trust the local clock)."""
    if not clock or clock.get("epoch") is None:
        return 0.0
    return float(ref_epoch) - float(clock["epoch"])


def hist_quantile(hist, p):
    """Quantile estimate from a Histogram.snapshot() dict (cumulative
    `buckets` keyed by upper edge — float or "+Inf" — plus count/min/max).
    Works on JSON round-tripped snapshots (string keys). None when empty.
    Same linear-interpolation semantics as registry.Histogram.percentiles,
    kept separate because the collector only ever holds snapshots."""
    if not hist:
        return None
    count = int(hist.get("count") or 0)
    if count <= 0:
        return None
    edges = []
    for k, v in (hist.get("buckets") or {}).items():
        le = float("inf") if str(k) in ("+Inf", "inf") else float(k)
        edges.append((le, int(v)))
    if not edges:
        return None
    edges.sort()
    mn, mx = hist.get("min"), hist.get("max")
    rank = float(p) / 100.0 * count
    prev_le, prev_c = None, 0
    for le, c in edges:
        if c > prev_c and c >= rank:
            lo = prev_le if prev_le is not None else \
                (mn if mn is not None else 0.0)
            hi = le
            if le == float("inf"):
                hi = mx if mx is not None else (prev_le or 0.0)
            frac = (rank - prev_c) / (c - prev_c)
            v = lo + frac * (hi - lo)
            if mn is not None:
                v = max(v, float(mn))
            if mx is not None:
                v = min(v, float(mx))
            return v
        prev_le, prev_c = le, c
    return float(mx) if mx is not None else None


def merge_step_timeline(processes, straggler_ratio=1.2,
                        straggler_steps=3):
    """Merge per-process step journals into one global timeline.

    processes: [{"name": str, "journal": [step records], and optionally
    "offset_s": float (clock_offset output) or "clock" + "ref_epoch"}].
    Journals align on the per-process step INDEX (each process counts its
    own steps; in data-parallel fleets step N is the same global batch).

    Returns {
      "events":    every step record as {"t" (corrected epoch), "name",
                   "step", "total_ms"} sorted by corrected time — the
                   monotonic global timeline,
      "steps":     [{"step", "replicas": {name: total_ms}, "skew_ms"
                   (max-min), "max_over_median", "slowest"}] for steps
                   covered by >= 2 processes,
      "stragglers": {name: longest consecutive-slowest run length} for
                   processes that were the slowest replica on >=
                   `straggler_steps` CONSECUTIVE multi-replica steps
                   while exceeding `straggler_ratio` x the step median,
      "per_process": {name: {"steps", "first_step", "last_step",
                   "mean_ms"}},
    }
    """
    events = []
    by_step = {}
    per_process = {}
    for proc in processes:
        name = proc["name"]
        offset = proc.get("offset_s")
        if offset is None:
            offset = clock_offset(proc.get("clock"),
                                  proc.get("ref_epoch", 0.0)) \
                if proc.get("clock") and proc.get("ref_epoch") is not None \
                else 0.0
        totals = []
        steps_seen = []
        for rec in proc.get("journal") or []:
            step = rec.get("step")
            total = rec.get("total_ms")
            if step is None or total is None:
                continue
            step, total = int(step), float(total)
            ts = rec.get("ts")
            t = (float(ts) + offset) if ts is not None else None
            events.append({"t": t, "name": name, "step": step,
                           "total_ms": total})
            # a step replayed after a rollback/restore overwrites its
            # earlier attempt: the LAST record for (process, step) wins
            by_step.setdefault(step, {})[name] = total
            totals.append(total)
            steps_seen.append(step)
        if steps_seen:
            per_process[name] = {
                "steps": len(steps_seen),
                "first_step": min(steps_seen),
                "last_step": max(steps_seen),
                "mean_ms": sum(totals) / len(totals),
            }
    events.sort(key=lambda e: (e["t"] if e["t"] is not None else 0.0,
                               e["step"], e["name"]))
    steps = []
    runs = {}        # name -> current consecutive-slowest run
    longest = {}     # name -> longest qualifying run
    for step in sorted(by_step):
        reps = by_step[step]
        if len(reps) < 2:
            continue
        vals = sorted(reps.values())
        n = len(vals)
        # same median semantics as monitor/skew.replica_skew: average
        # the middle pair for even n (a 2-replica fleet must not use
        # the slow replica itself as the baseline)
        median = vals[n // 2] if n % 2 == 1 \
            else 0.5 * (vals[n // 2 - 1] + vals[n // 2])
        slowest = max(reps, key=lambda n: reps[n])
        ratio = (reps[slowest] / median) if median > 0 else None
        steps.append({
            "step": step,
            "replicas": dict(reps),
            "skew_ms": vals[-1] - vals[0],
            "max_over_median": ratio,
            "slowest": slowest,
        })
        qualifying = ratio is not None and ratio >= straggler_ratio
        for name in runs:
            if name != slowest or not qualifying:
                runs[name] = 0
        if qualifying:
            runs[slowest] = runs.get(slowest, 0) + 1
            if runs[slowest] >= straggler_steps:
                longest[slowest] = max(longest.get(slowest, 0),
                                       runs[slowest])
        else:
            runs[slowest] = 0
    return {"events": events, "steps": steps, "stragglers": longest,
            "per_process": per_process}


def merge_chrome_traces(dumps, names=None):
    """Flight-recorder dumps -> ONE chrome trace dict with a distinct pid
    lane per process.

    dumps: [{"manifest": dict, "spans": [span dicts]}] (trace.load_dump
    output). Each lane's pid is the dumping process's REAL pid from its
    manifest (stable per process — the per-dump exporter reuses pid 1 for
    every process, so naive concatenation collides every fleet member
    into one lane). Lanes are re-anchored onto one global epoch timeline
    through each manifest's {perf_counter, epoch} clock anchor; a dump
    without an anchor falls back to its own earliest span as origin
    (lane renders, alignment degrades to per-process relative time).

    names: optional [str] per dump for the lane's process_name metadata
    (defaults to "<role?> pid <pid>").
    """
    per = []
    origin_epoch = None
    for i, d in enumerate(dumps):
        man = d.get("manifest") or {}
        spans = d.get("spans") or []
        clock = man.get("clock") or {}
        pid = man.get("pid")
        if pid is None:
            pid = 1000 + i  # manifest predates the pid field: synthetic
        anchored = clock.get("perf_counter") is not None \
            and clock.get("epoch") is not None
        t_min = min((s["t0"] for s in spans), default=None)
        e_min = epoch_of(t_min, clock) \
            if anchored and t_min is not None else None
        if e_min is not None:
            origin_epoch = e_min if origin_epoch is None \
                else min(origin_epoch, e_min)
        per.append((int(pid), spans, clock if anchored else None, t_min))
    events = []
    seen_pids = set()
    for i, (pid, spans, clock, t_min) in enumerate(per):
        while pid in seen_pids:   # same-pid collision (recycled pids)
            pid += 100000
        seen_pids.add(pid)
        if not spans:
            continue
        if clock is not None and origin_epoch is not None:
            # the perf_counter value IN THIS PROCESS at the global origin
            t0 = origin_epoch - float(clock["epoch"]) \
                + float(clock["perf_counter"])
        else:
            t0 = t_min
        name = (names[i] if names and i < len(names) and names[i]
                else f"pid {pid}")
        events.extend(chrome_events(spans, t0=t0, pid=pid,
                                    process_name=name))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def overlap_efficiency(compute_ms, comm_ms, measured_step_ms):
    """Fraction of collective time hidden under compute, in [0, 1].

    Joins the static schedule's analytic split (analysis/schedule.py:
    serial = compute + comm) with a MEASURED step wall time: whatever the
    measured step took beyond the analytic compute is exposed (serialized)
    comm, so hidden = comm - exposed. 1.0 = the step ran at the compute
    cost (perfect overlap), 0.0 = fully serialized (measured >= compute +
    comm). None when the analytic comm share is zero/absent — there is
    nothing to hide."""
    if not comm_ms or comm_ms <= 0 or measured_step_ms is None \
            or compute_ms is None:
        return None
    exposed = max(0.0, float(measured_step_ms) - float(compute_ms))
    return max(0.0, min(1.0, (float(comm_ms) - exposed) / float(comm_ms)))


def format_timeline(merged, top=8):
    """Human rendering of merge_step_timeline output."""
    lines = []
    pp = merged["per_process"]
    lines.append(f"processes: {len(pp)}  multi-replica steps: "
                 f"{len(merged['steps'])}")
    for name in sorted(pp):
        st = pp[name]
        lines.append(
            f"  {name:<20} steps {st['first_step']}..{st['last_step']} "
            f"({st['steps']})  mean {st['mean_ms']:.3f} ms")
    if merged["steps"]:
        worst = sorted(merged["steps"], key=lambda s: -s["skew_ms"])[:top]
        lines.append(f"  {'step':>6} {'skew_ms':>10} {'max/med':>8} "
                     f"slowest")
        for s in worst:
            ratio = s["max_over_median"]
            lines.append(
                f"  {s['step']:>6} {s['skew_ms']:>10.3f} "
                f"{ratio if ratio is None else round(ratio, 3)!s:>8} "
                f"{s['slowest']}")
    if merged["stragglers"]:
        lines.append("  stragglers: " + ", ".join(
            f"{n} (x{k} consecutive)"
            for n, k in sorted(merged["stragglers"].items())))
    return "\n".join(lines)
