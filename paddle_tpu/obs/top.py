"""`paddle_tpu obs top` — live fleet table, curses-free.

Renders the collector's /v1/obs/summary as a redraw-in-place terminal
table (ANSI cursor-home + clear-to-end between frames; plain sequential
frames when stdout is not a TTY, so piping to a file stays readable).
One row per live process:

    REPLICA ROLE V STEPS STEP/S P50 P99 QUEUE HBM CACHE HEALTH ST AGE

with ST flagging the replicas the collector currently attributes as
stragglers (fleet_straggler gauge), plus a fleet header line (process /
expired counts, pushes, dropped snapshots, max step skew).
"""

import json
import sys
import time

__all__ = ["fetch_summary", "render_summary", "run_top"]

_CLEAR = "\x1b[2J"        # clear screen (first frame)
_HOME = "\x1b[H"          # cursor home
_WIPE = "\x1b[J"          # clear from cursor to end


def fetch_summary(endpoint, timeout_s=3.0):
    """GET /v1/obs/summary from host:port -> dict (raises OSError)."""
    import http.client

    host, port = endpoint.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout_s)
    try:
        conn.request("GET", "/v1/obs/summary")
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise OSError(f"{endpoint}: HTTP {resp.status}")
        return json.loads(body.decode("utf-8", "replace"))
    finally:
        conn.close()


def _fmt(v, spec="{:.1f}", dash="-"):
    if v is None:
        return dash
    try:
        return spec.format(v)
    except (ValueError, TypeError):
        return dash


def _fmt_bytes(n):
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0


def render_summary(summary):
    """Summary dict -> multi-line table string (no ANSI; the caller owns
    screen control)."""
    fleet = summary.get("fleet", {})
    lines = []
    skew = fleet.get("max_skew_ms")
    stragglers = fleet.get("stragglers") or {}
    lines.append(
        f"fleet: {fleet.get('processes', 0)} up"
        f" / {fleet.get('expired', 0)} expired"
        f"   pushes {int(fleet.get('pushes') or 0)}"
        f"   scrapes {int(fleet.get('scrapes') or 0)}"
        f"   dropped {int(fleet.get('dropped_snapshots') or 0)}"
        f"   steps(multi) {fleet.get('multi_replica_steps', 0)}"
        f"   max skew {_fmt(skew)} ms")
    if stragglers:
        worst = ", ".join(f"{k} x{v}" for k, v in
                          sorted(stragglers.items()))
        lines.append(f"stragglers: {worst}")
    hdr = (f"{'REPLICA':<18}{'ROLE':<9}{'V':<2}{'STEPS':>7}"
           f"{'STEP/S':>8}{'P50MS':>8}{'P99MS':>8}{'QUEUE':>6}"
           f"{'HBM':>9}{'CACHE%':>7}{'HLTH':>5}{'ST':>3}{'AGE':>6}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for p in summary.get("processes", []):
        lab = p.get("labels", {})
        hit = p.get("cache_hit_ratio")
        lines.append(
            f"{str(lab.get('replica', '?')):<18.17}"
            f"{str(lab.get('role', '?')):<9.8}"
            f"{'p' if p.get('via') == 'push' else 's':<2}"
            f"{_fmt(p.get('steps_total'), '{:.0f}'):>7}"
            f"{_fmt(p.get('step_rate'), '{:.2f}'):>8}"
            f"{_fmt(p.get('p50_ms')):>8}"
            f"{_fmt(p.get('p99_ms')):>8}"
            f"{_fmt(p.get('queue_rows'), '{:.0f}'):>6}"
            f"{_fmt_bytes(p.get('hbm_bytes')):>9}"
            f"{_fmt(hit * 100.0 if hit is not None else None):>7}"
            f"{_fmt(p.get('health_events'), '{:.0f}'):>5}"
            f"{'*' if p.get('straggler') else '':>3}"
            f"{_fmt(p.get('age_s')):>6}")
    for e in summary.get("expired", []):
        lab = e.get("labels", {})
        lines.append(f"{str(lab.get('replica', '?')):<18.17}"
                     f"{str(lab.get('role', '?')):<9.8}"
                     f"expired {_fmt(e.get('age_s'), '{:.0f}')}s ago")
    return "\n".join(lines)


def run_top(endpoint, interval_s=2.0, once=False, json_out=False,
            iterations=None, out=None):
    """The `obs top` loop. `once` prints a single frame; `iterations`
    bounds the loop (tests); returns 0, or 2 when the collector is
    unreachable on the first fetch."""
    out = out or sys.stdout
    inplace = (not once) and (not json_out) \
        and getattr(out, "isatty", lambda: False)()
    n = 0
    first = True
    while True:
        try:
            summary = fetch_summary(endpoint)
        except (OSError, ValueError) as e:
            if first:
                print(f"obs top: collector {endpoint} unreachable: {e}",
                      file=sys.stderr)
                return 2
            summary = None
        if summary is not None:
            if json_out:
                out.write(json.dumps(summary) + "\n")
            else:
                frame = (f"paddle_tpu obs top — {endpoint} — "
                         f"{time.strftime('%H:%M:%S')}\n"
                         + render_summary(summary) + "\n")
                if inplace:
                    out.write((_CLEAR if first else "") + _HOME + frame
                              + _WIPE)
                else:
                    out.write(frame)
            out.flush()
        first = False
        n += 1
        if once or (iterations is not None and n >= iterations):
            return 0
        try:
            time.sleep(interval_s)
        except KeyboardInterrupt:
            return 0
