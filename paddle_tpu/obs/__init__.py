"""Fleet-wide observability control plane.

Single-process telemetry already exists in three layers — the metrics
registry (monitor/), the step journal + health ledger (monitor/journal,
health/ledger) and the flight recorder (trace/). This package is the
cross-PROCESS layer that joins them for a whole job:

    collector.py   HTTP sink: processes push registry snapshots,
                   journal/health tails and trace-dump manifests
                   (POST /v1/obs/push), or are scraped off their
                   existing /metrics pages; re-served aggregated as
                   GET /metrics (counter-sum / gauge-max /
                   histogram-merge, per-replica labels, HELP/TYPE,
                   TTL stale expiry) + /v1/obs/summary JSON
    timeline.py    clock-aligned merge of per-process journals and
                   chrome traces onto one epoch timeline: per-step
                   cross-replica skew, consecutive-straggler
                   attribution, overlap efficiency, merged trace with
                   one pid lane per process
    client.py      the in-process push loop (maybe_start(role) hook
                   wired into Trainer/resilience sessions, fleet
                   replicas, the router and the elastic master; armed
                   by FLAGS_obs_push)
    top.py         `paddle_tpu obs top` — live redraw-in-place fleet
                   table over /v1/obs/summary

CLI: `paddle_tpu obs collect|top|timeline` (cli.py)."""

from .client import JsonlTail, ObsClient, maybe_start
from .collector import (Collector, make_obs_http, merge_hists,
                        parse_exposition, serve_obs)
from .timeline import (clock_offset, epoch_of, format_timeline,
                       hist_quantile, merge_chrome_traces,
                       merge_step_timeline, overlap_efficiency)
from .top import fetch_summary, render_summary, run_top

__all__ = [
    # collector
    "Collector", "make_obs_http", "serve_obs", "parse_exposition",
    "merge_hists",
    # timeline
    "epoch_of", "clock_offset", "hist_quantile", "merge_step_timeline",
    "merge_chrome_traces", "overlap_efficiency", "format_timeline",
    # client
    "ObsClient", "JsonlTail", "maybe_start",
    # top
    "fetch_summary", "render_summary", "run_top",
]
