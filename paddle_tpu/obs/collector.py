"""Fleet observability collector: the cross-process metrics/trace/health
sink.

Every process in a job — Trainer loops, serve fleet replicas, the fleet
router, the elastic master — either PUSHES periodic snapshots here
(client.py, `POST /v1/obs/push`) or is SCRAPED (polling an existing
`GET /metrics` exposition). The collector keys each process on its
(job, role, replica, pid) labels and keeps, per process:

    metrics      the latest registry export (name/kind/help/labels/value)
    journal      a capped tail of step-journal records
    health       a capped tail of health-ledger records
    trace_dumps  flight-recorder dump manifests (dir + manifest), each
                 carrying the process's perf_counter<->epoch clock anchor
    clock        the push-time {perf_counter, epoch} sample; joined with
                 the collector's receive time it yields the per-process
                 clock offset every timeline merge uses

Aggregation semantics (GET /metrics):
  * every pushed series re-emitted with {job, role, replica} identity
    labels merged in (the per-replica view a dashboard slices on), and
  * one aggregate series per (name, original labels) WITHOUT identity
    labels: counters SUM across processes, gauges take the MAX, and
    histograms merge bucket-wise (cumulative counts add) — so fleet p99
    comes from one merged histogram, not an average of averages.
  * `# HELP`/`# TYPE` comment lines per family, carried through from the
    source registries' descriptions.

Stale-process expiry uses the membership TTL idiom (serve/fleet,
parallel/master): a process silent past FLAGS_obs_ttl_s leaves the
aggregate (and is counted in obs_expired_total) but stays visible as
expired in the summary; a new push under the same key revives it.

Fleet-derived gauges the collector itself maintains:
  fleet_straggler{replica=}      1 while the replica is the slowest on
                                 consecutive multi-replica steps (see
                                 timeline.merge_step_timeline)
  fleet_step_skew_ms             max-min step time at the latest
                                 multi-replica step
  fleet_overlap_efficiency{replica=}
                                 comm hidden under compute: the PR-13
                                 schedule's analytic compute/comm gauges
                                 joined with the measured step median
  obs_pushes_total / obs_scrapes_total / obs_dropped_snapshots_total /
  obs_expired_total / obs_processes

Zero-drop accounting: push payloads carry a per-process `seq`; a gap
between consecutive sequence numbers counts the missing snapshots into
obs_dropped_snapshots_total — the green_gate drill asserts it stays 0.
"""

import json
import re
import threading
import time

from .. import flags
from ..monitor.registry import MetricsRegistry, _escape_label_value, \
    _NAME_RE
from . import timeline as tl

__all__ = ["Collector", "ProcessEntry", "parse_exposition",
           "merge_hists", "make_obs_http", "serve_obs"]

flags.define(
    "obs_ttl_s", float, 15.0,
    "Fleet collector stale-process expiry: a pushed/scraped process "
    "silent past this many seconds leaves the aggregated exposition "
    "(same TTL idiom as fleet membership). It stays listed as expired "
    "in the summary and revives on its next push.")

_IDENTITY_KEYS = ("job", "role", "replica")


class ProcessEntry:
    """One process's latest snapshot + capped artifact tails."""

    __slots__ = ("key", "labels", "via", "clock", "offset_s", "metrics",
                 "journal", "health", "trace_dumps", "last_seen",
                 "last_ts", "seq", "dropped", "pushes",
                 "_prev_steps", "_prev_seen", "step_rate")

    def __init__(self, key, labels, via="push"):
        self.key = key
        self.labels = dict(labels)
        self.via = via
        self.clock = None
        self.offset_s = 0.0
        self.metrics = []
        self.journal = []
        self.health = []
        self.trace_dumps = []       # [{"dir", "manifest"}], dedup by dir
        self.last_seen = time.monotonic()
        self.last_ts = time.time()
        self.seq = None
        self.dropped = 0
        self.pushes = 0
        self._prev_steps = None
        self._prev_seen = None
        self.step_rate = None

    # -- metric lookups over the latest export --------------------------
    def metric_values(self, name, kinds=("counter", "gauge")):
        return [m.get("value", 0.0) for m in self.metrics
                if m["name"] == name and m.get("kind") in kinds]

    def metric_sum(self, name, kinds=("counter", "gauge")):
        vals = self.metric_values(name, kinds)
        return sum(vals) if vals else None

    def metric_max(self, name, kinds=("gauge", "counter")):
        vals = self.metric_values(name, kinds)
        return max(vals) if vals else None

    def merged_hist(self, name):
        hists = [m["hist"] for m in self.metrics
                 if m["name"] == name and m.get("kind") == "histogram"]
        return merge_hists(hists) if hists else None

    def _note_steps(self):
        """Update the steps/sec estimate from successive snapshots."""
        steps = self.metric_sum("steps_total", kinds=("counter",))
        now = time.monotonic()
        if steps is not None and self._prev_steps is not None \
                and now > self._prev_seen:
            dt = now - self._prev_seen
            self.step_rate = max(0.0, steps - self._prev_steps) / dt
        if steps is not None:
            self._prev_steps, self._prev_seen = steps, now


def merge_hists(hists):
    """Bucket-wise merge of Histogram.snapshot() dicts (cumulative counts
    add; min/max combine; sum/count add). Bucket edges are matched on
    their string form — registries share code, so fleet members emit the
    same edges; an edge missing from one process is dropped from the
    merge (cumulative counts cannot be interpolated safely)."""
    out = {"count": 0, "sum": 0.0, "min": None, "max": None, "buckets": {}}
    keysets = []
    for h in hists:
        if not h:
            continue
        out["count"] += int(h.get("count") or 0)
        out["sum"] += float(h.get("sum") or 0.0)
        for edge in ("min", "max"):
            v = h.get(edge)
            if v is None:
                continue
            cur = out[edge]
            out[edge] = v if cur is None else \
                (min(cur, v) if edge == "min" else max(cur, v))
        buckets = {str(k): int(v) for k, v in (h.get("buckets") or {})
                   .items()}
        keysets.append(set(buckets))
        for k, v in buckets.items():
            out["buckets"][k] = out["buckets"].get(k, 0) + v
    if keysets:
        common = set.intersection(*keysets)
        out["buckets"] = {k: v for k, v in out["buckets"].items()
                          if k in common}
    return out


# -- Prometheus text parsing (scrape mode) ------------------------------
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$')


def _unescape(v):
    return v.replace("\\n", "\n").replace('\\"', '"') \
        .replace("\\\\", "\\")


def parse_exposition(text):
    """Prometheus text exposition -> registry-export-style dicts
    ([{"name","kind","help","labels","value"|"hist"}]) — the scrape-mode
    inverse of MetricsRegistry.export(). Histogram families are
    reassembled from their _bucket/_sum/_count series (min/max are not
    recoverable from a scrape; hist_quantile tolerates their absence).
    Unparseable lines are skipped — a scrape must degrade, not raise."""
    kinds, helps = {}, {}
    samples = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) == 4:
                kinds[parts[2]] = parts[3]
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) >= 3:
                helps[parts[2]] = parts[3] if len(parts) == 4 else ""
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labelstr, value = m.groups()
        try:
            value = float(value)
        except ValueError:
            continue
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(labelstr or "")}
        samples.append((name, labels, value))

    out = []
    hist_parts = {}   # (base, labelkey) -> {"buckets", "sum", "count"}
    for name, labels, value in samples:
        base, part = name, None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) \
                    and kinds.get(name[: -len(suffix)]) == "histogram":
                base, part = name[: -len(suffix)], suffix[1:]
                break
        if part is not None:
            lab = dict(labels)
            le = lab.pop("le", None)
            key = (base, tuple(sorted(lab.items())))
            h = hist_parts.setdefault(
                key, {"buckets": {}, "sum": 0.0, "count": 0,
                      "labels": lab})
            if part == "bucket" and le is not None:
                h["buckets"][le] = int(value)
            elif part == "sum":
                h["sum"] = value
            elif part == "count":
                h["count"] = int(value)
            continue
        out.append({"name": name, "kind": kinds.get(name, "gauge"),
                    "help": helps.get(name, ""), "labels": labels,
                    "value": value})
    for (base, _), h in hist_parts.items():
        out.append({"name": base, "kind": "histogram",
                    "help": helps.get(base, ""), "labels": h["labels"],
                    "hist": {"count": h["count"], "sum": h["sum"],
                             "min": None, "max": None,
                             "buckets": h["buckets"]}})
    return out


def _fetch_metrics(endpoint, timeout_s=2.0):
    """GET http://endpoint/metrics -> exposition text (raises OSError)."""
    import http.client

    host, port = endpoint.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout_s)
    try:
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise OSError(f"scrape {endpoint}: HTTP {resp.status}")
        return body.decode("utf-8", "replace")
    finally:
        conn.close()


class Collector:
    """The in-process aggregation core; make_obs_http wraps it in the
    HTTP surface, tests drive it directly."""

    def __init__(self, ttl_s=None, straggler_ratio=1.2, straggler_steps=3,
                 journal_cap=4096, fetch=None):
        self.ttl_s = float(ttl_s if ttl_s is not None
                           else flags.get("obs_ttl_s"))
        self.straggler_ratio = float(straggler_ratio)
        self.straggler_steps = int(straggler_steps)
        self.journal_cap = int(journal_cap)
        self.registry = MetricsRegistry()   # collector-owned fleet gauges
        self._fetch = fetch if fetch is not None else _fetch_metrics
        self._lock = threading.Lock()
        self._procs = {}      # key -> ProcessEntry (live)
        self._expired = {}    # key -> ProcessEntry (TTL-lapsed)
        self._scrape_targets = []   # (name, endpoint, labels)

    # -- ingestion ------------------------------------------------------
    @staticmethod
    def _key_of(labels):
        return (str(labels.get("job", "")), str(labels.get("role", "")),
                str(labels.get("replica", "")),
                int(labels.get("pid", 0) or 0))

    def ingest(self, payload):
        """One push payload in; returns the ack dict. ValueError on a
        structurally bad payload (the HTTP layer maps it to 400)."""
        if not isinstance(payload, dict) \
                or not isinstance(payload.get("labels"), dict):
            raise ValueError('payload must be {"labels": {...}, ...}')
        labels = payload["labels"]
        key = self._key_of(labels)
        now_epoch = time.time()
        with self._lock:
            entry = self._procs.get(key) or self._expired.pop(key, None)
            if entry is None:
                entry = ProcessEntry(key, labels)
            self._procs[key] = entry
            entry.labels = dict(labels)
            entry.via = "push"
            entry.last_seen = time.monotonic()
            entry.last_ts = now_epoch
            seq = payload.get("seq")
            replay = False
            if seq is not None:
                seq = int(seq)
                if entry.seq is not None:
                    if seq > entry.seq + 1:
                        gap = seq - entry.seq - 1
                        entry.dropped += gap
                        self.registry.counter(
                            "obs_dropped_snapshots_total",
                            help="push snapshots lost between a "
                                 "client's consecutive sequence "
                                 "numbers").inc(gap)
                    # a client retries a failed push under the SAME seq;
                    # if the first attempt actually landed (lost ack),
                    # appending its tails again would duplicate samples
                    replay = seq <= entry.seq
                entry.seq = max(entry.seq or 0, seq)
            clock = payload.get("clock")
            if isinstance(clock, dict):
                entry.clock = clock
                entry.offset_s = tl.clock_offset(clock, now_epoch)
            if isinstance(payload.get("metrics"), list):
                entry.metrics = payload["metrics"]
                entry._note_steps()
            for field, cap in (("journal", self.journal_cap),
                               ("health", self.journal_cap)):
                tail = payload.get(field)
                if isinstance(tail, list) and tail and not replay:
                    store = getattr(entry, field)
                    store.extend(r for r in tail if isinstance(r, dict))
                    del store[: max(0, len(store) - cap)]
            for d in payload.get("trace_dumps") or []:
                if isinstance(d, dict) and d.get("dir") \
                        and all(x.get("dir") != d["dir"]
                                for x in entry.trace_dumps):
                    entry.trace_dumps.append(
                        {"dir": str(d["dir"]),
                         "manifest": d.get("manifest")})
            entry.pushes += 1
        self.registry.counter(
            "obs_pushes_total",
            help="push snapshots accepted by the collector").inc()
        return {"ok": True, "seq": entry.seq}

    # -- scrape mode ----------------------------------------------------
    def add_scrape_target(self, name, endpoint, labels=None):
        """Poll an existing GET /metrics exposition (serve replica,
        router, any Prometheus endpoint) as a fleet member."""
        base = {"job": flags.get("obs_job") or "job", "role": "scrape",
                "replica": str(name), "pid": 0}
        base.update(labels or {})
        with self._lock:
            self._scrape_targets.append((str(name), str(endpoint), base))

    def scrape_tick(self):
        """One scrape sweep over every target; unreachable targets are
        skipped (TTL expiry handles persistent silence)."""
        with self._lock:
            targets = list(self._scrape_targets)
        ok = 0
        for name, endpoint, labels in targets:
            try:
                metrics = parse_exposition(self._fetch(endpoint))
            except (OSError, ValueError):
                continue
            key = self._key_of(labels)
            with self._lock:
                entry = self._procs.get(key) \
                    or self._expired.pop(key, None) \
                    or ProcessEntry(key, labels, via="scrape")
                self._procs[key] = entry
                entry.via = "scrape"
                entry.metrics = metrics
                entry.last_seen = time.monotonic()
                entry.last_ts = time.time()
                entry._note_steps()
            ok += 1
        if ok:
            self.registry.counter(
                "obs_scrapes_total",
                help="successful scrape sweeps over /metrics "
                     "targets").inc(ok)
        return ok

    # -- liveness -------------------------------------------------------
    def _expire_locked(self):
        now = time.monotonic()
        lapsed = [k for k, e in self._procs.items()
                  if now - e.last_seen > self.ttl_s]
        for k in lapsed:
            self._expired[k] = self._procs.pop(k)
            self.registry.counter(
                "obs_expired_total",
                help="processes dropped from the aggregate by the "
                     "FLAGS_obs_ttl_s stale-process expiry").inc()

    def processes(self):
        """Live (non-expired) entries, expiring stale ones first."""
        with self._lock:
            self._expire_locked()
            return list(self._procs.values())

    # -- fleet-derived gauges + timeline --------------------------------
    def _merged_timeline(self, live):
        return tl.merge_step_timeline(
            [{"name": e.labels.get("replica") or str(e.key),
              "journal": e.journal, "offset_s": e.offset_s}
             for e in live if e.journal],
            straggler_ratio=self.straggler_ratio,
            straggler_steps=self.straggler_steps)

    def _refresh(self):
        """Recompute skew/straggler/overlap gauges from the live set."""
        live = self.processes()
        self.registry.gauge(
            "obs_processes",
            help="live (non-expired) processes in the aggregate").set(
            len(live))
        merged = self._merged_timeline(live)
        if merged["steps"]:
            last = merged["steps"][-1]
            self.registry.gauge(
                "fleet_step_skew_ms",
                help="max-min per-replica step time at the latest "
                     "multi-replica step").set(last["skew_ms"])
            if last["max_over_median"] is not None:
                self.registry.gauge(
                    "fleet_step_skew_max_over_median",
                    help="straggler signal at the latest multi-replica "
                         "step").set(last["max_over_median"])
        stragglers = merged["stragglers"]
        for e in live:
            rep = e.labels.get("replica") or str(e.key)
            self.registry.gauge(
                "fleet_straggler",
                help="1 while this replica is the slowest on >= the "
                     "configured consecutive multi-replica steps",
                replica=rep).set(1.0 if rep in stragglers else 0.0)
            eff = tl.overlap_efficiency(
                e.metric_max("dataflow_compute_ms"),
                e.metric_max("dataflow_comm_ms"),
                tl.hist_quantile(e.merged_hist("step_ms"), 50))
            if eff is not None:
                self.registry.gauge(
                    "fleet_overlap_efficiency",
                    help="fraction of analytic collective time hidden "
                         "under compute (schedule costs joined with the "
                         "measured step median)",
                    replica=rep).set(eff)
        return live, merged

    def timeline(self):
        """Merged step timeline + the fleet's known trace-dump dirs."""
        live, merged = self._refresh()
        dumps = []
        for e in live:
            rep = e.labels.get("replica") or str(e.key)
            for d in e.trace_dumps:
                dumps.append({"replica": rep, "dir": d["dir"]})
        return {"timeline": merged, "dumps": dumps}

    # -- rendering ------------------------------------------------------
    def exposition(self):
        """Aggregated Prometheus text exposition (see module docstring
        for the per-replica + sum/max/histogram-merge semantics)."""
        live, _ = self._refresh()
        fams = {}
        for e in live:
            ident = {k: str(e.labels.get(k, "")) for k in _IDENTITY_KEYS}
            for m in e.metrics:
                name, kind = m.get("name"), m.get("kind")
                if not name or kind not in ("counter", "gauge",
                                            "histogram"):
                    continue
                fam = fams.setdefault(
                    name, {"kind": kind, "help": m.get("help") or "",
                           "rows": [], "agg": {}})
                if fam["kind"] != kind:
                    continue   # kind clash across processes: first wins
                if not fam["help"] and m.get("help"):
                    fam["help"] = m["help"]
                labels = {k: str(v)
                          for k, v in (m.get("labels") or {}).items()}
                row_labels = dict(labels)
                row_labels.update(ident)
                aggkey = tuple(sorted(labels.items()))
                if kind == "histogram":
                    fam["rows"].append((row_labels, m.get("hist")))
                    fam["agg"].setdefault(aggkey, []).append(
                        m.get("hist"))
                else:
                    v = float(m.get("value") or 0.0)
                    fam["rows"].append((row_labels, v))
                    agg = fam["agg"]
                    if kind == "counter":
                        agg[aggkey] = agg.get(aggkey, 0.0) + v
                    else:
                        agg[aggkey] = max(agg.get(aggkey, v), v)

        lines = []
        for name in sorted(fams):
            fam = fams[name]
            pname = _NAME_RE.sub("_", name)
            if fam["help"]:
                lines.append(f"# HELP {pname} {fam['help']}")
            lines.append(f"# TYPE {pname} {fam['kind']}")
            if fam["kind"] == "histogram":
                for labels, hist in fam["rows"]:
                    self._hist_lines(lines, pname, labels, hist)
                for aggkey, hists in sorted(fam["agg"].items()):
                    self._hist_lines(lines, pname, dict(aggkey),
                                     merge_hists(hists))
            else:
                for labels, v in fam["rows"]:
                    lines.append(f"{pname}{_label_suffix(labels)} {v}")
                for aggkey, v in sorted(fam["agg"].items()):
                    lines.append(
                        f"{pname}{_label_suffix(dict(aggkey))} {v}")
        own = self.registry.exposition()
        return own + "\n".join(lines) + ("\n" if lines else "")

    @staticmethod
    def _hist_lines(lines, pname, labels, hist):
        if not hist:
            return
        base = _label_suffix(labels, trailing_comma=True)
        for le, n in sorted(
                ((float("inf") if str(k) in ("+Inf", "inf") else float(k),
                  v) for k, v in (hist.get("buckets") or {}).items())):
            le_s = "+Inf" if le == float("inf") else le
            lines.append(f'{pname}_bucket{base[:-1]},le="{le_s}"}} {n}'
                         if base else f'{pname}_bucket{{le="{le_s}"}} {n}')
        suffix = _label_suffix(labels)
        lines.append(f"{pname}_sum{suffix} {hist.get('sum', 0.0)}")
        lines.append(f"{pname}_count{suffix} {hist.get('count', 0)}")

    def summary(self):
        """The JSON view `obs top` renders: per-process vitals + fleet
        rollup."""
        live, merged = self._refresh()
        snap = self.registry.snapshot()
        procs = []
        for e in sorted(live, key=lambda e: (
                e.labels.get("role", ""), e.labels.get("replica", ""))):
            rep = e.labels.get("replica") or str(e.key)
            step_hist = e.merged_hist("step_ms")
            req_hist = e.merged_hist("serve_request_ms") \
                or e.merged_hist("fleet_request_ms")
            hits = e.metric_sum("compile_cache_hits_total",
                                kinds=("counter",))
            misses = e.metric_sum("compile_cache_misses_total",
                                  kinds=("counter",))
            lookups = (hits or 0.0) + (misses or 0.0)
            hbm = None
            for g in ("hbm_live_bytes_per_replica",
                      "analysis_peak_hbm_bytes_per_replica"):
                hbm = e.metric_max(g, kinds=("gauge",))
                if hbm is not None:
                    break
            procs.append({
                "labels": dict(e.labels),
                "via": e.via,
                "age_s": round(time.monotonic() - e.last_seen, 3),
                "seq": e.seq,
                "dropped": e.dropped,
                "steps_total": e.metric_sum("steps_total",
                                            kinds=("counter",)),
                "step_rate": e.step_rate,
                "p50_ms": tl.hist_quantile(step_hist or req_hist, 50),
                "p99_ms": tl.hist_quantile(step_hist or req_hist, 99),
                "queue_rows": e.metric_max("serve_queue_rows",
                                           kinds=("gauge",)),
                "hbm_bytes": hbm,
                "cache_hit_ratio": ((hits or 0.0) / lookups)
                                   if lookups else None,
                "health_events": e.metric_sum("health_events_total",
                                              kinds=("counter",)),
                "journal_steps": len(e.journal),
                "straggler": rep in merged["stragglers"],
            })
        with self._lock:
            expired = [{"labels": dict(e.labels),
                        "age_s": round(time.monotonic() - e.last_seen, 3)}
                       for e in self._expired.values()]
        return {
            "ts": time.time(),
            "processes": procs,
            "expired": expired,
            "fleet": {
                "processes": len(procs),
                "expired": len(expired),
                "pushes": snap.get("obs_pushes_total", 0),
                "scrapes": snap.get("obs_scrapes_total", 0),
                "dropped_snapshots": snap.get(
                    "obs_dropped_snapshots_total", 0),
                "multi_replica_steps": len(merged["steps"]),
                "max_skew_ms": max((s["skew_ms"] for s in merged["steps"]),
                                   default=None),
                "stragglers": merged["stragglers"],
            },
        }


def _label_suffix(labels, trailing_comma=False):
    labels = {k: v for k, v in labels.items() if v != ""}
    if not labels:
        return ""
    inner = ",".join(
        f'{_NAME_RE.sub("_", k)}="{_escape_label_value(v)}"'
        for k, v in sorted(labels.items()))
    return "{" + inner + ("," if trailing_comma else "}")


# -- HTTP surface -------------------------------------------------------
def make_obs_http(collector, host="127.0.0.1", port=9200):
    """ThreadingHTTPServer over a Collector:
    POST /v1/obs/push, GET /metrics /v1/obs/summary /v1/obs/timeline
    /healthz. Caller owns serve_forever()/shutdown()."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def _reply(self, code, body, content_type="application/json"):
            data = body if isinstance(body, bytes) \
                else body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _json(self, code, obj):
            self._reply(code, json.dumps(obj))

        def do_GET(self):
            col = self.server.collector
            if self.path == "/healthz":
                self._reply(200, "ok\n", content_type="text/plain")
            elif self.path == "/metrics":
                self._reply(200, col.exposition(),
                            content_type="text/plain; version=0.0.4")
            elif self.path == "/v1/obs/summary":
                self._json(200, col.summary())
            elif self.path == "/v1/obs/timeline":
                self._json(200, col.timeline())
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            col = self.server.collector
            if self.path != "/v1/obs/push":
                self._json(404, {"error": f"no route {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
                ack = col.ingest(payload)
            except (ValueError, TypeError) as e:
                self._json(400, {"error": str(e)})
                return
            self._json(200, ack)

    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    httpd.collector = collector
    return httpd


def serve_obs(collector, host="127.0.0.1", port=9200,
              scrape_interval_s=2.0):
    """Blocking collector frontend: serve until KeyboardInterrupt,
    running the scrape sweep on a background cadence when targets are
    registered."""
    httpd = make_obs_http(collector, host, port)
    stop = threading.Event()

    def _scrape_loop():
        while not stop.wait(scrape_interval_s):
            collector.scrape_tick()

    scraper = None
    if collector._scrape_targets:
        collector.scrape_tick()
        scraper = threading.Thread(target=_scrape_loop, name="obs-scrape",
                                   daemon=True)
        scraper.start()
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        stop.set()
        httpd.shutdown()
        httpd.server_close()
    return httpd
