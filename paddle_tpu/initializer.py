"""Parameter initializers — emit init ops into the startup program.

Reference parity: python/paddle/fluid/initializer.py (Constant, Uniform,
Normal, Xavier, MSRA, force_init_on_cpu).
"""

import contextlib
import math

__all__ = [
    "Constant", "Uniform", "Normal", "Xavier", "MSRA", "Bilinear",
    "force_init_on_cpu", "init_on_cpu",
    "ConstantInitializer", "UniformInitializer", "NormalInitializer",
    "XavierInitializer", "MSRAInitializer", "BilinearInitializer",
]

_force_init_on_cpu_ = False


def force_init_on_cpu():
    return _force_init_on_cpu_


@contextlib.contextmanager
def init_on_cpu():
    global _force_init_on_cpu_
    pre = _force_init_on_cpu_
    _force_init_on_cpu_ = True
    try:
        yield
    finally:
        _force_init_on_cpu_ = pre


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    @staticmethod
    def _fan_in_out(var):
        shape = var.shape
        if len(shape) < 2:
            return shape[0] if shape else 1, shape[0] if shape else 1
        receptive = 1
        for s in shape[2:]:
            receptive *= s
        return shape[1] * receptive, shape[0] * receptive


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self._value = value

    def __call__(self, var, block):
        return block.append_op(
            "fill_constant",
            {},
            {"Out": [var]},
            {"shape": list(var.shape), "value": float(self._value), "dtype": var.dtype},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self._low, self._high, self._seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            "uniform_random",
            {},
            {"Out": [var]},
            {
                "shape": list(var.shape),
                "min": float(self._low),
                "max": float(self._high),
                "seed": self._seed,
                "dtype": var.dtype,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std_dev, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "gaussian_random",
            {},
            {"Out": [var]},
            {
                "shape": list(var.shape),
                "mean": float(self._mean),
                "std": float(self._std_dev),
                "seed": self._seed,
                "dtype": var.dtype,
            },
        )


class XavierInitializer(Initializer):
    """reference initializer.py Xavier (Glorot & Bengio 2010)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self._uniform = uniform
        self._fan_in = fan_in
        self._fan_out = fan_out
        self._seed = seed

    def __call__(self, var, block):
        f_in, f_out = self._fan_in_out(var)
        fan_in = f_in if self._fan_in is None else self._fan_in
        fan_out = f_out if self._fan_out is None else self._fan_out
        if self._uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return NormalInitializer(0.0, std, self._seed)(var, block)


class MSRAInitializer(Initializer):
    """reference initializer.py MSRA (He et al. 2015)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self._uniform = uniform
        self._fan_in = fan_in
        self._seed = seed

    def __call__(self, var, block):
        f_in, _ = self._fan_in_out(var)
        fan_in = f_in if self._fan_in is None else self._fan_in
        if self._uniform:
            limit = math.sqrt(6.0 / fan_in)
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = math.sqrt(2.0 / fan_in)
        return NormalInitializer(0.0, std, self._seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling filter init (for conv2d_transpose upsampling)."""

    def __call__(self, var, block):
        import numpy as np

        shape = var.shape
        if len(shape) != 4:
            raise ValueError("BilinearInitializer expects a 4-D filter")
        c_out, c_in, kh, kw = shape
        f = math.ceil(kw / 2.0)
        cgrid = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype="float32")
        for i in range(kh):
            for j in range(kw):
                v = (1 - abs(i / f - cgrid)) * (1 - abs(j / f - cgrid))
                weight[:, :, i, j] = v
        return block.append_op(
            "assign_value",
            {},
            {"Out": [var]},
            {"shape": list(shape), "dtype": var.dtype, "values": weight},
        )


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
