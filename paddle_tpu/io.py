"""Model save/load + checkpointing (reference python/paddle/fluid/io.py:
save_vars:63, save_params, save_persistables, load_vars, load_params,
load_persistables, save_inference_model:300, load_inference_model:377,
save_checkpoint:463 (+_SUCCESS markers :595, LRU retention :576),
load_checkpoint:505, clean_checkpoint).

Programs built here contain `save`/`load` ops executed by the eager
interpreter path — same architecture as the reference's save/load ops.
The model file is the JSON-serialized Program IR.
"""

import errno
import json
import os
import shutil
import time

from .core.framework import (Program, Parameter, Variable,
                             default_main_program, default_startup_program)
from .executor import Executor

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars", "load_params",
    "load_persistables", "save_inference_model", "load_inference_model",
    "get_inference_program", "save_checkpoint", "load_checkpoint",
    "clean_checkpoint", "save_train_model",
]

SUCCESS_MARK_FILENAME = "_SUCCESS"
CHECKPOINT_PREFIX = "checkpoint"
MODEL_DIR = "__model__"
CHECKPOINT_SEPARATOR = "_"


def is_parameter(var):
    return isinstance(var, Parameter)


def is_persistable(var):
    from .core.framework import VarType

    if var.type in (VarType.FEED_MINIBATCH, VarType.FETCH_LIST, VarType.READER):
        return False
    return var.persistable


def _clone_var_in_block_(block, var):
    return block.create_var(
        name=var.name,
        shape=var.shape,
        dtype=var.dtype,
        lod_level=var.lod_level,
        persistable=True,
    )


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    """reference io.py:63."""
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        save_vars(
            executor,
            dirname=dirname,
            vars=list(filter(predicate, main_program.list_vars())),
            filename=filename,
        )
    else:
        save_program = Program()
        save_block = save_program.global_block()
        save_var_list = []
        for each_var in vars:
            if each_var.type == "raw":
                continue
            new_var = _clone_var_in_block_(save_block, each_var)
            if filename is None:
                save_block.append_op(
                    "save",
                    {"X": [new_var]},
                    {},
                    {"file_path": os.path.join(dirname, new_var.name)},
                )
            else:
                save_var_list.append(new_var)
        if filename is not None:
            save_block.append_op(
                "save_combine",
                {"X": save_var_list},
                {},
                {"file_path": os.path.join(dirname, filename)},
            )
        executor.run(save_program)


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, None, is_parameter, filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, None, is_persistable, filename)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    """reference io.py:124."""
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        load_vars(
            executor,
            dirname=dirname,
            vars=list(filter(predicate, main_program.list_vars())),
            filename=filename,
        )
    else:
        load_prog = Program()
        load_block = load_prog.global_block()
        load_var_list = []
        for each_var in vars:
            assert isinstance(each_var, Variable)
            new_var = _clone_var_in_block_(load_block, each_var)
            if filename is None:
                load_block.append_op(
                    "load",
                    {},
                    {"Out": [new_var]},
                    {"file_path": os.path.join(dirname, new_var.name)},
                )
            else:
                load_var_list.append(new_var)
        if filename is not None:
            load_block.append_op(
                "load_combine",
                {},
                {"Out": load_var_list},
                {"file_path": os.path.join(dirname, filename)},
            )
        executor.run(load_prog)


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, None, is_parameter, filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, None, is_persistable, filename)


def get_inference_program(target_vars, main_program=None):
    if main_program is None:
        main_program = default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    pruned = main_program.prune(targets=target_vars)
    inference_program = pruned.inference_optimize()
    return inference_program


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None):
    """reference io.py:300: prune to feed/fetch targets + serialize."""
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    if main_program is None:
        main_program = default_main_program()
    if not os.path.isdir(dirname):
        os.makedirs(dirname, exist_ok=True)

    pruned_program = main_program.prune(targets=target_vars)
    inference_program = pruned_program.inference_optimize()
    fetch_var_names = [v.name for v in target_vars]

    model_basename = model_filename or "__model__"
    with open(os.path.join(dirname, model_basename), "w") as f:
        json.dump(
            {
                "program": inference_program.to_dict(),
                "feed_var_names": feeded_var_names,
                "fetch_var_names": fetch_var_names,
            },
            f,
        )
    save_persistables(executor, dirname, inference_program, params_filename)
    return fetch_var_names


def save_train_model(dirname, feeded_var_names, loss, main_program=None,
                     startup_program=None):
    """Serialize a FULL training program (forward + backward + optimizer
    ops) plus its startup program for the native C++ trainer
    (native/train.cc; reference parity: the ProgramDesc + init program
    fluid/train/demo/demo_trainer.cc loads). No parameters are written —
    the native side runs the startup initializers itself."""
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if main_program is None:
        main_program = default_main_program()
    if startup_program is None:
        startup_program = default_startup_program()
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "__train__"), "w") as f:
        json.dump(
            {
                "main_program": main_program.to_dict(),
                "startup_program": startup_program.to_dict(),
                "feed_var_names": feeded_var_names,
                "loss_name": loss.name if isinstance(loss, Variable)
                else str(loss),
            },
            f,
        )
    return dirname


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """reference io.py:377 -> (program, feed_names, fetch_targets)."""
    if not os.path.isdir(dirname):
        raise ValueError("There is no directory named '%s'" % dirname)
    model_basename = model_filename or "__model__"
    with open(os.path.join(dirname, model_basename)) as f:
        payload = json.load(f)
    program = Program.from_dict(payload["program"])
    load_persistables(executor, dirname, program, params_filename)
    feed_names = payload["feed_var_names"]
    fetch_targets = [program.global_block().var(n) for n in payload["fetch_var_names"]]
    return program, feed_names, fetch_targets


# ---------------------------------------------------------------------------
# Checkpointing (reference io.py:463-644)
# ---------------------------------------------------------------------------
def save_checkpoint(executor, checkpoint_dir=None, max_num_checkpoints=3,
                    save_interval_secs=600, main_program=None):
    if checkpoint_dir is None:
        checkpoint_dir = os.getcwd()
    if not os.path.isdir(checkpoint_dir):
        os.makedirs(checkpoint_dir, exist_ok=True)
    serial = _get_latest_checkpoint_serial(checkpoint_dir)
    if serial >= 0 and not _interval_secs_exceed(
        _get_serial_dir(serial, checkpoint_dir), save_interval_secs
    ):
        return
    serial += 1
    cur_dir = _get_serial_dir(serial, checkpoint_dir)
    # write into a .tmp sibling and commit by rename: a crash mid-save can
    # only ever leave a .tmp orphan (swept by _lru_delete), never a
    # half-written checkpoint_<N> that a reader could pick up
    tmp_dir = cur_dir + ".tmp"
    if os.path.isdir(tmp_dir):
        shutil.rmtree(tmp_dir, ignore_errors=True)
    save_vars(
        executor,
        dirname=tmp_dir,
        main_program=main_program,
        vars=None,
        predicate=_is_checkpoint_var,
        filename=None,
    )
    _write_success(tmp_dir)
    _fsync_dir(tmp_dir)
    os.replace(tmp_dir, cur_dir)
    _fsync_dir(checkpoint_dir)
    _lru_delete(checkpoint_dir, max_num_checkpoints)


def load_checkpoint(executor, checkpoint_dir=None, main_program=None):
    if checkpoint_dir is None:
        checkpoint_dir = os.getcwd()
    serial = _get_latest_checkpoint_serial(checkpoint_dir)
    if serial < 0:
        return
    cur_dir = _get_serial_dir(serial, checkpoint_dir)
    load_vars(
        executor,
        dirname=cur_dir,
        main_program=main_program,
        predicate=_is_checkpoint_var,
        filename=None,
    )


def clean_checkpoint(checkpoint_dir, delete_dir=False):
    if checkpoint_dir is None:
        checkpoint_dir = os.getcwd()
    _lru_delete(checkpoint_dir, max_num_checkpoints=0)
    if delete_dir and not os.listdir(checkpoint_dir):
        os.rmdir(checkpoint_dir)


def _get_serial_dir(serial, checkpoint_dir):
    serial_folder = CHECKPOINT_PREFIX + CHECKPOINT_SEPARATOR + str(serial)
    return os.path.join(checkpoint_dir, serial_folder)


def _is_checkpoint_var(var):
    """reference io.py:551 — persistables minus feed/fetch/reader/grads."""
    from .core.framework import VarType

    if var.type in (VarType.FEED_MINIBATCH, VarType.FETCH_LIST, VarType.RAW,
                    VarType.READER):
        return False
    if var.name.endswith("@GRAD"):
        return False
    return var.persistable


def _interval_secs_exceed(dirname, save_interval_secs):
    dir_time = os.path.getmtime(dirname)
    return (time.time() - save_interval_secs) >= dir_time


def _lru_delete(dirname, max_num_checkpoints=3):
    """reference io.py:576 — keep newest N COMMITTED checkpoint dirs.

    Only dirs carrying the _SUCCESS marker count toward the retention
    budget; _SUCCESS-less serial dirs are crash debris (with the atomic
    rename protocol a committed dir always has its marker) and are
    removed outright rather than silently eating retention slots. Stale
    `.tmp` staging dirs are swept too (age-gated so a concurrent writer's
    in-flight temp dir is left alone)."""
    committed = []
    for name in os.listdir(dirname):
        path = os.path.join(dirname, name)
        if not os.path.isdir(path):
            continue
        if name.endswith(".tmp"):
            try:
                stale = (time.time() - os.path.getmtime(path)) > 300
            except OSError:
                stale = False
            if stale:
                shutil.rmtree(path, ignore_errors=True)
            continue
        try:
            serial = int(name.split(CHECKPOINT_SEPARATOR)[-1])
        except ValueError:
            continue
        if os.path.isfile(os.path.join(path, SUCCESS_MARK_FILENAME)):
            committed.append(serial)
        else:
            shutil.rmtree(path, ignore_errors=True)
    if len(committed) <= max_num_checkpoints:
        return
    committed.sort(reverse=True)
    for serial in committed[max_num_checkpoints:]:
        shutil.rmtree(_get_serial_dir(serial, dirname), ignore_errors=True)


def _fsync_dir(path):
    """fsync a directory fd so the rename/create is durable (no-op where
    directory fds aren't a thing)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_success(dirname):
    """reference io.py:595 — completion marker, fsynced so the marker is
    on disk before the enclosing dir is renamed into place."""
    with open(os.path.join(dirname, SUCCESS_MARK_FILENAME), "a") as f:
        now = time.ctime()
        f.write(now)
        f.flush()
        os.fsync(f.fileno())


def _get_latest_checkpoint_serial(checkpoint_dir):
    """reference io.py:606 — newest serial with a _SUCCESS marker."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return -1

    def has_success(checkpoint_dir, cur_dir):
        serial = cur_dir.split(CHECKPOINT_SEPARATOR)[-1]
        try:
            int(serial)
        except ValueError:
            return -1
        if not os.path.isdir(os.path.join(checkpoint_dir, cur_dir)):
            return -1
        success_path = os.path.join(
            _get_serial_dir(int(serial), checkpoint_dir), SUCCESS_MARK_FILENAME
        )
        if os.path.isfile(success_path):
            return int(serial)
        return -1

    current_dir = -1
    for cur_dir in os.listdir(checkpoint_dir):
        success_num = has_success(checkpoint_dir, cur_dir)
        if success_num > current_dir:
            current_dir = success_num
    return current_dir
