"""paddle_tpu.trace — end-to-end distributed tracing + flight recorder.

The monitor (paddle_tpu.monitor) answers "what is the average"; trace
answers "why was THIS one slow" and "what happened right before the
hang". Three hot paths are instrumented end to end:

  serve     serve.http -> serve.request -> queue/pad/dispatch/readback
            child spans per request; the batcher's fan-in dispatch is a
            serve.batch span LINKED to every coalesced request's context
            (one slow request stays attributable after batching).
  training  <kind>.step spans with feed_wait/feed_encode/compile/
            dispatch/fetch_readback phase children, replayed from
            monitor.StepRecord's existing phase boundaries at step_end;
            datapipe.map / datapipe.stack / datapipe.transfer worker
            spans with explicit context propagation into the pools.
  compiles  compile phases carry the cache fingerprint; costs.py joins
            the fingerprint's HLO cost totals back onto ProgramDesc ops
            for the slowest-ops table (`paddle_tpu trace ops`).

Spans land in an in-memory flight recorder (recorder.py): per-thread
fixed-size rings, dumped (spans.jsonl + chrome trace.json +
manifest.json) when the resilience watchdog fires, the NaN guard trips,
a serve SLO violation / ServerOverloaded occurs, or on demand via
`python -m paddle_tpu trace dump`.

Off contract (FLAGS_trace=0, the default): one flag check per
instrumentation site, no allocation — same deal as FLAGS_monitor.
See docs/observability.md.
"""

from .costs import (attribute_costs, format_ops_table, op_costs,
                    register_program, registered_fingerprints,
                    slowest_ops)
from .export import CHROME_PID, FORMAT, chrome_events, load_dump, write_dump
from .recorder import (append, dump, last_dump, maybe_dump, reset,
                       snapshot)
from .span import (SpanContext, attach, current, enabled, new_context,
                   record, span)

__all__ = [
    # span API
    "SpanContext", "enabled", "current", "new_context", "attach", "span",
    "record",
    # flight recorder
    "append", "snapshot", "reset", "dump", "maybe_dump", "last_dump",
    # dump formats
    "FORMAT", "CHROME_PID", "chrome_events", "write_dump", "load_dump",
    # per-op cost attribution
    "register_program", "registered_fingerprints", "op_costs",
    "attribute_costs", "slowest_ops", "format_ops_table",
]
