"""Per-op compile cost attribution: HLO totals mapped back to ProgramDesc.

jax's cost_analysis (monitor.compile_probe) reports ONE aggregate FLOP
count per compiled step — true but unactionable when the question is
"which layer do I shard / fuse / shrink". XLA destroys op identity, so
the mapping back is analytic: estimate each ProgramDesc op's FLOPs from
its operand/result shapes (the standard 2*M*K*N-style counts the HLO
total itself is built from), then scale every estimate so they sum to
the measured HLO total. Shares are exact under the estimator; absolute
FLOPs inherit the HLO measurement.

Executors register the Program behind each compile-cache fingerprint
(register_program, weakref — attribution must not extend program
lifetime), so slowest_ops() can join monitor.compile_info()'s measured
totals with the op graph after the fact: the `paddle_tpu trace ops`
table, and the slowest_ops block in flight-recorder manifests.
"""

import weakref

from .. import monitor

__all__ = ["register_program", "registered_fingerprints", "op_costs",
           "attribute_costs", "slowest_ops", "format_ops_table"]

_programs = {}  # fingerprint -> weakref.ref(Program)

# ops that move/bookkeep but do no arithmetic worth attributing
_FREE_OPS = frozenset((
    "feed", "fetch", "fill_constant", "shape", "read", "read_from_array",
    "write_to_array", "increment", "assign", "share_lod", "print",
))

# per-element arithmetic weight for ops whose cost ~ output size; the
# default (1 flop/elem) covers the elementwise/copy family
_ELEM_WEIGHTS = {
    "softmax": 5.0, "log_softmax": 5.0, "sigmoid": 4.0, "tanh": 4.0,
    "exp": 4.0, "log": 4.0, "sqrt": 2.0, "rsqrt": 2.0,
    "batch_norm": 8.0, "layer_norm": 8.0, "group_norm": 8.0,
    "dropout": 2.0, "cross_entropy": 5.0,
    "softmax_with_cross_entropy": 10.0, "sigmoid_cross_entropy_with_logits":
    8.0, "swish": 4.0, "gelu": 8.0, "elu": 3.0, "selu": 3.0,
}


def register_program(fingerprint, program):
    """Remember (weakly) which Program a compile-cache fingerprint was
    built from; called by the executors alongside record_compile."""
    if fingerprint is None or program is None:
        return
    try:
        _programs[str(fingerprint)] = weakref.ref(program)
    except TypeError:
        pass


def registered_fingerprints():
    """Fingerprints whose Program is still alive."""
    return [fp for fp, ref in list(_programs.items())
            if ref() is not None]


def _numel(shape, batch):
    n = 1
    for d in shape or ():
        d = batch if (d is None or int(d) < 0) else int(d)
        n *= max(1, d)
    return max(1, n)


def _shape_of(block, name, batch):
    var = block.vars.get(name)
    if var is None and hasattr(block, "var_recursive"):
        try:
            var = block.var_recursive(name)
        except Exception:
            var = None
    return None if var is None else (var.shape or ())


def _estimate(block, op, batch):
    """Analytic FLOPs for one op (forward form); returns float."""
    t = op.type
    outs = op.output_arg_names()
    out_elems = _numel(_shape_of(block, outs[0], batch), batch) \
        if outs else 1

    if t in ("mul", "matmul", "matmul_v2"):
        # X [.., K] x Y [K, N]: 2*M*K*N with M = numel(X)/K
        xs = op.input("X") or op.input_arg_names()[:1]
        ys = op.input("Y") or op.input_arg_names()[1:2]
        x_shape = _shape_of(block, xs[0], batch) if xs else None
        y_shape = _shape_of(block, ys[0], batch) if ys else None
        if x_shape and y_shape:
            k = max(1, _numel(y_shape[:1], batch))
            m = _numel(x_shape, batch) / k
            n = _numel(y_shape, batch) / k
            return 2.0 * m * k * n
        return 2.0 * out_elems
    if t in ("conv2d", "depthwise_conv2d", "conv2d_transpose", "conv3d"):
        fs = op.input("Filter")
        f_shape = _shape_of(block, fs[0], batch) if fs else None
        if f_shape and len(f_shape) >= 3:
            # [Cout, Cin/groups, kh, kw]: 2 * out * Cin_g * prod(k)
            per_out = 2.0
            for d in f_shape[1:]:
                per_out *= max(1, int(d) if d is not None and d > 0 else 1)
            return out_elems * per_out
        return 2.0 * out_elems
    if t in ("pool2d", "pool3d"):
        k = op.attrs.get("ksize") or []
        kk = 1.0
        for d in k:
            kk *= max(1, int(d))
        if op.attrs.get("global_pooling"):
            ins = op.input("X")
            in_shape = _shape_of(block, ins[0], batch) if ins else None
            if in_shape and len(in_shape) >= 2:
                kk = _numel(in_shape, batch) / max(1, out_elems)
        return out_elems * kk
    if t.startswith("reduce_") or t in ("mean", "sum"):
        ins = op.input("X") or op.input_arg_names()[:1]
        in_shape = _shape_of(block, ins[0], batch) if ins else None
        return float(_numel(in_shape, batch)) if in_shape is not None \
            else float(out_elems)
    if t in ("lookup_table", "gather", "concat", "split", "transpose",
             "reshape", "squeeze", "unsqueeze", "cast", "scale", "pad"):
        return float(out_elems)
    if t in ("pipeline_send", "pipeline_recv", "zero1_gather",
             "all_gather", "broadcast"):
        # pure data movement (ICI): attribute the moved elements
        return float(out_elems)
    if t in ("zero1_scatter", "all_reduce", "reduce_scatter"):
        # ring reduction: ~one add per input element around the ring
        ins = op.input("X") or op.input_arg_names()[:1]
        in_shape = _shape_of(block, ins[0], batch) if ins else None
        return float(_numel(in_shape, batch)) if in_shape is not None \
            else float(out_elems)
    if t == "fused_elementwise":
        # the collapsed chain does every sub-op's arithmetic in one pass
        subs = op.attrs.get("sub_types") or ()
        return sum(_ELEM_WEIGHTS.get(s, 1.0) for s in subs) * out_elems
    if t in ("fused_sgd_update", "fused_momentum_update",
             "fused_adam_update"):
        # per-element update cost x total bucket payload
        per = {"fused_sgd_update": 2.0, "fused_momentum_update": 5.0,
               "fused_adam_update": 12.0}[t]
        total = 0.0
        for nm in (op.input("Param") or []):
            total += _numel(_shape_of(block, nm, batch), batch)
        return per * max(1.0, total)
    if t.endswith("_grad"):
        # grad ops roughly mirror the forward cost for input grads plus
        # a comparable pass for parameter grads
        fwd = _OpProxy(op, t[:-len("_grad")])
        return 2.0 * _estimate(block, fwd, batch)
    return _ELEM_WEIGHTS.get(t, 1.0) * out_elems


class _OpProxy:
    """An op view with a substituted type (grad -> forward estimation)."""

    __slots__ = ("_op", "type")

    def __init__(self, op, type_):
        self._op = op
        self.type = type_

    def __getattr__(self, name):
        return getattr(self._op, name)


def op_costs(program, batch_size=1):
    """Analytic per-op FLOP estimates over the global block:
    [{"index", "op", "out", "flops_est"}] in program order."""
    gb = program.global_block()
    batch = max(1, int(batch_size))
    rows = []
    for i, op in enumerate(gb.ops):
        if op.type in _FREE_OPS:
            continue
        try:
            est = float(_estimate(gb, op, batch))
        except Exception:
            est = 0.0
        outs = op.output_arg_names()
        rows.append({"index": i, "op": op.type,
                     "out": outs[0] if outs else "", "flops_est": est})
    return rows


def attribute_costs(program, total_flops=None, batch_size=1):
    """Per-op attribution, most expensive first. Each row carries
    `share` (of the analytic total — exact under the estimator) and
    `flops` (share scaled onto the measured HLO total when given, else
    the raw estimate)."""
    rows = op_costs(program, batch_size=batch_size)
    est_total = sum(r["flops_est"] for r in rows) or 1.0
    scale = (float(total_flops) / est_total) if total_flops else 1.0
    for r in rows:
        r["share"] = r["flops_est"] / est_total
        r["flops"] = r["flops_est"] * scale
    rows.sort(key=lambda r: -r["flops_est"])
    return rows


def slowest_ops(fingerprint=None, batch_size=1, top=10):
    """The slowest-ops report joining a registered Program with its
    measured compile info: {"fingerprint", "total_flops", "wall_s",
    "measured", "ops": [...top rows...]}. Picks the registered
    fingerprint with the largest measured FLOPs when none is named;
    None when nothing usable is registered."""
    info = monitor.compile_info()
    live = {fp: ref() for fp, ref in _programs.items()
            if ref() is not None}
    if not live:
        return None
    if fingerprint is None:
        def measured(fp):
            return info.get(fp, {}).get("flops") or 0.0
        fingerprint = max(live, key=measured)
    fingerprint = str(fingerprint)
    program = live.get(fingerprint)
    if program is None:
        return None
    ci = info.get(fingerprint, {})
    total = ci.get("flops")
    rows = attribute_costs(program, total_flops=total,
                           batch_size=batch_size)
    return {
        "fingerprint": fingerprint,
        "total_flops": total,
        "wall_s": ci.get("wall_s"),
        "measured": total is not None,
        "ops": [dict(r) for r in rows[:max(1, int(top))]],
    }


def _fmt_flops(v):
    for unit, div in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}"


def format_ops_table(report):
    """Human-readable slowest-ops table from a slowest_ops() report."""
    if not report:
        return "no compiled program registered (run a step first)"
    src = "HLO cost analysis" if report["measured"] \
        else "analytic estimate (no HLO total measured)"
    lines = [f"fingerprint {report['fingerprint']}  "
             f"total_flops="
             f"{_fmt_flops(report['total_flops'] or 0.0)}  [{src}]"]
    if report.get("wall_s") is not None:
        lines[0] += f"  compile_wall_s={report['wall_s']:.3f}"
    lines.append(f"{'#':>3} {'op':<28}{'output':<28}"
                 f"{'flops':>10}{'share':>8}{'cum':>8}")
    cum = 0.0
    for i, r in enumerate(report["ops"], 1):
        cum += r["share"]
        lines.append(f"{i:>3} {r['op']:<28}{r['out'][:27]:<28}"
                     f"{_fmt_flops(r['flops']):>10}"
                     f"{r['share']:>8.1%}{cum:>8.1%}")
    return "\n".join(lines)
