"""Flight-recorder dump formats: spans.jsonl, chrome trace, manifest.

One dump directory holds three views of the same snapshot:

    spans.jsonl     one span dict per line (the machine-readable source
                    of truth: load_dump() round-trips it)
    trace.json      chrome://tracing / Perfetto JSON — spans as "X"
                    complete events grouped pid=1 ("paddle_tpu trace"),
                    one tid row per recording thread. The same builder
                    feeds profiler.export_chrome_trace, so a profiler
                    session's merged timeline shows host events (pid 0),
                    trace spans (pid 1) and the XLA device lanes
                    (pid 100+) on one clock.
    manifest.json   schema below — everything needed to interpret the
                    other two files without this codebase.

Manifest schema (format "paddle_tpu.trace/1"):
    format      "paddle_tpu.trace/1"
    reason      dump trigger ("manual", "hang_<label>", "nan_guard",
                "serve_slo", "server_overloaded", ...)
    ts          wall-clock seconds (time.time) when the dump was written
    pid         dumping process id
    clock       {"perf_counter", "epoch"} sampled together at dump time:
                span t0/t1 are perf_counter seconds, so
                epoch_of(t) = t - clock.perf_counter + clock.epoch
    spans       span count in the snapshot
    dropped     spans overwritten in the rings before the dump (ring
                capacity FLAGS_trace_buffer per thread)
    buffers     per-thread rings contributing to the snapshot
    traces      distinct trace_ids in the snapshot
    names       {span name: count}
    files       {"spans": "spans.jsonl", "chrome": "trace.json"}
    slowest_ops per-op compile cost attribution (costs.slowest_ops()
                report) when a profiled compile was available, else null
"""

import json
import os
import time

__all__ = ["FORMAT", "CHROME_PID", "chrome_events", "write_dump",
           "load_dump"]

FORMAT = "paddle_tpu.trace/1"
CHROME_PID = 1  # profiler host lane is pid 0, XLA device lanes pid 100+


def chrome_events(spans, t0=None, pid=CHROME_PID,
                  process_name="paddle_tpu trace", sort_index=1):
    """Spans -> chrome-trace event dicts ("X" complete events, one tid
    row per recording thread). `t0` sets the timeline origin in
    perf_counter seconds (defaults to the earliest span) — pass the
    profiler's _trace_t0 to align with its host/device lanes. Fleet
    merges (obs/timeline.py) pass a distinct pid + process_name per
    process so lanes don't collide on the default pid 1."""
    if not spans:
        return []
    if t0 is None:
        t0 = min(s["t0"] for s in spans)
    events = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": process_name}},
        {"ph": "M", "pid": pid, "name": "process_sort_index",
         "args": {"sort_index": sort_index}},
    ]
    for s in spans:
        args = {"trace": s["trace"], "span": s["span"]}
        if s.get("parent"):
            args["parent"] = s["parent"]
        if s.get("links"):
            args["links"] = s["links"]
        if s.get("attrs"):
            args.update(s["attrs"])
        events.append({
            "name": s["name"],
            "ph": "X",
            "ts": (s["t0"] - t0) * 1e6,
            "dur": max(0.0, (s["t1"] - s["t0"]) * 1e6),
            "pid": pid,
            "tid": s.get("thread", "?"),
            "cat": s.get("kind", "span"),
            "args": args,
        })
    return events


def write_dump(path, spans, reason="manual", dropped=0, buffers=0,
               slowest_ops=None):
    """Materialize one dump directory at `path`; returns the path."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "spans.jsonl"), "w") as f:
        for s in spans:
            f.write(json.dumps(s) + "\n")
    with open(os.path.join(path, "trace.json"), "w") as f:
        json.dump({"traceEvents": chrome_events(spans),
                   "displayTimeUnit": "ms"}, f)
    names = {}
    for s in spans:
        names[s["name"]] = names.get(s["name"], 0) + 1
    manifest = {
        "format": FORMAT,
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "clock": {"perf_counter": time.perf_counter(),
                  "epoch": time.time()},
        "spans": len(spans),
        "dropped": int(dropped),
        "buffers": int(buffers),
        "traces": len({s["trace"] for s in spans}),
        "names": names,
        "files": {"spans": "spans.jsonl", "chrome": "trace.json"},
        "slowest_ops": slowest_ops,
    }
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    return path


def load_dump(path):
    """Read a dump directory (or its manifest.json path) back:
    {"manifest": dict, "spans": [span dicts]}."""
    if os.path.isfile(path):
        path = os.path.dirname(path) or "."
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    spans = []
    spans_file = os.path.join(path,
                              manifest.get("files", {}).get("spans",
                                                            "spans.jsonl"))
    with open(spans_file) as f:
        for line in f:
            line = line.strip()
            if line:
                spans.append(json.loads(line))
    return {"manifest": manifest, "spans": spans}
