"""Span primitives and the thread-local trace context.

A span is one timed operation: {trace, span, parent, name, kind, t0, t1,
thread, attrs, links}. trace/span ids are 16-hex random strings; every
span created while a context is attached inherits that context's
trace_id and parents under its span_id — so one serve request's HTTP
handler, queue wait, and readback land in ONE trace even though three
different threads touch the request.

Two recording styles, both landing in the flight recorder (recorder.py):

    with trace.span("serve.batch", links=[...]):   # eager: times a block
        exe.run(...)

    ctx = trace.record("executor.step", t0, t1)    # retroactive: stamps
    trace.record("dispatch", d0, d1, parent=ctx)   # already-measured work

Retroactive recording is how the executors emit step/phase spans without
re-indenting their hot paths: monitor.StepRecord already carries the
phase boundaries, and step_end replays them into spans after the step.

Cross-thread propagation is explicit (thread pools outlive any one
trace): capture `current()` where the work is submitted and `attach()`
it in the worker. Fan-in points (the serve batcher coalescing N requests
into one dispatch) cannot parent under N requests at once — they record
span LINKS to every coalesced request's context instead.

Off contract: FLAGS_trace=0 makes span() return a shared no-op handle
and record() return None — one flag check, no allocation (same contract
as FLAGS_monitor).
"""

import contextlib
import os
import threading
import time

from .. import flags

__all__ = ["SpanContext", "enabled", "current", "new_context", "attach",
           "span", "record"]

flags.define(
    "trace", bool, False,
    "Span-based tracing into the in-memory flight recorder "
    "(paddle_tpu.trace): serve request lifecycles, executor step/phase "
    "spans, datapipe worker spans. Off by default; when 0 the hot-path "
    "cost is a single flag check (asserted by tests/test_trace.py). "
    "Dumps on watchdog/NaN/SLO anomalies or `paddle_tpu trace dump`.")

# sentinel: record(parent=None) means "root span", omitting parent means
# "parent under the caller's current context"
_USE_CURRENT = object()

_tls = threading.local()


def _new_id():
    return os.urandom(8).hex()


def enabled():
    """THE hot-path flag check; every other trace call is gated on it."""
    return bool(flags.get("trace"))


class SpanContext:
    """Immutable (trace_id, span_id) pair — what propagates across
    threads and what links point at."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_dict(self):
        return {"trace": self.trace_id, "span": self.span_id}

    def __repr__(self):
        return f"SpanContext(trace={self.trace_id}, span={self.span_id})"


def current():
    """The calling thread's attached SpanContext, or None."""
    return getattr(_tls, "ctx", None)


def new_context(parent=_USE_CURRENT):
    """A fresh SpanContext: same trace as `parent` (default: the current
    context), new span id; a brand-new trace when parentless. Used to
    pre-allocate a span's identity before the span is recorded (the serve
    request span's id must exist at submit() so the batch span can link
    to it long before the request span itself is stamped)."""
    if parent is _USE_CURRENT:
        parent = current()
    tid = parent.trace_id if parent is not None else _new_id()
    return SpanContext(tid, _new_id())


@contextlib.contextmanager
def attach(ctx):
    """Make `ctx` the calling thread's current context for the block —
    the explicit propagation edge into worker threads (capture current()
    where work is submitted, attach() it where the work runs)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


def record(name, t0, t1, kind="span", ctx=None, parent=_USE_CURRENT,
           links=None, attrs=None):
    """Retroactively stamp one finished span into the flight recorder.

    t0/t1 are time.perf_counter() seconds (the manifest carries the
    perf_counter<->epoch anchor). `ctx` supplies a pre-allocated identity
    (new_context), otherwise one is minted under `parent`; passing
    parent=None explicitly makes a root span. Returns the span's
    SpanContext (None when tracing is off) so children can parent to it.
    """
    if not enabled():
        return None
    if parent is _USE_CURRENT:
        parent = current()
    if ctx is None:
        ctx = new_context(parent=parent)
    sp = {
        "name": name,
        "kind": kind,
        "trace": ctx.trace_id,
        "span": ctx.span_id,
        "parent": parent.span_id if parent is not None else None,
        "t0": float(t0),
        "t1": float(t1),
        "thread": threading.current_thread().name,
    }
    if links:
        sp["links"] = [l.to_dict() for l in links if l is not None]
    if attrs:
        sp["attrs"] = dict(attrs)
    from . import recorder

    recorder.append(sp)
    return ctx


class _NoopSpan:
    """Shared disabled-path handle: span() returns this singleton when
    FLAGS_trace=0 — no allocation per call."""

    __slots__ = ()
    ctx = None

    def set(self, **attrs):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopSpan()


class _LiveSpan:
    """Eager span: __enter__ attaches a fresh context (so nested spans
    and worker handoffs parent correctly), __exit__ records."""

    __slots__ = ("name", "kind", "links", "attrs", "ctx", "_parent",
                 "_prev", "_t0")

    def __init__(self, name, kind, links, attrs):
        self.name = name
        self.kind = kind
        self.links = links
        self.attrs = attrs
        self.ctx = None

    def set(self, **attrs):
        self.attrs.update(attrs)

    def __enter__(self):
        self._parent = current()
        self.ctx = new_context(parent=self._parent)
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        _tls.ctx = self._prev
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        sp = {
            "name": self.name,
            "kind": self.kind,
            "trace": self.ctx.trace_id,
            "span": self.ctx.span_id,
            "parent": self._parent.span_id
            if self._parent is not None else None,
            "t0": self._t0,
            "t1": t1,
            "thread": threading.current_thread().name,
        }
        if self.links:
            sp["links"] = [l.to_dict() for l in self.links
                           if l is not None]
        if self.attrs:
            sp["attrs"] = self.attrs
        from . import recorder

        recorder.append(sp)
        return False


def span(name, kind="span", links=None, **attrs):
    """Context manager timing a block as one span; the handle exposes
    .ctx (the span's identity, for links) and .set(**attrs). Returns the
    shared no-op handle when tracing is off."""
    if not enabled():
        return _NOOP
    return _LiveSpan(name, kind, links, attrs)
