"""Flight recorder: always-on per-thread span ring buffers + dump triggers.

A black-box recorder, not a profiler session: while FLAGS_trace=1 every
span lands in the APPENDING THREAD's fixed-size ring (FLAGS_trace_buffer
spans each), so the last N spans per thread are always available with no
cross-thread contention on the hot path — appends touch only thread-local
state (list slot assignment is atomic under the GIL); the global registry
lock is taken once per thread lifetime, when its ring is created.

The recorder is read two ways:

    snapshot()            -> (spans sorted by t0, dropped_count)
    dump(reason)          -> trace_<reason>_<n>/ directory with
                             spans.jsonl + trace.json (chrome) +
                             manifest.json        (export.py formats)

maybe_dump(reason) is the anomaly hook the watchdog / NaN guard / serve
SLO paths call: per-reason cooldown (FLAGS_trace_dump_cooldown_s) so a
storm of violations produces one post-mortem, not a disk flood; a no-op
(one flag check) when tracing is off. Dumps never raise into the caller.
"""

import os
import re
import threading
import time

from .. import flags
from .. import monitor

__all__ = ["append", "snapshot", "reset", "dump", "maybe_dump",
           "last_dump"]

flags.define(
    "trace_buffer", int, 4096,
    "Flight-recorder capacity in spans PER THREAD (each recording thread "
    "owns one ring this size; older spans are overwritten and counted as "
    "dropped in the dump manifest).")
flags.define(
    "trace_dump_dir", str, "",
    "Directory flight-recorder dumps land in (trace_<reason>_<n>/ "
    "subdirectories); empty = current directory. Anomaly-triggered dumps "
    "(watchdog, NaN guard, serve SLO/overload) and `paddle_tpu trace "
    "dump` both write here unless given an explicit path.")
flags.define(
    "trace_dump_cooldown_s", float, 60.0,
    "Minimum seconds between automatic flight-recorder dumps PER trigger "
    "reason (maybe_dump) — an SLO-violation storm produces one "
    "post-mortem, not one per request. 0 = dump every trigger.")
flags.define(
    "trace_dump_keep", int, 0,
    "Retention cap on trace_<reason>_<n>/ dump directories in the dump "
    "directory: after each dump the oldest beyond this many are pruned, "
    "so a detector/anomaly storm cannot leak disk without bound. "
    "0 = keep everything.")

_lock = threading.Lock()
_rings = []          # [(thread_name, _Ring)] — grows per recording thread
_gen = [0]           # bumped by reset(): stale thread-local rings re-register
_tls = threading.local()
_dump_seq = [0]
_last_dump = [None]
_last_trigger = {}   # reason -> time.monotonic() of last accepted dump

_REASON_RE = re.compile(r"[^A-Za-z0-9_.-]+")


class _Ring:
    """Fixed-size overwrite-oldest span buffer owned by ONE thread; only
    snapshot() reads it cross-thread (GIL-consistent slot reads — a torn
    snapshot can at worst miss/duplicate the span being written)."""

    __slots__ = ("buf", "cap", "n")

    def __init__(self, cap):
        self.cap = max(16, int(cap))
        self.buf = [None] * self.cap
        self.n = 0

    def append(self, sp):
        self.buf[self.n % self.cap] = sp
        self.n += 1

    def items(self):
        if self.n <= self.cap:
            return [s for s in self.buf[:self.n] if s is not None]
        i = self.n % self.cap
        return [s for s in self.buf[i:] + self.buf[:i] if s is not None]

    def dropped(self):
        return max(0, self.n - self.cap)


def append(sp):
    """Land one span dict in the calling thread's ring (span.py's only
    entry point; callers have already passed the enabled() gate)."""
    ring = getattr(_tls, "ring", None)
    if ring is None or getattr(_tls, "gen", -1) != _gen[0]:
        ring = _Ring(flags.get("trace_buffer"))
        with _lock:
            _tls.ring = ring
            _tls.gen = _gen[0]
            _rings.append((threading.current_thread().name, ring))
    ring.append(sp)


def snapshot():
    """(spans sorted by t0, dropped span count) across every thread's
    ring — the live read the dump and the unified chrome export use."""
    with _lock:
        rings = list(_rings)
    spans, dropped = [], 0
    for _, ring in rings:
        spans.extend(ring.items())
        dropped += ring.dropped()
    spans.sort(key=lambda s: s["t0"])
    return spans, dropped


def reset():
    """Fresh recorder (tests / long-lived processes): forget every ring,
    trigger cooldowns, and the last-dump path. Threads still holding a
    stale thread-local ring re-register on their next append."""
    with _lock:
        _gen[0] += 1
        _rings.clear()
        _last_trigger.clear()
        _last_dump[0] = None


def last_dump():
    """Path of the most recent dump directory, or None."""
    return _last_dump[0]


def dump(reason="manual", out_dir=None):
    """Write the flight recorder to <out_dir>/trace_<reason>_<n>/
    (out_dir defaults to FLAGS_trace_dump_dir, then cwd) and return the
    directory path. Format: export.write_dump (spans.jsonl + chrome
    trace.json + manifest.json, with the slowest-ops table when compile
    cost attribution is available)."""
    from . import costs, export

    reason = _REASON_RE.sub("_", str(reason)) or "manual"
    spans, dropped = snapshot()
    base = out_dir or flags.get("trace_dump_dir") or "."
    with _lock:
        _dump_seq[0] += 1
        seq = _dump_seq[0]
        buffers = len(_rings)
    path = os.path.join(base, f"trace_{reason}_{seq}")
    try:
        slowest = costs.slowest_ops()
    except Exception:
        slowest = None
    export.write_dump(path, spans, reason=reason, dropped=dropped,
                      buffers=buffers, slowest_ops=slowest)
    _last_dump[0] = path
    monitor.registry().counter(
        "trace_dumps_total",
        help="flight-recorder dumps written, by trigger reason",
        reason=reason).inc()
    _prune_dumps(base)
    return path


_DUMP_DIR_RE = re.compile(r"^trace_.+_\d+$")


def _prune_dumps(base):
    """FLAGS_trace_dump_keep retention: remove the oldest trace_*_<n>/
    siblings beyond the cap. Best-effort — retention must never fail the
    dump that triggered it."""
    keep = flags.get("trace_dump_keep")
    if not keep or keep <= 0:
        return
    try:
        dirs = []
        for name in os.listdir(base):
            p = os.path.join(base, name)
            if _DUMP_DIR_RE.match(name) and os.path.isdir(p):
                dirs.append((os.path.getmtime(p), name, p))
        dirs.sort()
        for _, _, p in dirs[:max(0, len(dirs) - int(keep))]:
            import shutil

            shutil.rmtree(p, ignore_errors=True)
            monitor.registry().counter(
                "trace_dumps_pruned_total",
                help="flight-recorder dumps removed by the "
                     "FLAGS_trace_dump_keep retention cap").inc()
    except OSError:
        pass


def maybe_dump(reason):
    """Anomaly hook (watchdog fire, NaN guard trip, serve SLO violation /
    overload): dump unless tracing is off or `reason` dumped within the
    cooldown window. Never raises — a failed post-mortem must not take
    down the path that triggered it. Returns the dump path or None."""
    from .span import enabled

    if not enabled():
        return None
    cooldown = flags.get("trace_dump_cooldown_s")
    now = time.monotonic()
    with _lock:
        last = _last_trigger.get(reason)
        if last is not None and cooldown > 0 and now - last < cooldown:
            return None
        _last_trigger[reason] = now
    try:
        return dump(reason)
    except Exception:
        return None
