"""Neural-net layers (reference python/paddle/fluid/layers/nn.py — 61 layers).

Each function builds IR ops; shapes are propagated best-effort at build time
(the compiled trace is the source of truth at runtime).
"""

import math

from ..layer_helper import LayerHelper
from ..core.framework import Variable
from ..param_attr import ParamAttr
from ..initializer import Constant, Normal, Xavier
from . import tensor as tensor_layers

__all__ = [
    "fc", "embedding", "dynamic_lstm", "dynamic_lstmp", "dynamic_gru",
    "gru_unit", "lstm_unit", "cos_sim", "cross_entropy", "square_error_cost",
    "accuracy", "auc", "chunk_eval", "sequence_conv", "conv2d", "conv3d",
    "sequence_concat",
    "sequence_pool", "sequence_softmax", "softmax", "pool2d", "batch_norm",
    "layer_norm", "beam_search_decode", "conv2d_transpose", "sequence_expand",
    "beam_search", "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod", "sequence_first_step", "sequence_last_step", "dropout",
    "l2_normalize", "matmul", "topk", "warpctc", "sequence_reshape",
    "transpose", "im2sequence", "nce", "hsigmoid", "row_conv", "multiplex",
    "softmax_with_cross_entropy", "smooth_l1", "one_hot",
    "autoincreased_step_counter", "reshape", "lod_reset", "lrn", "pad",
    "label_smooth", "roi_pool", "dice_loss", "upsampling_bilinear2d",
    "random_crop", "linear_chain_crf", "crf_decoding", "edit_distance",
    "ctc_greedy_decoder", "sigmoid_cross_entropy_with_logits", "squeeze",
    "attention_lstm_decoder",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       use_mkldnn=False, act=None, is_test=False, name=None):
    """Fully connected (reference layers/nn.py:88): mul per input + sum +
    bias + act. On TPU the muls land on the MXU as one fused matmul chain."""
    helper = LayerHelper("fc", **locals())
    dtype = helper.input_dtype()
    mul_results = []
    for input_var, param_attr in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        if input_shape is None:
            raise ValueError(f"fc input {input_var.name} needs a known shape")
        param_shape = [
            int(math.prod(input_shape[num_flatten_dims:]))
        ] + [size]
        w = helper.create_parameter(param_attr, param_shape, dtype, is_bias=False)
        tmp = helper.create_tmp_variable(
            dtype, shape=tuple(input_shape[:num_flatten_dims]) + (size,),
            lod_level=input_var.lod_level,
        )
        helper.append_op(
            "mul",
            {"X": [input_var], "Y": [w]},
            {"Out": [tmp]},
            {"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_tmp_variable(
            dtype, shape=mul_results[0].shape, lod_level=mul_results[0].lod_level
        )
        helper.append_op("sum", {"X": mul_results}, {"Out": [pre_bias]})
    pre_activation = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_activation)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference layers/nn.py:199. is_sparse keeps API parity; on TPU the
    gather/scatter vjp is already sparse-update shaped."""
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(helper.param_attr, size, dtype, is_bias=False)
    out = helper.create_tmp_variable(
        dtype,
        shape=tuple(input.shape[:-1] if input.shape and input.shape[-1] == 1 else (input.shape or ()))
        + (size[1],),
        lod_level=input.lod_level,
    )
    padding_idx = (
        -1 if padding_idx is None
        else padding_idx if padding_idx >= 0 else (size[0] + padding_idx)
    )
    helper.append_op(
        "lookup_table",
        {"Ids": [input], "W": [w]},
        {"Out": [out]},
        {"is_sparse": is_sparse, "is_distributed": is_distributed, "padding_idx": padding_idx},
    )
    return out


def dynamic_lstm(input, size, param_attr=None, bias_attr=None, use_peepholes=True,
                 is_reverse=False, gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 h_0=None, c_0=None, max_len=None):
    """reference layers/nn.py:262. input: [N, 4*hidden] ragged projection."""
    helper = LayerHelper("lstm", **locals())
    size = size // 4
    weight = helper.create_parameter(helper.param_attr, shape=[size, 4 * size], dtype=dtype)
    bias_size = [1, 7 * size] if use_peepholes else [1, 4 * size]
    bias = helper.create_parameter(helper.bias_attr or ParamAttr(), shape=bias_size,
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_tmp_variable(
        dtype, shape=(-1, size), lod_level=input.lod_level)
    cell = helper.create_tmp_variable(
        dtype, shape=(-1, size), lod_level=input.lod_level)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        "lstm",
        inputs,
        {"Hidden": [hidden], "Cell": [cell]},
        {
            "use_peepholes": use_peepholes,
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
            "max_len": -1 if max_len is None else int(max_len),
        },
    )
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32", name=None, max_len=None):
    """LSTM with recurrent projection (reference layers/nn.py:408):
    composed here as dynamic_lstm + projection fc on the hidden."""
    hidden, cell = dynamic_lstm(
        input, size, param_attr, bias_attr, use_peepholes, is_reverse,
        gate_activation, cell_activation, candidate_activation, dtype, name,
        max_len=max_len,
    )
    proj = fc(hidden, proj_size, act=proj_activation, name=(name or "lstmp") + "_proj")
    return proj, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None, is_reverse=False,
                gate_activation="sigmoid", candidate_activation="tanh", h_0=None,
                max_len=None):
    """reference layers/nn.py:594. input: [N, 3*size] ragged projection."""
    helper = LayerHelper("gru", **locals())
    dtype = helper.input_dtype()
    weight = helper.create_parameter(helper.param_attr, shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(helper.bias_attr or ParamAttr(), shape=[1, 3 * size],
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_tmp_variable(
        dtype, shape=(-1, size), lod_level=input.lod_level)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        "gru",
        inputs,
        {"Hidden": [hidden]},
        {
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
            "max_len": -1 if max_len is None else int(max_len),
        },
    )
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """reference layers/nn.py:701 — single-step GRU."""
    helper = LayerHelper("gru_unit", **locals())
    dtype = helper.input_dtype()
    size = size // 3
    weight = helper.create_parameter(helper.param_attr, shape=[size, 3 * size], dtype=dtype)
    gate = helper.create_tmp_variable(dtype)
    reset_hidden_pre = helper.create_tmp_variable(dtype)
    updated_hidden = helper.create_tmp_variable(dtype, shape=hidden.shape)
    inputs = {"Input": [input], "HiddenPrev": [hidden], "Weight": [weight]}
    if helper.bias_attr:
        bias_size = [1, 3 * size]
        bias = helper.create_parameter(helper.bias_attr, shape=bias_size, dtype=dtype,
                                       is_bias=True)
        inputs["Bias"] = [bias]
    helper.append_op(
        "gru_unit",
        inputs,
        {"Gate": [gate], "ResetHiddenPrev": [reset_hidden_pre], "Hidden": [updated_hidden]},
        {"activation": activation, "gate_activation": gate_activation},
    )
    return updated_hidden, reset_hidden_pre, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0, param_attr=None,
              bias_attr=None, name=None):
    """reference layers/nn.py:1968 — fc(x,h) + lstm_unit op."""
    helper = LayerHelper("lstm_unit", **locals())
    size = cell_t_prev.shape[1]
    concat_out = tensor_layers.concat([x_t, hidden_t_prev], axis=1)
    fc_out = fc(concat_out, 4 * size, param_attr=param_attr, bias_attr=bias_attr)
    dtype = x_t.dtype
    c = helper.create_tmp_variable(dtype, shape=cell_t_prev.shape)
    h = helper.create_tmp_variable(dtype, shape=hidden_t_prev.shape)
    helper.append_op(
        "lstm_unit",
        {"X": [fc_out], "C_prev": [cell_t_prev]},
        {"C": [c], "H": [h]},
        {"forget_bias": forget_bias},
    )
    return h, c


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim", **locals())
    out = helper.create_tmp_variable(dtype=X.dtype)
    xnorm = helper.create_tmp_variable(dtype=X.dtype)
    ynorm = helper.create_tmp_variable(dtype=X.dtype)
    helper.append_op(
        "cos_sim", {"X": [X], "Y": [Y]},
        {"Out": [out], "XNorm": [xnorm], "YNorm": [ynorm]},
    )
    return out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None):
    helper = LayerHelper("dropout", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape, lod_level=x.lod_level)
    mask = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape, stop_gradient=True)
    helper.append_op(
        "dropout",
        {"X": [x]},
        {"Out": [out], "Mask": [mask]},
        {"dropout_prob": dropout_prob, "is_test": is_test, "seed": seed if seed is not None else 0},
    )
    return out


def cross_entropy(input, label, soft_label=False):
    helper = LayerHelper("cross_entropy", **locals())
    out = helper.create_tmp_variable(
        dtype=input.dtype,
        shape=tuple(input.shape[:-1]) + (1,) if input.shape else None,
    )
    helper.append_op(
        "cross_entropy",
        {"X": [input], "Label": [label]},
        {"Y": [out]},
        {"soft_label": soft_label},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype, shape=input.shape)
    helper.append_op("square_error_cost", {"X": [input], "Y": [label]}, {"Out": [out]})
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    """reference layers/metric.py accuracy: topk + accuracy op."""
    helper = LayerHelper("accuracy", **locals())
    topk_out, topk_indices = topk(input, k=k)
    acc_out = helper.create_tmp_variable(dtype="float32", shape=(), stop_gradient=True)
    if correct is None:
        correct = helper.create_tmp_variable(dtype="int32", stop_gradient=True)
    if total is None:
        total = helper.create_tmp_variable(dtype="int32", stop_gradient=True)
    helper.append_op(
        "accuracy",
        {"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        {"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200):
    helper = LayerHelper("auc", **locals())
    stat_pos = helper.create_or_get_global_variable(
        helper.name + "_stat_pos", "float32", (num_thresholds + 1,)
    )
    stat_neg = helper.create_or_get_global_variable(
        helper.name + "_stat_neg", "float32", (num_thresholds + 1,)
    )
    for v in (stat_pos, stat_neg):
        helper.set_variable_initializer(v, Constant(0.0))
    auc_out = helper.create_tmp_variable(dtype="float32", shape=(), stop_gradient=True)
    helper.append_op(
        "auc",
        {"Predict": [input], "Label": [label], "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        {"AUC": [auc_out], "StatPosOut": [stat_pos], "StatNegOut": [stat_neg]},
        {"curve": curve, "num_thresholds": num_thresholds},
    )
    return auc_out


def chunk_eval(input, label, chunk_scheme, num_chunk_types, excluded_chunk_types=None):
    helper = LayerHelper("chunk_eval", **locals())
    precision = helper.create_tmp_variable(dtype="float32", stop_gradient=True)
    recall = helper.create_tmp_variable(dtype="float32", stop_gradient=True)
    f1_score = helper.create_tmp_variable(dtype="float32", stop_gradient=True)
    num_infer_chunks = helper.create_tmp_variable(dtype="int64", stop_gradient=True)
    num_label_chunks = helper.create_tmp_variable(dtype="int64", stop_gradient=True)
    num_correct_chunks = helper.create_tmp_variable(dtype="int64", stop_gradient=True)
    helper.append_op(
        "chunk_eval",
        {"Inference": [input], "Label": [label]},
        {
            "Precision": [precision],
            "Recall": [recall],
            "F1_Score": [f1_score],
            "NumInferChunks": [num_infer_chunks],
            "NumLabelChunks": [num_label_chunks],
            "NumCorrectChunks": [num_correct_chunks],
        },
        {
            "num_chunk_types": num_chunk_types,
            "chunk_scheme": chunk_scheme,
            "excluded_chunk_types": excluded_chunk_types or [],
        },
    )
    return precision, recall, f1_score, num_infer_chunks, num_label_chunks, num_correct_chunks


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1, padding=None,
                  bias_attr=None, param_attr=None, act=None):
    helper = LayerHelper("sequence_conv", **locals())
    dtype = helper.input_dtype()
    filter_shape = [filter_size * input.shape[-1], num_filters]
    filter_param = helper.create_parameter(helper.param_attr, filter_shape, dtype)
    pre_bias = helper.create_tmp_variable(
        dtype, shape=(-1, num_filters), lod_level=input.lod_level)
    helper.append_op(
        "sequence_conv",
        {"X": [input], "Filter": [filter_param]},
        {"Out": [pre_bias]},
        {
            "contextStride": filter_stride,
            "contextStart": -int(filter_size // 2),
            "contextLength": filter_size,
        },
    )
    pre_act = helper.append_bias_op(pre_bias)
    return helper.append_activation(pre_act)


def sequence_pool(input, pool_type):
    helper = LayerHelper("sequence_pool", **locals())
    dtype = helper.input_dtype()
    pool_out = helper.create_tmp_variable(dtype, shape=input.shape)
    max_index = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op(
        "sequence_pool",
        {"X": [input]},
        {"Out": [pool_out], "MaxIndex": [max_index]},
        {"pooltype": pool_type.upper()},
    )
    return pool_out


def sequence_concat(input, axis=1, name=None):
    """reference layers/nn.py sequence_concat: join sequences feature-wise
    (axis=1, equal lod) or time-wise (axis=0, appending pairwise)."""
    helper = LayerHelper("sequence_concat", **locals())
    shape = None
    if axis == 1 and all(
            v.shape is not None and isinstance(v.shape[-1], int)
            and v.shape[-1] > 0 for v in input):
        shape = (-1, int(sum(v.shape[-1] for v in input)))
    out = helper.create_tmp_variable(dtype=helper.input_dtype(), shape=shape,
                                     lod_level=input[0].lod_level)
    helper.append_op("sequence_concat", {"X": list(input)}, {"Out": [out]},
                     {"axis": axis})
    return out


def sequence_first_step(input):
    return sequence_pool(input=input, pool_type="first")


def sequence_last_step(input):
    return sequence_pool(input=input, pool_type="last")


def sequence_softmax(input, param_attr=None, bias_attr=None, use_cudnn=True):
    helper = LayerHelper("sequence_softmax", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype, lod_level=input.lod_level)
    helper.append_op("sequence_softmax", {"X": [input]}, {"Out": [out]})
    return out


def softmax(input, param_attr=None, bias_attr=None, use_cudnn=True, name=None):
    helper = LayerHelper("softmax", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype, shape=input.shape)
    helper.append_op("softmax", {"X": [input]}, {"Out": [out]})
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           use_mkldnn=False, act=None, name=None, data_format="NCHW"):
    """reference layers/nn.py:1132. data_format (TPU extension): "NCHW"
    (reference default) or "NHWC" activations; filters stay OIHW in both so
    parameters are layout-independent."""
    helper = LayerHelper("conv2d", **locals())
    dtype = helper.input_dtype()
    nhwc = data_format == "NHWC"
    num_channels = input.shape[-1 if nhwc else 1]
    if groups is None:
        num_filter_channels = num_channels
        groups = 1
    else:
        if num_channels % groups != 0:
            raise ValueError("num_channels must be divisible by groups")
        num_filter_channels = num_channels // groups

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    filter_shape = [num_filters, num_filter_channels] + filter_size

    def _default_param_initializer(*_):
        std = (2.0 / (filter_size[0] ** 2 * num_channels)) ** 0.5
        return Normal(0.0, std, 0)

    h_ax, w_ax = (1, 2) if nhwc else (2, 3)
    pre_bias_shape = None
    if input.shape and None not in (input.shape[h_ax], input.shape[w_ax]):
        oh = (input.shape[h_ax] + 2 * padding[0] - (dilation[0] * (filter_size[0] - 1) + 1)) // stride[0] + 1
        ow = (input.shape[w_ax] + 2 * padding[1] - (dilation[1] * (filter_size[1] - 1) + 1)) // stride[1] + 1
        pre_bias_shape = (input.shape[0], oh, ow, num_filters) if nhwc \
            else (input.shape[0], num_filters, oh, ow)

    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=_default_param_initializer(),
    )
    pre_bias = helper.create_tmp_variable(dtype, shape=pre_bias_shape)
    helper.append_op(
        "conv2d",
        {"Input": [input], "Filter": [filter_param]},
        {"Output": [pre_bias]},
        {
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "use_cudnn": use_cudnn,
            "data_format": data_format,
        },
    )
    if nhwc:
        pre_act = helper.append_bias_op(pre_bias, dim_start=3, dim_end=4)
    else:
        pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1

    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v, v]

    filter_size = _triple(filter_size)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    filter_param = helper.create_parameter(helper.param_attr, filter_shape, dtype)
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(
        "conv3d",
        {"Input": [input], "Filter": [filter_param]},
        {"Output": [pre_bias]},
        {
            "strides": _triple(stride),
            "paddings": _triple(padding),
            "dilations": _triple(dilation),
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False, use_mkldnn=False,
           name=None, data_format="NCHW"):
    """reference layers/nn.py:1441. data_format: NCHW (default) or NHWC."""
    if pool_type not in ["max", "avg"]:
        raise ValueError(f"Unknown pool_type {pool_type}")
    helper = LayerHelper("pool2d", **locals())
    dtype = helper.input_dtype()
    nhwc = data_format == "NHWC"
    h_ax, w_ax, c_ax = (1, 2, 3) if nhwc else (2, 3, 1)

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    pool_size = _pair(pool_size)
    pool_stride = _pair(pool_stride)
    pool_padding = _pair(pool_padding)
    shape = None
    if input.shape and None not in (input.shape[h_ax], input.shape[w_ax]) \
            and not global_pooling:
        rnd = math.ceil if ceil_mode else math.floor
        oh = int(rnd((input.shape[h_ax] + 2 * pool_padding[0] - pool_size[0]) / pool_stride[0])) + 1
        ow = int(rnd((input.shape[w_ax] + 2 * pool_padding[1] - pool_size[1]) / pool_stride[1])) + 1
        shape = (input.shape[0], oh, ow, input.shape[c_ax]) if nhwc \
            else (input.shape[0], input.shape[c_ax], oh, ow)
    elif global_pooling and input.shape:
        shape = (input.shape[0], 1, 1, input.shape[c_ax]) if nhwc \
            else (input.shape[0], input.shape[c_ax], 1, 1)
    pool_out = helper.create_tmp_variable(dtype, shape=shape)
    helper.append_op(
        "pool2d",
        {"X": [input]},
        {"Out": [pool_out]},
        {
            "pooling_type": pool_type,
            "ksize": pool_size,
            "global_pooling": global_pooling,
            "strides": pool_stride,
            "paddings": pool_padding,
            "use_cudnn": use_cudnn,
            "ceil_mode": ceil_mode,
            "data_format": data_format,
        },
    )
    return pool_out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout="NCHW", in_place=False,
               use_mkldnn=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False):
    """reference layers/nn.py:1494."""
    helper = LayerHelper("batch_norm", **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    if data_layout == "NCHW":
        channel_num = input_shape[1]
    elif data_layout == "NHWC":
        channel_num = input_shape[-1]
    else:
        raise ValueError("unsupported data layout:" + data_layout)
    param_shape = [channel_num]

    scale = helper.create_parameter(
        attr=helper.param_attr, shape=param_shape, dtype=dtype,
        default_initializer=Constant(1.0),
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr or ParamAttr(), shape=param_shape, dtype=dtype, is_bias=True
    )
    mean = helper.create_parameter(
        attr=ParamAttr(
            name=moving_mean_name, initializer=Constant(0.0), trainable=False,
            do_model_average=do_model_average_for_mean_and_var,
        ),
        shape=param_shape, dtype=dtype,
    )
    mean.stop_gradient = True
    variance = helper.create_parameter(
        attr=ParamAttr(
            name=moving_variance_name, initializer=Constant(1.0), trainable=False,
            do_model_average=do_model_average_for_mean_and_var,
        ),
        shape=param_shape, dtype=dtype,
    )
    variance.stop_gradient = True

    saved_mean = helper.create_tmp_variable(dtype=dtype, stop_gradient=True)
    saved_variance = helper.create_tmp_variable(dtype=dtype, stop_gradient=True)
    batch_norm_out = input if in_place else helper.create_tmp_variable(dtype, shape=input.shape)
    helper.append_op(
        "batch_norm",
        {
            "X": [input], "Scale": [scale], "Bias": [bias],
            "Mean": [mean], "Variance": [variance],
        },
        {
            "Y": [batch_norm_out], "MeanOut": [mean], "VarianceOut": [variance],
            "SavedMean": [saved_mean], "SavedVariance": [saved_variance],
        },
        {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
         "data_layout": data_layout},
    )
    return helper.append_activation(batch_norm_out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-05,
               param_attr=None, bias_attr=None, act=None, name=None):
    """reference layers/nn.py:1592."""
    helper = LayerHelper("layer_norm", **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    param_shape = [int(math.prod(input_shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        scale_p = helper.create_parameter(
            attr=helper.param_attr, shape=param_shape, dtype=dtype,
            default_initializer=Constant(1.0),
        )
        inputs["Scale"] = [scale_p]
    if shift:
        bias_p = helper.create_parameter(
            attr=helper.bias_attr or ParamAttr(), shape=param_shape, dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [bias_p]
    mean_out = helper.create_tmp_variable(dtype=dtype, stop_gradient=True)
    variance_out = helper.create_tmp_variable(dtype=dtype, stop_gradient=True)
    layer_norm_out = helper.create_tmp_variable(dtype, shape=input.shape)
    helper.append_op(
        "layer_norm",
        inputs,
        {"Y": [layer_norm_out], "Mean": [mean_out], "Variance": [variance_out]},
        {"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(layer_norm_out)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, use_cudnn=True, act=None, name=None):
    """reference layers/nn.py:1705."""
    helper = LayerHelper("conv2d_transpose", **locals())
    if not isinstance(input, Variable):
        raise TypeError("Input of conv2d_transpose must be Variable")
    input_channel = input.shape[1]

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    padding = _pair(padding)
    stride = _pair(stride)
    dilation = _pair(dilation)
    if filter_size is None:
        if output_size is None:
            raise ValueError("output_size must be set when filter_size is None")
        output_size = _pair(output_size)
        h_in, w_in = input.shape[2], input.shape[3]
        filter_size_h = (output_size[0] - (h_in - 1) * stride[0] + 2 * padding[0] - 1) // dilation[0] + 1
        filter_size_w = (output_size[1] - (w_in - 1) * stride[1] + 2 * padding[1] - 1) // dilation[1] + 1
        filter_size = [filter_size_h, filter_size_w]
    else:
        filter_size = _pair(filter_size)
    filter_shape = [input_channel, num_filters] + filter_size
    img_filter = helper.create_parameter(dtype=input.dtype, shape=filter_shape,
                                         attr=helper.param_attr)
    pre_bias = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        "conv2d_transpose",
        {"Input": [input], "Filter": [img_filter]},
        {"Output": [pre_bias]},
        {"strides": stride, "paddings": padding, "dilations": dilation, "use_cudnn": use_cudnn},
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype, lod_level=max(1, y.lod_level))
    helper.append_op(
        "sequence_expand", {"X": [x], "Y": [y]}, {"Out": [out]}, {"ref_level": ref_level}
    )
    return out


def beam_search(pre_ids, ids, scores, beam_size, end_id, level=0,
                pre_scores=None, return_parents=False):
    """reference layers/nn.py:1936 — one beam-search step over beams
    (ops/beam_search_ops.py: dense [B*beam_size] slots instead of 2-level
    LoD; pass pre_scores for exact finished-beam carry, request
    return_parents to drive beam_search_decode's backtrack)."""
    helper = LayerHelper("beam_search", **locals())
    selected_scores = helper.create_tmp_variable(dtype=scores.dtype, lod_level=2)
    selected_ids = helper.create_tmp_variable(
        dtype=ids.dtype if ids is not None else "int64", lod_level=2)
    parent_idx = helper.create_tmp_variable(dtype="int64", stop_gradient=True)
    inputs = {"pre_ids": [pre_ids], "scores": [scores]}
    if ids is not None:
        inputs["ids"] = [ids]
    if pre_scores is not None:
        inputs["pre_scores"] = [pre_scores]
    helper.append_op(
        "beam_search",
        inputs,
        {"selected_ids": [selected_ids], "selected_scores": [selected_scores],
         "parent_idx": [parent_idx]},
        {"level": level, "beam_size": beam_size, "end_id": end_id},
    )
    if return_parents:
        return selected_ids, selected_scores, parent_idx
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, name=None, parents=None, end_id=-1):
    helper = LayerHelper("beam_search_decode", **locals())
    sentence_ids = helper.create_tmp_variable(dtype=ids.dtype, lod_level=2)
    sentence_scores = helper.create_tmp_variable(dtype=scores.dtype, lod_level=2)
    inputs = {"Ids": [ids], "Scores": [scores]}
    if parents is not None:
        inputs["Parents"] = [parents]
    helper.append_op(
        "beam_search_decode",
        inputs,
        {"SentenceIds": [sentence_ids], "SentenceScores": [sentence_scores]},
        {"end_id": end_id},
    )
    return sentence_ids, sentence_scores


def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, name=name)
    shape = None
    if input.shape is not None:
        if dim is None:
            shape = ()
        else:
            dims = [dim] if isinstance(dim, int) else list(dim)
            dims = [d % len(input.shape) for d in dims]
            shape = tuple(
                (1 if keep_dim else None) if i in dims else s
                for i, s in enumerate(input.shape)
            )
            shape = tuple(s for s in shape if s is not None) if not keep_dim else shape
    out = helper.create_tmp_variable(dtype=input.dtype, shape=shape)
    helper.append_op(
        op_type,
        {"X": [input]},
        {"Out": [out]},
        {
            "dim": dim if dim is not None else 0,
            "keep_dim": keep_dim,
            "reduce_all": dim is None,
        },
    )
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    """reference layers/nn.py:2425 via the norm op composition."""
    if len(x.shape) == 1:
        axis = 0
    helper = LayerHelper("l2_normalize", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape)
    norm = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        "norm", {"X": [x]}, {"Out": [out], "Norm": [norm]},
        {"axis": 1 if axis is None else axis, "epsilon": epsilon},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    helper = LayerHelper("matmul", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        "matmul",
        {"X": [x], "Y": [y]},
        {"Out": [out]},
        {"transpose_X": transpose_x, "transpose_Y": transpose_y},
    )
    return out


def topk(input, k):
    helper = LayerHelper("top_k", **locals())
    values = helper.create_tmp_variable(dtype=input.dtype)
    indices = helper.create_tmp_variable(dtype="int64", stop_gradient=True)
    helper.append_op(
        "top_k", {"X": [input]}, {"Out": [values], "Indices": [indices]}, {"k": k}
    )
    values.stop_gradient = True
    return values, indices


def warpctc(input, label, blank=0, norm_by_times=False):
    """reference layers/nn.py:2813 — CTC loss (ops/ctc_ops.py)."""
    helper = LayerHelper("warpctc", **locals())
    loss_out = helper.create_tmp_variable(dtype=input.dtype)
    grad_out = helper.create_tmp_variable(dtype=input.dtype, stop_gradient=True)
    helper.append_op(
        "warpctc",
        {"Logits": [input], "Label": [label]},
        {"WarpCTCGrad": [grad_out], "Loss": [loss_out]},
        {"blank": blank, "norm_by_times": norm_by_times},
    )
    return loss_out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype, lod_level=1)
    helper.append_op(
        "sequence_reshape", {"X": [input]}, {"Out": [out]}, {"new_dim": new_dim}
    )
    return out


def transpose(x, perm, name=None):
    if len(perm) != len(x.shape or perm):
        raise ValueError("perm length must match input rank")
    helper = LayerHelper("transpose", **locals())
    shape = tuple(x.shape[p] for p in perm) if x.shape else None
    out = helper.create_tmp_variable(dtype=x.dtype, shape=shape)
    helper.append_op("transpose", {"X": [x]}, {"Out": [out]}, {"axis": list(perm)})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", **locals())

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    padding = padding if isinstance(padding, (list, tuple)) else [padding] * 4
    if len(padding) == 2:
        padding = [padding[0], padding[1], padding[0], padding[1]]
    out = helper.create_tmp_variable(dtype=input.dtype, lod_level=1)
    helper.append_op(
        "im2sequence",
        {"X": [input]},
        {"Out": [out]},
        {"kernels": _pair(filter_size), "strides": _pair(stride), "paddings": list(padding)},
    )
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", **locals())
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[-1]]
    filter_param = helper.create_parameter(helper.param_attr, filter_shape, dtype)
    out = helper.create_tmp_variable(
        dtype, shape=tuple(input.shape), lod_level=input.lod_level)
    helper.append_op("row_conv", {"X": [input], "Filter": [filter_param]}, {"Out": [out]})
    return helper.append_activation(out)


def multiplex(inputs, index):
    helper = LayerHelper("multiplex", **locals())
    if not isinstance(inputs, list) or len(inputs) < 2:
        raise ValueError("inputs should be a list of at least 2 variables")
    out = helper.create_tmp_variable(dtype=inputs[0].dtype, shape=inputs[0].shape)
    helper.append_op("multiplex", {"X": inputs, "Ids": [index]}, {"Out": [out]})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False):
    helper = LayerHelper("softmax_with_cross_entropy", **locals())
    softmax_v = helper.create_tmp_variable(dtype=logits.dtype, shape=logits.shape)
    loss = helper.create_tmp_variable(
        dtype=logits.dtype,
        shape=tuple(logits.shape[:-1]) + (1,) if logits.shape else None,
    )
    helper.append_op(
        "softmax_with_cross_entropy",
        {"Logits": [logits], "Label": [label]},
        {"Softmax": [softmax_v], "Loss": [loss]},
        {"soft_label": soft_label},
    )
    return loss


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1", **locals())
    diff = helper.create_tmp_variable(dtype=x.dtype)
    loss = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        "smooth_l1_loss",
        {
            "X": [x], "Y": [y],
            "InsideWeight": [inside_weight] if inside_weight is not None else [],
            "OutsideWeight": [outside_weight] if outside_weight is not None else [],
        },
        {"Diff": [diff], "Out": [loss]},
        {"sigma": sigma if sigma is not None else 1.0},
    )
    return loss


def one_hot(input, depth):
    return tensor_layers.one_hot(input, depth)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference layers/nn.py:3410 — persistable global step counter."""
    helper = LayerHelper("global_step_counter")
    counter_name = counter_name or "@STEP_COUNTER@"
    counter = helper.create_or_get_global_variable(
        name=counter_name, dtype="int64", shape=(1,), persistable=True
    )
    if not getattr(counter, "_step_counter_initialized", False):
        helper.set_variable_initializer(counter, Constant(value=begin - 1))
        helper.main_program.global_block().prepend_op(
            "increment", {"X": [counter]}, {"Out": [counter]}, {"step": float(step)}
        )
        counter._step_counter_initialized = True
        counter.stop_gradient = True
    return counter


def reshape(x, shape, actual_shape=None, act=None, inplace=True, name=None):
    helper = LayerHelper("reshape", **locals())
    known = None
    if x.shape is not None and -1 not in shape and 0 not in shape:
        known = tuple(shape)
    elif x.shape is not None:
        for i, s in enumerate(shape):
            if s == 0 and i >= len(x.shape):
                raise ValueError(
                    f"reshape: 0 at position {i} has no input dim to copy "
                    f"(input rank {len(x.shape)})")
        spec = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
        neg = [i for i, s in enumerate(spec) if s == -1]
        if len(neg) == 1 and None not in x.shape and -1 not in x.shape:
            # fully-static input: resolve the single -1 exactly
            total = int(math.prod([s for s in x.shape]))
            rest = int(math.prod([s for s in spec if s != -1]))
            spec[neg[0]] = total // rest if rest else -1
            known = tuple(spec)
        elif neg == [0]:
            # dynamic input: only a LEADING -1 may stay (the house batch
            # sentinel every shape consumer understands); a non-batch -1
            # left unresolved would leak into fc's size products
            known = tuple(spec)
    out = helper.create_tmp_variable(dtype=x.dtype, shape=known)
    resolved = list(known) if known is not None else list(shape)
    helper.append_op("reshape", {"X": [x]}, {"Out": [out]}, {"shape": resolved})
    return helper.append_activation(out)


def squeeze(input, axes, name=None):
    shape = [s for i, s in enumerate(input.shape) if i not in axes] if input.shape else None
    return reshape(input, shape)


def lod_reset(x, y=None, target_lod=None):
    helper = LayerHelper("lod_reset", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype, lod_level=1)
    if y is not None:
        helper.append_op("lod_reset", {"X": [x], "Y": [y]}, {"Out": [out]})
    elif target_lod is not None:
        helper.append_op("lod_reset", {"X": [x]}, {"Out": [out]}, {"target_lod": list(target_lod)})
    else:
        raise ValueError("how to set LoD?")
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", **locals())
    if len(input.shape) != 4:
        raise ValueError("Input's dimension size of Op(lrn) must be 4")
    mid_out = helper.create_tmp_variable(dtype=input.dtype, stop_gradient=True)
    lrn_out = helper.create_tmp_variable(dtype=input.dtype, shape=input.shape)
    helper.append_op(
        "lrn",
        {"X": [input]},
        {"Out": [lrn_out], "MidOut": [mid_out]},
        {"n": n, "k": k, "alpha": alpha, "beta": beta},
    )
    return lrn_out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        "pad", {"X": [x]}, {"Out": [out]}, {"paddings": list(paddings), "pad_value": float(pad_value)}
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    if epsilon > 1.0 or epsilon < 0.0:
        raise ValueError("The value of epsilon must be between 0 and 1.")
    helper = LayerHelper("label_smooth", **locals())
    smooth_label = helper.create_tmp_variable(dtype=dtype, shape=label.shape)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op("label_smooth", inputs, {"Out": [smooth_label]}, {"epsilon": float(epsilon)})
    return smooth_label


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0):
    helper = LayerHelper("roi_pool", **locals())
    dtype = helper.input_dtype()
    pool_out = helper.create_tmp_variable(dtype)
    argmaxes = helper.create_tmp_variable(dtype="int64", stop_gradient=True)
    helper.append_op(
        "roi_pool",
        {"X": [input], "ROIs": [rois]},
        {"Out": [pool_out], "Argmax": [argmaxes]},
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale},
    )
    return pool_out


def dice_loss(input, label, epsilon=1e-5):
    """reference layers/nn.py:3878 — composed from primitive layers."""
    from . import ops as ops_layers

    label = tensor_layers.one_hot(label, depth=input.shape[-1])
    reduce_dim = list(range(1, len(input.shape)))
    inse = reduce_sum(input * label, dim=reduce_dim)
    dice_denominator = reduce_sum(input, dim=reduce_dim) + reduce_sum(label, dim=reduce_dim)
    dice_score = 1 - inse * 2 / (dice_denominator + epsilon)
    from .ops import mean as _mean

    return _mean(dice_score)


def upsampling_bilinear2d(input, out_shape=None, scale=None, name=None):
    helper = LayerHelper("bilinear_interp", **locals())
    if out_shape is None and scale is None:
        raise ValueError("One of out_shape and scale must not be None")
    if out_shape is not None:
        out_h, out_w = out_shape
    else:
        out_h = int(input.shape[2] * scale)
        out_w = int(input.shape[3] * scale)
    out = helper.create_tmp_variable(
        dtype=input.dtype,
        shape=(input.shape[0], input.shape[1], out_h, out_w) if input.shape else None,
    )
    helper.append_op(
        "bilinear_interp", {"X": [input]}, {"Out": [out]}, {"out_h": out_h, "out_w": out_w}
    )
    return out


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        "random_crop", {"X": [x]}, {"Out": [out]},
        {"shape": list(shape), "seed": seed if seed is not None else 0},
    )
    return out


def linear_chain_crf(input, label, param_attr=None):
    """reference layers/nn.py:799 — CRF negative log-likelihood loss."""
    helper = LayerHelper("linear_chain_crf", **locals())
    size = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[size + 2, size], dtype=helper.input_dtype()
    )
    alpha = helper.create_tmp_variable(dtype=helper.input_dtype(), stop_gradient=True)
    emission_exps = helper.create_tmp_variable(dtype=helper.input_dtype(), stop_gradient=True)
    transition_exps = helper.create_tmp_variable(dtype=helper.input_dtype(), stop_gradient=True)
    log_likelihood = helper.create_tmp_variable(dtype=helper.input_dtype())
    helper.append_op(
        "linear_chain_crf",
        {"Emission": [input], "Transition": [transition], "Label": [label]},
        {
            "Alpha": [alpha],
            "EmissionExps": [emission_exps],
            "TransitionExps": [transition_exps],
            "LogLikelihood": [log_likelihood],
        },
    )
    return log_likelihood


def crf_decoding(input, param_attr, label=None):
    helper = LayerHelper("crf_decoding", **locals())
    transition = helper.get_parameter(param_attr.name)
    viterbi_path = helper.create_tmp_variable(dtype="int64", lod_level=input.lod_level,
                                              stop_gradient=True)
    helper.append_op(
        "crf_decoding",
        {"Emission": [input], "Transition": [transition]}
        | ({"Label": [label]} if label is not None else {}),
        {"ViterbiPath": [viterbi_path]},
    )
    return viterbi_path


def edit_distance(input, label, normalized=True, ignored_tokens=None, name=None):
    helper = LayerHelper("edit_distance", **locals())
    if ignored_tokens:
        erased_input = helper.create_tmp_variable(dtype=input.dtype, lod_level=1)
        erased_label = helper.create_tmp_variable(dtype=label.dtype, lod_level=1)
        helper.append_op(
            "sequence_erase", {"X": [input]}, {"Out": [erased_input]},
            {"tokens": list(ignored_tokens)},
        )
        helper.append_op(
            "sequence_erase", {"X": [label]}, {"Out": [erased_label]},
            {"tokens": list(ignored_tokens)},
        )
        input, label = erased_input, erased_label
    edit_distance_out = helper.create_tmp_variable(dtype="float32", stop_gradient=True)
    sequence_num = helper.create_tmp_variable(dtype="int64", stop_gradient=True)
    helper.append_op(
        "edit_distance",
        {"Hyps": [input], "Refs": [label]},
        {"Out": [edit_distance_out], "SequenceNum": [sequence_num]},
        {"normalized": normalized},
    )
    return edit_distance_out, sequence_num


def ctc_greedy_decoder(input, blank, name=None):
    """reference layers/nn.py:2741 — argmax + merge repeats + drop blanks."""
    helper = LayerHelper("ctc_greedy_decoder", **locals())
    _, topk_indices = topk(input, k=1)
    ctc_out = helper.create_tmp_variable(dtype="int64", lod_level=1, stop_gradient=True)
    helper.append_op(
        "ctc_align", {"Input": [topk_indices]}, {"Output": [ctc_out]},
        {"merge_repeated": True, "blank": blank},
    )
    return ctc_out


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape)
    helper.append_op(
        "sigmoid_cross_entropy_with_logits",
        {"X": [x], "Label": [label]},
        {"Out": [out]},
    )
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None):
    """reference layers/nn.py:2923 — noise contrastive estimation."""
    helper = LayerHelper("nce", **locals())
    dim = input.shape[1]
    num_true_class = label.shape[1] if label.shape and len(label.shape) > 1 else 1
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_total_classes, dim],
        dtype=input.dtype,
    )
    b = helper.create_parameter(
        attr=helper.bias_attr or ParamAttr(), shape=[num_total_classes, 1],
        dtype=input.dtype, is_bias=True,
    )
    cost = helper.create_tmp_variable(dtype=input.dtype)
    sample_logits = helper.create_tmp_variable(dtype=input.dtype, stop_gradient=True)
    sample_labels = helper.create_tmp_variable(dtype=label.dtype, stop_gradient=True)
    num_neg_samples = 10 if num_neg_samples is None else int(num_neg_samples)
    helper.append_op(
        "nce",
        {
            "Input": [input], "Label": [label], "Weight": [w], "Bias": [b],
            "SampleWeight": [sample_weight] if sample_weight is not None else [],
        },
        {"Cost": [cost], "SampleLogits": [sample_logits], "SampleLabels": [sample_labels]},
        {"num_total_classes": int(num_total_classes), "num_neg_samples": num_neg_samples},
    )
    return cost / (num_neg_samples + 1)


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None):
    """Hierarchical sigmoid (reference hierarchical_sigmoid_op)."""
    helper = LayerHelper("hierarchical_sigmoid", **locals())
    dim = input.shape[1]
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_classes - 1, dim], dtype=input.dtype
    )
    b = helper.create_parameter(
        attr=helper.bias_attr or ParamAttr(), shape=[num_classes - 1, 1],
        dtype=input.dtype, is_bias=True,
    )
    out = helper.create_tmp_variable(dtype=input.dtype)
    pre_out = helper.create_tmp_variable(dtype=input.dtype, stop_gradient=True)
    helper.append_op(
        "hierarchical_sigmoid",
        {"X": [input], "W": [w], "Label": [label], "Bias": [b]},
        {"Out": [out], "PreOut": [pre_out]},
        {"num_classes": num_classes},
    )
    return out


def attention_lstm_decoder(target_embedding, encoder_vec, encoder_proj,
                           decoder_boot, decoder_size, target_dict_dim,
                           param_attr=None, dtype="float32", name=None,
                           max_target_len=None, max_source_len=None):
    """Teacher-forced attention LSTM decoder over a ragged target sequence —
    fused-scan replacement for the reference's DynamicRNN decoder
    (benchmark/fluid/models/machine_translation.py:104-152)."""
    import copy as _copy

    helper = LayerHelper("attention_lstm_decoder", **locals())
    emb_dim = target_embedding.shape[-1]
    enc_dim = encoder_vec.shape[-1]
    d = decoder_size

    def _attr():
        # distinct copy per parameter: create_parameter mutates attr.name
        return _copy.deepcopy(helper.param_attr)

    w_att_state = helper.create_parameter(
        _attr(), shape=[d, d], dtype=dtype)
    w_att_score = helper.create_parameter(
        _attr(), shape=[2 * d, 1], dtype=dtype)
    w_step = helper.create_parameter(
        _attr(), shape=[d + enc_dim + emb_dim, 4 * d], dtype=dtype)
    b_step = helper.create_parameter(
        ParamAttr(), shape=[1, 4 * d], dtype=dtype, is_bias=True)
    w_out = helper.create_parameter(
        _attr(), shape=[d, target_dict_dim], dtype=dtype)
    b_out = helper.create_parameter(
        ParamAttr(), shape=[1, target_dict_dim], dtype=dtype, is_bias=True)
    pred = helper.create_tmp_variable(
        dtype, lod_level=target_embedding.lod_level)
    helper.append_op(
        "attention_lstm_decoder",
        {
            "TargetEmb": [target_embedding],
            "EncoderVec": [encoder_vec],
            "EncoderProj": [encoder_proj],
            "DecoderBoot": [decoder_boot],
            "WAttState": [w_att_state],
            "WAttScore": [w_att_score],
            "WStep": [w_step],
            "BStep": [b_step],
            "WOut": [w_out],
            "BOut": [b_out],
        },
        {"Out": [pred]},
        {
            "max_target_len": -1 if max_target_len is None else int(max_target_len),
            "max_source_len": -1 if max_source_len is None else int(max_source_len),
        },
    )
    pred.shape = (-1, target_dict_dim)
    return pred
