"""IO layers (reference python/paddle/fluid/layers/io.py): data, ListenAndServ,
Send/Recv, reader creation + decorators."""

from ..layer_helper import LayerHelper
from ..core.framework import Variable, VarType, default_main_program, default_startup_program
from .. import unique_name

__all__ = [
    "data", "BlockGuardServ", "ListenAndServ", "Send", "Recv",
    "open_recordio_file", "open_files", "open_datapipe", "read_file",
    "shuffle", "batch", "double_buffer", "multi_pass",
    "random_data_generator",
]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=VarType.LOD_TENSOR, stop_gradient=True):
    """reference layers/io.py:30."""
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    for i in range(len(shape)):
        if shape[i] is None:
            shape[i] = -1
            append_batch_size = False
        elif shape[i] < 0:
            append_batch_size = False
    if append_batch_size:
        shape = [-1] + shape
    return helper.create_global_variable(
        name=name,
        shape=shape,
        dtype=dtype,
        type=type,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
        is_data=True,
    )


class BlockGuardServ:
    """reference layers/io.py BlockGuardServ."""

    def __init__(self, server):
        if not isinstance(server, ListenAndServ):
            raise TypeError("BlockGuardServ takes a ListenAndServ")
        self.server = server
        self.main_program = server.helper.main_program

    def __enter__(self):
        self.main_program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        sub_block = self.main_program.current_block()
        self.main_program.rollback()
        self.server.complete_op(sub_block)
        return True


class ListenAndServ:
    """reference layers/io.py:109 — pserver-side blocking service op."""

    def __init__(self, endpoint, inputs, fan_in=1, optimizer_mode=True):
        self.helper = LayerHelper("listen_and_serv")
        self.inputs = inputs
        self.outputs = []
        self.endpoint = endpoint
        self.fan_in = fan_in

    def do(self):
        return BlockGuardServ(self)

    def get_params_and_grads(self):
        main_program = self.helper.main_program
        current_block = main_program.current_block()
        params, grads = [], []
        for op in current_block.ops:
            if "Grad" in op.inputs and "Param" in op.inputs:
                params.append(op.input("Param")[0])
                grads.append(op.input("Grad")[0])
        return params, grads

    def complete_op(self, sub_block):
        main_program = self.helper.main_program
        current_block = main_program.current_block()
        params, grads = [], []
        for op in sub_block.ops:
            if "Grad" in op.inputs and "Param" in op.inputs:
                params.append(op.input("Param")[0])
                grads.append(op.input("Grad")[0])
        current_block.append_op(
            "listen_and_serv",
            {"X": self.inputs},
            {},
            {
                "endpoint": self.endpoint,
                "Fanin": self.fan_in,
                "OptimizeBlock": sub_block,
                "ParamList": params,
                "GradList": grads,
            },
        )


def Send(endpoints, send_vars, get_vars=None):
    """reference layers/io.py:179 — send vars to pservers + fetch results."""
    assert isinstance(send_vars, list)
    epmap = endpoints.split(",")
    endpoints = list(set(epmap))
    helper = LayerHelper("Send", **locals())
    if not get_vars:
        get_vars = []
    helper.append_op(
        "send",
        {"X": send_vars},
        {"Out": get_vars},
        {"endpoints": endpoints, "epmap": epmap},
    )
    return get_vars


def Recv(endpoints, get_vars):
    """reference layers/io.py:218."""
    assert isinstance(get_vars, list)
    epmap = endpoints.split(",")
    endpoints = list(set(epmap))
    helper = LayerHelper("Recv", **locals())
    helper.append_op(
        "recv", {"X": get_vars}, {"Out": get_vars},
        {"endpoints": endpoints, "epmap": epmap},
    )
    return get_vars


# ---------------------------------------------------------------------------
# Readers-as-variables (reference layers/io.py:294+, operators/reader/)
# ---------------------------------------------------------------------------
def _create_reader_var(name, feed_shapes, dtypes_, lod_levels):
    main = default_main_program()
    var = main.global_block().create_var(name=name, type=VarType.READER, persistable=True)
    var._reader_meta = {
        "shapes": feed_shapes,
        "dtypes": dtypes_,
        "lod_levels": lod_levels,
    }
    return var


def open_recordio_file(filename, shapes, lod_levels, dtypes,
                       pass_num=1, for_parallel=False):
    """reference layers/io.py open_recordio_file — creates a file reader var."""
    helper = LayerHelper("open_recordio_file")
    name = unique_name.generate("recordio_reader")
    var = _create_reader_var(name, shapes, dtypes, lod_levels)
    startup = default_startup_program()
    startup.global_block().create_var(name=name, type=VarType.READER, persistable=True)
    startup.global_block().append_op(
        "create_recordio_file_reader",
        {},
        {"Out": [name]},
        {
            "filename": filename,
            "shapes": [list(s) for s in shapes],
            "dtypes": list(dtypes),
            "lod_levels": list(lod_levels),
            "pass_num": pass_num,
        },
    )
    return var


def open_files(filenames, shapes, lod_levels, dtypes, thread_num=1, buffer_size=None,
               pass_num=1, for_parallel=False):
    helper = LayerHelper("open_files")
    name = unique_name.generate("multi_file_reader")
    var = _create_reader_var(name, shapes, dtypes, lod_levels)
    startup = default_startup_program()
    startup.global_block().create_var(name=name, type=VarType.READER, persistable=True)
    startup.global_block().append_op(
        "open_files",
        {},
        {"Out": [name]},
        {
            "filenames": list(filenames),
            "shapes": [list(s) for s in shapes],
            "dtypes": list(dtypes),
            "lod_levels": list(lod_levels),
            "thread_num": thread_num,
            "pass_num": pass_num,
        },
    )
    return var


def open_datapipe(pipe, slot_names, shapes, dtypes, lod_levels=None):
    """Expose a datapipe.DataPipe as a reader VARIABLE, so read_file and
    the rest of the reader-op surface consume the prefetching pipeline.
    Each pipeline item (a {name: array} dict — usually pipe.batch() output)
    becomes one read, slots ordered by slot_names. The live pipe cannot be
    serialized into op attrs, so it is parked in a process-local registry
    and the creation op carries an integer token (programs using this op
    are not portable across processes)."""
    if lod_levels is None:
        lod_levels = [0] * len(slot_names)
    if not (len(slot_names) == len(shapes) == len(dtypes) == len(lod_levels)):
        raise ValueError(
            f"slot_names/shapes/dtypes/lod_levels lengths differ: "
            f"{len(slot_names)}/{len(shapes)}/{len(dtypes)}/"
            f"{len(lod_levels)}")
    from ..ops.reader_ops import register_datapipe

    helper = LayerHelper("open_datapipe")
    name = unique_name.generate("datapipe_reader")
    var = _create_reader_var(name, shapes, dtypes, lod_levels)
    startup = default_startup_program()
    startup.global_block().create_var(name=name, type=VarType.READER, persistable=True)
    startup.global_block().append_op(
        "create_datapipe_reader",
        {},
        {"Out": [name]},
        {
            "token": register_datapipe(pipe),
            "slot_names": list(slot_names),
            "shapes": [list(s) for s in shapes],
            "dtypes": list(dtypes),
            "lod_levels": list(lod_levels),
        },
    )
    return var


def _decorate_reader(op_type, reader, attrs=None):
    helper = LayerHelper(op_type)
    name = unique_name.generate(op_type)
    main = default_main_program()
    new_var = main.global_block().create_var(
        name=name, type=VarType.READER, persistable=True
    )
    new_var._reader_meta = getattr(reader, "_reader_meta", None)
    main.global_block().append_op(
        "create_" + op_type, {"UnderlyingReader": [reader]}, {"Out": [new_var]}, attrs or {}
    )
    return new_var


def shuffle(reader, buffer_size):
    return _decorate_reader("shuffle_reader", reader, {"buffer_size": buffer_size})


def batch(reader, batch_size):
    return _decorate_reader("batch_reader", reader, {"batch_size": batch_size})


def double_buffer(reader, place=None, name=None):
    """reference create_double_buffer_reader_op.cc:34 — a prefetch thread
    stages upcoming batches into DEVICE memory (jax.device_put off the
    compute path). `place` pins the staging device; default: the Executor's
    place at run time."""
    attrs = {}
    if place is not None:
        from ..core.places import place_to_str

        attrs["place"] = place_to_str(place)
    return _decorate_reader("double_buffer_reader", reader, attrs)


def multi_pass(reader, pass_num):
    """reference create_multi_pass_reader_op.cc — replay the underlying
    reader pass_num times (epoch loop as a reader decorator)."""
    return _decorate_reader("multi_pass_reader", reader,
                            {"pass_num": pass_num})


def random_data_generator(low, high, shapes, lod_levels, for_parallel=False):
    helper = LayerHelper("random_data_generator")
    name = unique_name.generate("random_reader")
    var = _create_reader_var(name, shapes, ["float32"] * len(shapes), lod_levels)
    startup = default_startup_program()
    startup.global_block().create_var(name=name, type=VarType.READER, persistable=True)
    startup.global_block().append_op(
        "create_random_data_generator",
        {},
        {"Out": [name]},
        {
            "low": low,
            "high": high,
            "shapes": [list(s) for s in shapes],
            "lod_levels": list(lod_levels),
        },
    )
    return var


def read_file(file_obj):
    """reference read_op: pop one batch from a reader variable."""
    helper = LayerHelper("read_file")
    meta = getattr(file_obj, "_reader_meta", None)
    outs = []
    if meta:
        for shape, dtype, lod in zip(meta["shapes"], meta["dtypes"], meta["lod_levels"]):
            outs.append(
                helper.create_tmp_variable(
                    dtype=dtype, shape=tuple(shape), lod_level=lod,
                    stop_gradient=True)
            )
    else:
        outs.append(
            helper.create_tmp_variable(dtype="float32", stop_gradient=True))
    helper.append_op("read", {"Reader": [file_obj]}, {"Out": outs})
    if len(outs) == 1:
        return outs[0]
    return outs
