"""Auto-generated simple layer wrappers.

Reference parity: python/paddle/fluid/layers/ops.py +
layer_function_generator.py — one Python function per simple (X->Out) op.
"""

from ..layer_helper import LayerHelper
from ..core.framework import Variable

_unary_ops = [
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink", "softshrink",
    "sqrt", "abs", "ceil", "floor", "cos", "sin", "round", "reciprocal", "log",
    "square", "softplus", "softsign", "brelu", "leaky_relu", "soft_relu", "elu",
    "relu6", "pow", "stanh", "hard_shrink", "hard_sigmoid", "thresholded_relu",
    "swish", "gelu",
]

_binary_ops = [
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "elementwise_pow",
]

__all__ = (
    _unary_ops
    + _binary_ops
    + [
        "mean", "scale", "clip", "clip_by_norm", "sums", "logical_and",
        "logical_or", "logical_xor", "logical_not", "uniform_random",
        "gaussian_random", "cumsum", "maxout",
        "elementwise_binary_dispatch",
    ]
)


def _make_unary(op_type):
    def func(x, name=None, **kwargs):
        helper = LayerHelper(op_type, name=name, **kwargs)
        out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape, lod_level=x.lod_level)
        attrs = {k: v for k, v in kwargs.items() if not isinstance(v, Variable)}
        helper.append_op(op_type, {"X": [x]}, {"Out": [out]}, attrs)
        return out

    func.__name__ = op_type
    func.__doc__ = f"{op_type} activation (see ops/activation_ops.py)."
    return func


for _op in _unary_ops:
    globals()[_op] = _make_unary(_op)


def _make_binary(op_type):
    def func(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, name=name, act=act)
        out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape, lod_level=x.lod_level)
        helper.append_op(op_type, {"X": [x], "Y": [y]}, {"Out": [out]}, {"axis": axis})
        return helper.append_activation(out)

    func.__name__ = op_type
    return func


for _op in _binary_ops:
    globals()[_op] = _make_binary(_op)


def elementwise_binary_dispatch(x, other, op_type):
    """Implements Variable.__add__ etc. (reference math_op_patch.py)."""
    if isinstance(other, Variable):
        return globals()[op_type](x, other)
    # scalar fast path via scale/shift
    val = float(other)
    if op_type == "elementwise_add":
        return scale(x, scale=1.0, bias=val)
    if op_type == "elementwise_sub":
        return scale(x, scale=1.0, bias=-val)
    if op_type == "elementwise_mul":
        return scale(x, scale=val)
    if op_type == "elementwise_div":
        return scale(x, scale=1.0 / val)
    raise NotImplementedError(op_type)


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_tmp_variable(dtype=x.dtype, shape=())
    helper.append_op("mean", {"X": [x]}, {"Out": [out]})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape, lod_level=x.lod_level)
    helper.append_op(
        "scale",
        {"X": [x]},
        {"Out": [out]},
        {"scale": float(scale), "bias": float(bias), "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape)
    helper.append_op("clip", {"X": [x]}, {"Out": [out]}, {"min": min, "max": max})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape)
    helper.append_op("clip_by_norm", {"X": [x]}, {"Out": [out]}, {"max_norm": max_norm})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_tmp_variable(dtype=helper.input_dtype())
    helper.append_op("sum", {"X": input}, {"Out": [out]})
    return out


def _logical(op_type, x, y=None, out=None, name=None):
    helper = LayerHelper(op_type, name=name)
    if out is None:
        out = helper.create_tmp_variable(dtype="bool", shape=x.shape)
    ins = {"X": [x]} if y is None else {"X": [x], "Y": [y]}
    helper.append_op(op_type, ins, {"Out": [out]})
    return out


def logical_and(x, y, out=None, name=None):
    return _logical("logical_and", x, y, out, name)


def logical_or(x, y, out=None, name=None):
    return _logical("logical_or", x, y, out, name)


def logical_xor(x, y, out=None, name=None):
    return _logical("logical_xor", x, y, out, name)


def logical_not(x, out=None, name=None):
    return _logical("logical_not", x, None, out, name)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_tmp_variable(dtype=dtype, shape=shape, stop_gradient=True)
    helper.append_op(
        "uniform_random",
        {},
        {"Out": [out]},
        {"shape": list(shape), "dtype": dtype, "min": min, "max": max, "seed": seed},
    )
    return out


def gaussian_random(shape, dtype="float32", mean=0.0, std=1.0, seed=0):
    helper = LayerHelper("gaussian_random")
    out = helper.create_tmp_variable(dtype=dtype, shape=shape, stop_gradient=True)
    helper.append_op(
        "gaussian_random",
        {},
        {"Out": [out]},
        {"shape": list(shape), "dtype": dtype, "mean": mean, "std": std, "seed": seed},
    )
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    helper = LayerHelper("cumsum")
    out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape)
    helper.append_op(
        "cumsum",
        {"X": [x]},
        {"Out": [out]},
        {"axis": axis, "exclusive": exclusive, "reverse": reverse},
    )
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    shape = None
    if x.shape:
        shape = (x.shape[0], x.shape[1] // groups, x.shape[2], x.shape[3])
    out = helper.create_tmp_variable(dtype=x.dtype, shape=shape)
    helper.append_op("maxout", {"X": [x]}, {"Out": [out]}, {"groups": groups})
    return out
