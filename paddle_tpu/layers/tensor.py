"""Tensor layers (reference python/paddle/fluid/layers/tensor.py)."""

import numpy as np

from ..layer_helper import LayerHelper
from ..core.framework import Variable
from ..core import dtypes
from ..initializer import Constant

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "cast",
    "concat", "assign", "fill_constant_batch_size_like", "fill_constant",
    "argmin", "argmax", "ones", "zeros", "reverse", "split", "one_hot",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype, persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter", name=name)
    if attr is None:
        attr = ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable, name=name
    )
    helper.set_variable_initializer(var, initializer=Constant(value=float(value)))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_tmp_variable(
        dtype=dtypes.canonicalize(dtype), shape=x.shape, lod_level=x.lod_level
    )
    helper.append_op(
        "cast",
        {"X": [x]},
        {"Out": [out]},
        {"in_dtype": x.dtype, "out_dtype": dtypes.canonicalize(dtype)},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    shape = None
    if all(v.shape is not None for v in input):
        shapes = [list(v.shape) for v in input]
        ndim = len(shapes[0])
        ax = axis % ndim
        if all(len(s) == ndim for s in shapes):
            shape = list(shapes[0])
            dims = [s[ax] for s in shapes]
            shape[ax] = -1 if any(d == -1 for d in dims) else sum(dims)
            shape = tuple(shape)
    out = helper.create_tmp_variable(
        dtype=helper.input_dtype(), shape=shape, lod_level=input[0].lod_level)
    helper.append_op("concat", {"X": input}, {"Out": [out]}, {"axis": axis})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_tmp_variable(
                dtype=input.dtype, shape=input.shape, lod_level=input.lod_level
            )
        helper.append_op("assign", {"X": [input]}, {"Out": [output]})
    elif isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_tmp_variable(dtype=str(input.dtype), shape=input.shape)
        helper.append_op(
            "assign_value",
            {},
            {"Out": [output]},
            {"shape": list(input.shape), "dtype": str(input.dtype), "values": input},
        )
    else:
        raise ValueError("Wrong type for assign input: %s" % type(input))
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_tmp_variable(
            dtype=dtypes.canonicalize(dtype), shape=tuple(shape), stop_gradient=True
        )
    helper.append_op(
        "fill_constant",
        {},
        {"Out": [out]},
        {"shape": list(shape), "dtype": dtypes.canonicalize(dtype), "value": float(value)},
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_tmp_variable(
        dtype=dtypes.canonicalize(dtype), shape=tuple(shape), stop_gradient=True
    )
    helper.append_op(
        "fill_constant_batch_size_like",
        {"Input": [input]},
        {"Out": [out]},
        {
            "shape": list(shape),
            "dtype": dtypes.canonicalize(dtype),
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    out.stop_gradient = True
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op("arg_min", {"X": [x]}, {"Out": [out]}, {"axis": axis})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op("arg_max", {"X": [x]}, {"Out": [out]}, {"axis": axis})
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(value=1.0, shape=shape, dtype=dtype)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(value=0.0, shape=shape, dtype=dtype)


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape)
    if isinstance(axis, int):
        axis = [axis]
    helper.append_op("reverse", {"X": [x]}, {"Out": [out]}, {"axis": axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    """reference layers/nn.py:2365 split."""
    helper = LayerHelper("split", name=name)
    input_shape = input.shape
    if isinstance(num_or_sections, int):
        num = num_or_sections
        attrs = {"num": num, "sections": [], "axis": dim}
    else:
        num = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
    outs = [helper.create_tmp_variable(dtype=input.dtype) for _ in range(num)]
    helper.append_op("split", {"X": [input]}, {"Out": outs}, attrs)
    return outs


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_tmp_variable(dtype="float32")
    helper.append_op("one_hot", {"X": [input]}, {"Out": [out]}, {"depth": depth})
    return out
