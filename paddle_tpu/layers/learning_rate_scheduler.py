"""Learning-rate decay schedules (reference
python/paddle/fluid/layers/learning_rate_scheduler.py: exponential_decay,
natural_exp_decay, inverse_time_decay, polynomial_decay, piecewise_decay,
noam_decay). Each builds ops on a global step counter, so the schedule is
part of the compiled step."""

import math

from .nn import autoincreased_step_counter
from . import tensor
from . import ops
from . import control_flow

__all__ = [
    "exponential_decay", "natural_exp_decay", "inverse_time_decay",
    "polynomial_decay", "piecewise_decay", "noam_decay",
]


def _decay_step_counter(begin=0):
    global_step = autoincreased_step_counter(
        counter_name="@LR_DECAY_COUNTER@", begin=begin, step=1
    )
    return tensor.cast(global_step, "float32")


def noam_decay(d_model, warmup_steps):
    global_step = _decay_step_counter(1)
    a = ops.pow(global_step, factor=-0.5)
    b = ops.scale(global_step, scale=warmup_steps ** -1.5)
    lr_value = ops.elementwise_min(a, b)
    return ops.scale(lr_value, scale=d_model ** -0.5)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _decay_step_counter()
    div_res = ops.scale(global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    # lr * decay_rate ^ div_res  = lr * exp(div_res * ln(decay_rate))
    exponent = ops.scale(div_res, scale=math.log(decay_rate))
    return ops.scale(ops.exp(exponent), scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _decay_step_counter()
    div_res = ops.scale(global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    return ops.scale(ops.exp(ops.scale(div_res, scale=-decay_rate)),
                     scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _decay_step_counter()
    div_res = ops.scale(global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    denom = ops.scale(div_res, scale=decay_rate, bias=1.0, bias_after_scale=True)
    return ops.scale(ops.reciprocal(denom), scale=float(learning_rate))


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    global_step = _decay_step_counter()
    if cycle:
        div_res = ops.ceil(ops.scale(global_step, scale=1.0 / decay_steps))
        # handle step=0: ceil(0)=0 -> use max(div,1)
        one = tensor.fill_constant(shape=(1,), dtype="float32", value=1.0)
        div_res = ops.elementwise_max(div_res, one)
        decay_steps_var = ops.scale(div_res, scale=float(decay_steps))
        ratio = ops.elementwise_div(global_step, decay_steps_var)
    else:
        ratio = ops.scale(global_step, scale=1.0 / decay_steps)
        one = tensor.fill_constant(shape=(), dtype="float32", value=1.0)
        ratio = ops.elementwise_min(ratio, one)
    # (lr - end)*(1-ratio)^power + end
    base = ops.scale(ratio, scale=-1.0, bias=1.0)
    powd = ops.pow(base, factor=power)
    return ops.scale(powd, scale=float(learning_rate) - float(end_learning_rate),
                     bias=float(end_learning_rate))


def piecewise_decay(boundaries, values):
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) - len(boundaries) should be 1")
    global_step = _decay_step_counter()
    from .. import unique_name
    from ..layer_helper import LayerHelper

    helper = LayerHelper("piecewise_decay")
    lr = helper.create_or_get_global_variable(
        unique_name.generate("learning_rate"), "float32", (1,), persistable=True
    )
    from ..initializer import Constant

    helper.set_variable_initializer(lr, Constant(values[0]))
    with control_flow.Switch() as switch:
        for i in range(len(boundaries)):
            boundary_val = tensor.fill_constant(shape=(1,), dtype="float32",
                                                value=float(boundaries[i]))
            value_var = tensor.fill_constant(shape=(1,), dtype="float32",
                                             value=float(values[i]))
            with switch.case(control_flow.less_than(global_step, boundary_val)):
                tensor.assign(value_var, lr)
        last_value_var = tensor.fill_constant(shape=(1,), dtype="float32",
                                              value=float(values[len(values) - 1]))
        with switch.default():
            tensor.assign(last_value_var, lr)
    return lr
