"""Layer library (reference python/paddle/fluid/layers/)."""

from . import ops
from .ops import *
from . import tensor
from .tensor import *
from . import nn
from .nn import *
from . import control_flow
from .control_flow import *
from . import io
from .io import *
from . import device
from .device import *
from . import detection
from .detection import *
from . import learning_rate_scheduler
from .learning_rate_scheduler import *

__all__ = (
    ops.__all__
    + tensor.__all__
    + nn.__all__
    + control_flow.__all__
    + io.__all__
    + device.__all__
    + detection.__all__
    + learning_rate_scheduler.__all__
    + ["elementwise_binary_dispatch"]
)

from .ops import elementwise_binary_dispatch
