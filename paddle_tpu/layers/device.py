"""Device layers (reference python/paddle/fluid/layers/device.py)."""

from ..layer_helper import LayerHelper
from .. import unique_name

__all__ = ["get_places"]


def get_places(device_count=None, device_type=None):
    helper = LayerHelper("get_places")
    out_places = helper.create_variable(name=unique_name.generate(helper.name + ".out"))
    attrs = {}
    if device_count is not None:
        attrs["device_count"] = int(device_count)
    if device_type is not None:
        attrs["device_type"] = str(device_type)
    helper.append_op("get_places", {}, {"Out": [out_places]}, attrs)
    return out_places
