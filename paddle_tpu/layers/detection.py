"""Detection layers (reference python/paddle/fluid/layers/detection.py):
box_coder, iou_similarity, prior_box family. Round-1 coverage of the box
utilities; SSD loss staged in ROADMAP.md.
"""

from ..layer_helper import LayerHelper

__all__ = ["box_coder", "iou_similarity"]


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True):
    helper = LayerHelper("box_coder")
    output_box = helper.create_tmp_variable(dtype=prior_box.dtype)
    helper.append_op(
        "box_coder",
        {
            "PriorBox": [prior_box],
            "PriorBoxVar": [prior_box_var] if prior_box_var is not None else [],
            "TargetBox": [target_box],
        },
        {"OutputBox": [output_box]},
        {"code_type": code_type, "box_normalized": box_normalized},
    )
    return output_box


def iou_similarity(x, y, box_normalized=True):
    helper = LayerHelper("iou_similarity")
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op("iou_similarity", {"X": [x], "Y": [y]}, {"Out": [out]})
    return out
