"""Detection layers (reference python/paddle/fluid/layers/detection.py):
prior_box, bipartite_match, target_assign, ssd_loss, multiclass_nms /
detection_output, plus the box utilities.

ssd_loss mirrors the reference composite (detection.py:350): matching /
mining / target assignment run as host ops producing STOP-GRADIENT targets,
while the differentiable loss terms (softmax cross-entropy + smooth-L1)
stay on the traced path so gradients flow to the location/confidence heads.
"""

from ..layer_helper import LayerHelper

__all__ = ["box_coder", "iou_similarity", "prior_box", "bipartite_match",
           "target_assign", "mine_hard_examples", "ssd_loss",
           "multiclass_nms", "detection_output", "multi_box_head",
           "detection_map"]


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None):
    """reference detection.py:679 — the SSD prediction head: per feature
    map, a prior_box grid plus 1x1 conv loc/conf branches, flattened and
    concatenated across maps.

    Returns (mbox_locs [N, P_total, 4], mbox_confs [N, P_total, C],
    boxes [P_total, 4], variances [P_total, 4])."""
    import math

    from . import nn
    from . import tensor as tensor_layers

    num_layer = len(inputs)
    if min_sizes is None:
        # reference ratio schedule: evenly spaced [min_ratio, max_ratio]
        # over layers 1.., with a half-scale prior for layer 0
        assert num_layer >= 3, \
            "min_sizes must be given explicitly for < 3 feature maps"
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio) / (num_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes
        max_sizes = [base_size * 0.20] + max_sizes
    if steps:
        step_w = step_h = steps

    mbox_locs, mbox_confs, box_results, var_results = [], [], [], []
    for i, inp in enumerate(inputs):
        min_size = min_sizes[i]
        max_size = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if aspect_ratios is not None else [1.0]
        if not isinstance(ar, (list, tuple)):
            ar = [ar]
        step = [step_w[i] if step_w else 0.0,
                step_h[i] if step_h else 0.0]
        box, var = prior_box(inp, image, min_size, max_size, list(ar),
                             variance, flip, clip, step, offset)
        H, W, P = box.shape[0], box.shape[1], box.shape[2]
        box_results.append(nn.reshape(box, shape=[H * W * P, 4],
                                      inplace=False))
        var_results.append(nn.reshape(var, shape=[H * W * P, 4],
                                      inplace=False))

        loc = nn.conv2d(input=inp, num_filters=P * 4,
                        filter_size=kernel_size, padding=pad, stride=stride)
        loc = nn.transpose(loc, perm=[0, 2, 3, 1])       # NHWC
        mbox_locs.append(nn.reshape(loc, shape=[0, H * W * P, 4],
                                    inplace=False))

        conf = nn.conv2d(input=inp, num_filters=P * num_classes,
                         filter_size=kernel_size, padding=pad,
                         stride=stride)
        conf = nn.transpose(conf, perm=[0, 2, 3, 1])
        mbox_confs.append(nn.reshape(
            conf, shape=[0, H * W * P, num_classes], inplace=False))

    if num_layer == 1:
        return mbox_locs[0], mbox_confs[0], box_results[0], var_results[0]
    return (tensor_layers.concat(mbox_locs, axis=1),
            tensor_layers.concat(mbox_confs, axis=1),
            tensor_layers.concat(box_results, axis=0),
            tensor_layers.concat(var_results, axis=0))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              elementwise=False):
    helper = LayerHelper("box_coder")
    output_box = helper.create_tmp_variable(dtype=prior_box.dtype)
    helper.append_op(
        "box_coder",
        {
            "PriorBox": [prior_box],
            "PriorBoxVar": [prior_box_var] if prior_box_var is not None else [],
            "TargetBox": [target_box],
        },
        {"OutputBox": [output_box]},
        {"code_type": code_type, "box_normalized": box_normalized,
         "elementwise": elementwise},
    )
    return output_box


def iou_similarity(x, y, box_normalized=True):
    helper = LayerHelper("iou_similarity")
    out = helper.create_tmp_variable(dtype=x.dtype, lod_level=x.lod_level)
    helper.append_op("iou_similarity", {"X": [x], "Y": [y]}, {"Out": [out]})
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.0],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, name=None):
    """reference detection.py:568 — SSD anchor grid for one feature map.
    Returns (boxes, variances), each [H, W, num_priors, 4]."""
    helper = LayerHelper("prior_box", **locals())
    if not isinstance(min_sizes, (list, tuple)):
        min_sizes = [min_sizes]
    if not isinstance(aspect_ratios, (list, tuple)):
        aspect_ratios = [aspect_ratios]
    attrs = {
        "min_sizes": [float(s) for s in min_sizes],
        "aspect_ratios": [float(a) for a in aspect_ratios],
        "variances": list(variance),
        "flip": flip,
        "clip": clip,
        "step_w": float(steps[0]),
        "step_h": float(steps[1]),
        "offset": offset,
    }
    if max_sizes:
        attrs["max_sizes"] = [float(s) for s in (
            max_sizes if isinstance(max_sizes, (list, tuple)) else [max_sizes])]
        assert len(attrs["max_sizes"]) == len(attrs["min_sizes"]), (
            "max_sizes must pair 1:1 with min_sizes (one sqrt(min*max) "
            "square prior per min_size)")
    # static [H, W, P, 4] shape so heads (multi_box_head) can size their
    # conv branches; P mirrors the kernel's prior-count rule: per min_size,
    # every aspect ratio plus (when max_sizes given) one square prior
    from ..ops.detection_ops import _expand_aspect_ratios

    shape = None
    if input.shape is not None and len(input.shape) == 4:
        n_ar = len(_expand_aspect_ratios(attrs["aspect_ratios"], flip))
        P = len(attrs["min_sizes"]) * (n_ar + (1 if max_sizes else 0))
        shape = (input.shape[2], input.shape[3], P, 4)
    box = helper.create_tmp_variable(dtype=input.dtype, shape=shape)
    var = helper.create_tmp_variable(dtype=input.dtype, shape=shape)
    helper.append_op("prior_box", {"Input": [input], "Image": [image]},
                     {"Boxes": [box], "Variances": [var]}, attrs)
    box.stop_gradient = True
    var.stop_gradient = True
    return box, var


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """reference detection.py:208 -> (match_indices, matched_distance),
    each [B, num_priors]; indices are per-image gt rows, -1 = unmatched."""
    helper = LayerHelper("bipartite_match", **locals())
    match_indices = helper.create_tmp_variable(dtype="int64")
    match_distance = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        "bipartite_match", {"DistMat": [dist_matrix]},
        {"ColToRowMatchIndices": [match_indices],
         "ColToRowMatchDist": [match_distance]},
        {"match_type": match_type, "dist_threshold": dist_threshold},
    )
    match_indices.stop_gradient = True
    match_distance.stop_gradient = True
    return match_indices, match_distance


def target_assign(input, match_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """reference detection.py:285 -> (out [B, P, D], out_weight [B, P, 1])."""
    helper = LayerHelper("target_assign", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype)
    out_weight = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        "target_assign",
        {"X": [input], "MatchIndices": [match_indices],
         "NegIndices": [negative_indices] if negative_indices is not None
         else []},
        {"Out": [out], "OutWeight": [out_weight]},
        {"mismatch_value": mismatch_value},
    )
    out.stop_gradient = True
    out_weight.stop_gradient = True
    return out, out_weight


def mine_hard_examples(cls_loss, match_indices, match_dist,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=None):
    """Hard-negative mining (reference mine_hard_examples_op.cc).

    Divergence from the reference: under mining_type="max_negative" the
    reference IGNORES sample_size (it only budgets hard_example mining);
    here a given sample_size additionally CAPS the mined negatives per
    prior set. Porting reference code that sets both mining_type=
    "max_negative" and sample_size will mine fewer negatives here — leave
    sample_size=None for strict reference numerics."""
    helper = LayerHelper("mine_hard_examples", **locals())
    neg_indices = helper.create_tmp_variable(dtype="int64", lod_level=1)
    updated = helper.create_tmp_variable(dtype="int64")
    helper.append_op(
        "mine_hard_examples",
        {"ClsLoss": [cls_loss], "MatchIndices": [match_indices],
         "MatchDist": [match_dist]},
        {"NegIndices": [neg_indices], "UpdatedMatchIndices": [updated]},
        {"neg_pos_ratio": neg_pos_ratio,
         "neg_dist_threshold": neg_dist_threshold,
         "mining_type": mining_type,
         "sample_size": sample_size},
    )
    neg_indices.stop_gradient = True
    updated.stop_gradient = True
    return neg_indices, updated


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """reference detection.py:350 — SSD multibox loss.

    location [N, P, 4], confidence [N, P, C], gt_box LoD [sum_gt, 4],
    gt_label LoD [sum_gt, 1], prior_box [P, 4]. Returns loss [N*P, 1]
    (normalize=True divides by the matched-prior count)."""
    from . import nn

    num_classes = int(confidence.shape[-1])

    # 1-2. match gt to priors on IoU
    iou = iou_similarity(x=gt_box, y=prior_box)
    matched_indices, matched_dist = bipartite_match(
        iou, match_type, overlap_threshold)

    # 3. confidence loss on provisional targets (for mining)
    tgt_label, _ = target_assign(gt_label, matched_indices,
                                 mismatch_value=background_label)
    conf2d = nn.reshape(confidence, shape=[-1, num_classes], inplace=False)
    lbl2d = nn.reshape(tgt_label, shape=[-1, 1], inplace=False)
    lbl2d.stop_gradient = True
    mining_loss = nn.softmax_with_cross_entropy(conf2d, lbl2d)

    # 4. hard-negative mining
    neg_indices, updated_indices = mine_hard_examples(
        mining_loss, matched_indices, matched_dist,
        neg_pos_ratio=neg_pos_ratio, neg_dist_threshold=neg_overlap,
        mining_type=mining_type, sample_size=sample_size)

    # 5. final classification targets (positives + mined negatives)
    final_label, conf_w = target_assign(
        gt_label, updated_indices, negative_indices=neg_indices,
        mismatch_value=background_label)
    flbl2d = nn.reshape(final_label, shape=[-1, 1], inplace=False)
    flbl2d.stop_gradient = True
    conf_loss = nn.softmax_with_cross_entropy(conf2d, flbl2d)
    conf_loss = conf_loss * nn.reshape(conf_w, shape=[-1, 1], inplace=False)

    # 6. localization targets: matched gt box per prior, encoded vs priors
    tgt_box, loc_w = target_assign(gt_box, updated_indices)
    loc_target = box_coder(prior_box, prior_box_var, tgt_box,
                           elementwise=True)
    loc_target.stop_gradient = True
    loc2d = nn.reshape(location, shape=[-1, 4], inplace=False)
    loct2d = nn.reshape(loc_target, shape=[-1, 4], inplace=False)
    loc_loss = nn.smooth_l1(loc2d, loct2d)
    loc_loss = loc_loss * nn.reshape(loc_w, shape=[-1, 1], inplace=False)

    # 7-8. weighted sum; optional normalization by matched count
    loss = conf_loss_weight * conf_loss + loc_loss_weight * loc_loss
    if normalize:
        denom = nn.reduce_sum(loc_w) + 1e-6
        loss = loss / denom
    return loss


def multiclass_nms(bboxes, scores, background_label=0, score_threshold=0.0,
                   nms_top_k=-1, nms_threshold=0.3, keep_top_k=-1,
                   nms_eta=1.0, normalized=True):
    """bboxes [N, M, 4], scores [N, C, M] -> LoD [total_det, 6] rows
    (label, score, x1, y1, x2, y2)."""
    helper = LayerHelper("multiclass_nms")
    out = helper.create_tmp_variable(dtype=bboxes.dtype, lod_level=1)
    helper.append_op(
        "multiclass_nms", {"BBoxes": [bboxes], "Scores": [scores]},
        {"Out": [out]},
        {"background_label": background_label,
         "score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "nms_threshold": nms_threshold, "keep_top_k": keep_top_k,
         "nms_eta": nms_eta},
    )
    out.stop_gradient = True
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """reference detection.py:46 — decode predicted offsets against the
    priors, then per-class NMS. loc [N, P, 4], scores [N, P, C] (already
    softmaxed) -> LoD detections [total, 6]."""
    from . import nn

    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = nn.transpose(scores, perm=[0, 2, 1])  # [N, C, P]
    return multiclass_nms(
        decoded, scores_t, background_label=background_label,
        score_threshold=score_threshold, nms_top_k=nms_top_k,
        nms_threshold=nms_threshold, keep_top_k=keep_top_k, nms_eta=nms_eta)


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral"):
    """VOC mAP of a detection batch (reference layers/detection.py:157).

    detect_res: LoD [M,6] rows [label, score, xmin, ymin, xmax, ymax]
    label: LoD [N,6] rows [label, difficult, box] or [N,5] [label, box]
    With has_state/input_states/out_states the op chains its
    (pos_count, true_pos, false_pos) accumulators across batches —
    the DetectionMAP evaluator wires that loop up.
    """
    helper = LayerHelper("detection_map", **locals())

    def _var(dtype):
        return helper.create_tmp_variable(dtype=dtype, stop_gradient=True)

    map_out = _var("float32")
    accum_pos_count_out = out_states[0] if out_states else _var("int32")
    accum_true_pos_out = out_states[1] if out_states else _var("float32")
    accum_false_pos_out = out_states[2] if out_states else _var("float32")

    inputs = {"Label": [label], "DetectRes": [detect_res]}
    if has_state is not None:
        inputs["HasState"] = [has_state]
    if input_states:
        inputs["PosCount"] = [input_states[0]]
        inputs["TruePos"] = [input_states[1]]
        inputs["FalsePos"] = [input_states[2]]
    helper.append_op(
        "detection_map",
        inputs,
        {
            "MAP": [map_out],
            "AccumPosCount": [accum_pos_count_out],
            "AccumTruePos": [accum_true_pos_out],
            "AccumFalsePos": [accum_false_pos_out],
        },
        {
            "overlap_threshold": overlap_threshold,
            "evaluate_difficult": evaluate_difficult,
            "ap_type": ap_version,
            "class_num": class_num,
            "background_label": background_label,
        },
    )
    return map_out
