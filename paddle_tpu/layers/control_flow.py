"""Control-flow layers (reference python/paddle/fluid/layers/control_flow.py):
While:608, StaticRNN:383, DynamicRNN:1317, IfElse:1215, Switch:1126,
ConditionalBlock:1069, lod_rank_table, array read/write, compare helpers.
"""

import contextlib

from ..layer_helper import LayerHelper
from ..core.framework import Variable, VarType
from .. import unique_name
from . import tensor as tensor_layers

__all__ = [
    "While", "Switch", "IfElse", "ConditionalBlock", "StaticRNN", "DynamicRNN",
    "lod_rank_table", "max_sequence_len", "lod_tensor_to_array",
    "array_to_lod_tensor", "increment", "array_write", "create_array",
    "less_than", "equal", "array_read", "shrink_memory", "array_length",
    "zeros_like", "reorder_lod_tensor_by_rank", "Print",
]


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """reference layers/control_flow.py Print:165 — debug-print a tensor as
    a pass-through op. On TPU it lowers to jax.debug.print inside the
    compiled step (the reference had to run it host-side). `summarize`
    truncates to the first N elements; `first_n` / `print_phase` /
    `print_tensor_*` are accepted for signature parity but are no-ops — the
    op runs inside one traced computation, which has no per-invocation
    counter and no separate backward program to phase against."""
    helper = LayerHelper("print", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype, shape=input.shape,
                                     lod_level=input.lod_level)
    helper.append_op(
        "print", {"In": [input]}, {"Out": [out]},
        {"message": message or input.name, "summarize": summarize},
    )
    return out


def less_than(x, y, cond=None, **ignored):
    helper = LayerHelper("less_than", **locals())
    if cond is None:
        cond = helper.create_tmp_variable(dtype="bool", shape=x.shape)
        cond.stop_gradient = True
    helper.append_op("less_than", {"X": [x], "Y": [y]}, {"Out": [cond]})
    return cond


def equal(x, y, cond=None, **ignored):
    helper = LayerHelper("equal", **locals())
    if cond is None:
        cond = helper.create_tmp_variable(dtype="bool", shape=x.shape)
        cond.stop_gradient = True
    helper.append_op("equal", {"X": [x], "Y": [y]}, {"Out": [cond]})
    return cond


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment", **locals())
    if not in_place:
        out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape)
    else:
        out = x
    helper.append_op("increment", {"X": [x]}, {"Out": [out]}, {"step": float(value)})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like", **locals())
    if out is None:
        out = helper.create_tmp_variable(dtype=x.dtype, shape=x.shape)
    helper.append_op("fill_zeros_like", {"X": [x]}, {"Out": [out]})
    return out


def create_array(dtype):
    helper = LayerHelper("array")
    return helper.create_variable(
        name=unique_name.generate("array"), type=VarType.LOD_TENSOR_ARRAY, dtype=dtype
    )


def array_write(x, i, array=None):
    helper = LayerHelper("array_write", **locals())
    if array is None:
        array = create_array(x.dtype)
    # carry the element shape so array_read outputs stay shape-inferable
    # (downstream fc/reshape need it; all slots share one element shape
    # under the static-shape trace anyway)
    if getattr(array, "shape", None) is None and x.shape is not None:
        array.shape = x.shape
    helper.append_op("write_to_array", {"X": [x], "I": [i]}, {"Out": [array]})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read", **locals())
    out = helper.create_tmp_variable(dtype=array.dtype,
                                     shape=getattr(array, "shape", None))
    helper.append_op("read_from_array", {"X": [array], "I": [i]}, {"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length", **locals())
    out = helper.create_tmp_variable(dtype="int64", shape=(1,))
    out.stop_gradient = True
    helper.append_op("lod_array_length", {"X": [array]}, {"Out": [out]})
    return out


def lod_rank_table(x, level=0):
    helper = LayerHelper("lod_rank_table", **locals())
    table = helper.create_variable(
        name=unique_name.generate("lod_rank_table"), type=VarType.LOD_RANK_TABLE
    )
    helper.append_op("lod_rank_table", {"X": [x]}, {"Out": [table]}, {"level": level})
    return table


def max_sequence_len(rank_table):
    helper = LayerHelper("max_seqence_len", **locals())
    res = helper.create_tmp_variable(dtype="int64", shape=(1,))
    res.stop_gradient = True
    helper.append_op("max_sequence_len", {"RankTable": [rank_table]}, {"Out": [res]})
    return res


def lod_tensor_to_array(x, table):
    helper = LayerHelper("lod_tensor_to_array", **locals())
    array = helper.create_variable(
        name=unique_name.generate("lod_tensor_to_array"),
        type=VarType.LOD_TENSOR_ARRAY,
        dtype=x.dtype,
    )
    helper.append_op(
        "lod_tensor_to_array", {"X": [x], "RankTable": [table]}, {"Out": [array]}
    )
    return array


def array_to_lod_tensor(x, table):
    helper = LayerHelper("array_to_lod_tensor", **locals())
    tmp = helper.create_tmp_variable(dtype=x.dtype, lod_level=1)
    helper.append_op(
        "array_to_lod_tensor", {"X": [x], "RankTable": [table]}, {"Out": [tmp]}
    )
    return tmp


def shrink_memory(x, i, table):
    helper = LayerHelper("shrink_memory", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(
        "shrink_rnn_memory", {"X": [x], "I": [i], "RankTable": [table]}, {"Out": [out]}
    )
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    helper = LayerHelper("reorder_lod_tensor_by_rank", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype, lod_level=x.lod_level)
    helper.append_op(
        "reorder_lod_tensor_by_rank",
        {"X": [x], "RankTable": [rank_table]},
        {"Out": [out]},
    )
    return out


class BlockGuard:
    def __init__(self, main_program):
        self.main_program = main_program

    def __enter__(self):
        self.main_program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.main_program.rollback()
        return exc_type is None


def _scan_sub_block(sub_block):
    """(reads, writes) name sets of a sub-block — the one definition used
    by the While/ConditionalBlock interface and the recurrent closure."""
    x_names, inner = set(), set()
    for op in sub_block.ops:
        x_names.update(op.input_arg_names())
        inner.update(op.output_arg_names())
    return x_names, inner


def _sub_block_closure(parent_block, sub_block, exclude):
    """Parent-visible names the sub-block READS that are not otherwise
    declared on the op: the recurrent family must list them as inputs so
    the auto-vjp tracks them — undeclared closure reads (weights!) would
    silently get ZERO gradients. Read-AND-written names stay in (their
    first read is of the parent value)."""
    x_names, _inner = _scan_sub_block(sub_block)
    return sorted(
        n for n in x_names
        if n and n not in exclude
        and parent_block.has_var_recursive(n))


def _sub_block_interface(parent_block, sub_block, snap_suffix,
                         all_writes=False):
    """Shared by While and ConditionalBlock: derive the sub-block's
    parent-visible reads and writes, undo constant-initializer
    stop_gradient flags on rewritten float vars (a var the block REWRITES
    is no longer the constant its initializer created — without this the
    backward reach dies at every accumulator; explicit user flags on
    computed vars stay respected), and create one pre-op snapshot var per
    written name (the lax-idiomatic stand-in for the reference's saved
    scopes, while_op.cc:35 / conditional_block_op.cc grad).

    Returns (in_names, out_names, init_snapshot_names, input_snap_names).
    init_snapshot_names align with out_names (pre-op values of written
    state); input_snap_names align with in_names (values of every read AT
    op entry — grad replay must not see values a LATER forward op wrote
    over). Under the trace both snapshot kinds are pure aliases: zero
    runtime cost."""
    from .. import unique_name

    x_names, inner = _scan_sub_block(sub_block)
    if all_writes:
        # ALL written names are outputs: the flat trace env makes
        # sub-created vars observable downstream (how IfElse branch
        # outputs reach the merge), so the cotangent must route back
        # through the op. Sub-created ones get a parent-block var desc.
        out_names = sorted(n for n in inner if n)
    else:
        # While: loop temps are not observable after the loop (the carry
        # exports only entry-materialized state), so declaring them would
        # be dead IR that scales with body size
        out_names = sorted(
            n for n in inner if parent_block.has_var_recursive(n))
    in_names = sorted(n for n in x_names if parent_block.has_var_recursive(n))
    const_init_types = {
        "fill_constant", "fill_constant_batch_size_like",
        "fill_zeros_like", "uniform_random", "gaussian_random",
    }
    producer = {}
    for p_op in parent_block.ops:
        for n in p_op.output_arg_names():
            producer[n] = p_op.type

    def _var_of(n):
        if parent_block.has_var_recursive(n):
            return parent_block.var_recursive(n)
        sub_v = sub_block.vars.get(n)
        return parent_block.create_var(
            name=n,
            shape=sub_v.shape if sub_v is not None else None,
            dtype=sub_v.dtype if sub_v is not None else "float32")

    init_names = []
    for n in out_names:
        v = _var_of(n)
        if v.dtype and "float" in str(v.dtype) \
                and producer.get(n) in const_init_types:
            v.stop_gradient = False
        snap = unique_name.generate(n + snap_suffix)
        parent_block.create_var(name=snap, shape=v.shape, dtype=v.dtype)
        init_names.append(snap)
    input_snap_names = []
    for n in in_names:
        v = parent_block.var_recursive(n)
        snap = unique_name.generate(n + snap_suffix + "_IN")
        parent_block.create_var(name=snap, shape=v.shape, dtype=v.dtype)
        input_snap_names.append(snap)
    return in_names, out_names, init_names, input_snap_names


class While:
    """reference control_flow.py:608 — lowers to lax.while_loop.

    `max_trip_count` (TPU extension, no reference equivalent): an upper
    bound on loop trips. Required iff the loop is trained through —
    `lax.while_loop` is not reverse-differentiable, so while_grad replays
    the loop as a bounded masked `lax.scan` of max_trip_count iterations
    (the XLA-idiomatic answer to while_op.cc:95's step-scope replay).
    Forward-only loops don't need it."""

    BEFORE_WHILE_BLOCK = 0
    IN_WHILE_BLOCK = 1
    AFTER_WHILE_BLOCK = 2

    def __init__(self, cond, name=None, max_trip_count=None):
        self.helper = LayerHelper("while", name=name)
        self.status = While.BEFORE_WHILE_BLOCK
        if not isinstance(cond, Variable):
            raise TypeError("condition should be a variable")
        self.cond_var = cond
        self.max_trip_count = max_trip_count

    def block(self):
        return WhileGuard(self)

    def complete(self, sub_block):
        main_program = self.helper.main_program
        parent_block = main_program.block(sub_block.parent_idx)
        # Out: vars the loop body writes that live in the parent scope —
        # the loop's carried state (reference while_op lists these too).
        # X keeps ALL parent-visible reads, including read-AND-written
        # carried vars: their INITIAL values are loop inputs, which is what
        # makes gradients through the loop expressible at the IR level.
        in_names, out_names, init_names, in_snaps = _sub_block_interface(
            parent_block, sub_block, "@WHILE_INIT")
        attrs = {"sub_block": sub_block}
        if self.max_trip_count is not None:
            attrs["max_trip_count"] = int(self.max_trip_count)
        parent_block.append_op(
            "while",
            {"X": in_names, "Condition": [self.cond_var]},
            {"Out": out_names, "InitStates": init_names,
             "InputSnapshots": in_snaps, "StepScopes": []},
            attrs,
        )


class WhileGuard(BlockGuard):
    def __init__(self, while_op):
        super().__init__(while_op.helper.main_program)
        self.while_op = while_op

    def __enter__(self):
        self.while_op.status = While.IN_WHILE_BLOCK
        return super().__enter__()

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        sub_block = self.main_program.current_block()
        res = super().__exit__(exc_type, exc_val, exc_tb)
        self.while_op.status = While.AFTER_WHILE_BLOCK
        self.while_op.complete(sub_block)
        return res


class ConditionalBlock:
    """reference control_flow.py:1069 — lowers to lax.cond."""

    def __init__(self, inputs, is_scalar_condition=False, name=None):
        for each_input in inputs:
            if not isinstance(each_input, Variable):
                raise TypeError("Each input should be a Variable")
        self.inputs = inputs
        self.is_scalar_condition = is_scalar_condition
        self.helper = LayerHelper("conditional_block", name=name)

    def block(self):
        return ConditionalBlockGuard(self)

    def complete(self, sub_block):
        main_program = self.helper.main_program
        parent_block = main_program.block(sub_block.parent_idx)
        # Input: parent-visible reads AND writes (grad path — written
        # names must be op inputs so the backward walk applies its
        # in-place pre/post grad semantics to them); Out + InitStates: the
        # written state and its pre-op snapshot, so conditional_block_grad
        # can differentiate BOTH branches (taken: vjp through the block;
        # not taken: identity to the init). Inputs are fetched lazily:
        # a state var first materialized INSIDE the block has no value yet.
        in_names, out_names, init_names, in_snaps = _sub_block_interface(
            parent_block, sub_block, "@COND_INIT", all_writes=True)
        extra = sorted(set(out_names) - set(in_names))
        in_names = in_names + extra  # snapshot lists stay aligned
        in_snaps = in_snaps + [""] * len(extra)
        cond_snaps = []
        for v in self.inputs:
            snap = unique_name.generate(v.name + "@COND_INIT_X")
            parent_block.create_var(name=snap, shape=v.shape, dtype=v.dtype)
            cond_snaps.append(snap)
        parent_block.append_op(
            "conditional_block",
            {"X": self.inputs, "Input": in_names},
            {"Out": out_names, "InitStates": init_names,
             "InputSnapshots": in_snaps, "CondSnapshots": cond_snaps,
             "Scope": []},
            {"sub_block": sub_block, "is_scalar_condition": self.is_scalar_condition},
        )


class ConditionalBlockGuard(BlockGuard):
    def __init__(self, cond_block):
        super().__init__(cond_block.helper.main_program)
        self.cond_block = cond_block

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            # roll back even on error: otherwise the program's current-block
            # pointer stays inside the abandoned sub-block and later layers
            # silently land there
            return super().__exit__(exc_type, exc_val, exc_tb)
        sub_block = self.main_program.current_block()
        res = super().__exit__(exc_type, exc_val, exc_tb)
        self.cond_block.complete(sub_block)
        return res


class Switch:
    """reference control_flow.py:1126 — chained conditional blocks."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.inside_scope = False
        self.pre_not_conditions = []

    def case(self, condition):
        if not self.inside_scope:
            raise ValueError("case should be called inside with")
        from .ops import logical_and, logical_not

        if len(self.pre_not_conditions) == 0:
            cond_block = ConditionalBlock([condition], is_scalar_condition=True)
            not_cond = logical_not(x=condition)
            self.pre_not_conditions.append(not_cond)
        else:
            pre_cond_num = len(self.pre_not_conditions)
            pre_not_cond = self.pre_not_conditions[pre_cond_num - 1]
            new_not_cond = logical_and(x=pre_not_cond, y=logical_not(x=condition))
            self.pre_not_conditions.append(new_not_cond)
            cond_block = ConditionalBlock(
                [logical_and(x=pre_not_cond, y=condition)], is_scalar_condition=True
            )
        return ConditionalBlockGuard(cond_block)

    def default(self):
        pre_cond_num = len(self.pre_not_conditions)
        if pre_cond_num == 0:
            raise ValueError("there should be at least one condition")
        cond_block = ConditionalBlock(
            [self.pre_not_conditions[pre_cond_num - 1]], is_scalar_condition=True
        )
        return ConditionalBlockGuard(cond_block)

    def __enter__(self):
        self.inside_scope = True
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.inside_scope = False
        return exc_type is None


class IfElse:
    """reference control_flow.py:1215."""

    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        if not isinstance(cond, Variable):
            raise TypeError("cond must be a Variable")
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.input_table = {}
        self.status = IfElse.OUT_IF_ELSE_BLOCKS
        self.conditional_true_block = ConditionalBlock([cond])
        from .ops import logical_not

        self.not_cond = logical_not(cond)
        self.conditional_false_block = ConditionalBlock([self.not_cond])
        self.output_table = [[], []]  # [true_out, false_out]

    def input(self, x):
        # both branches see the full input (masking happens at output merge)
        return x

    @contextlib.contextmanager
    def true_block(self):
        self.status = IfElse.IN_IF_ELSE_TRUE_BLOCKS
        with self.conditional_true_block.block():
            yield
        self.status = IfElse.OUT_IF_ELSE_BLOCKS

    @contextlib.contextmanager
    def false_block(self):
        self.status = IfElse.IN_IF_ELSE_FALSE_BLOCKS
        with self.conditional_false_block.block():
            yield
        self.status = IfElse.OUT_IF_ELSE_BLOCKS

    def output(self, *outs):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("output can only be invoked in the sub-block")
        out_table = self.output_table[
            1 if self.status == IfElse.IN_IF_ELSE_FALSE_BLOCKS else 0
        ]
        for each_out in outs:
            out_table.append(each_out)

    def __call__(self):
        if self.status != self.OUT_IF_ELSE_BLOCKS:
            raise ValueError("IfElse::__call__ must be out of sub-block")
        # merge: select per-row by condition
        rlist = []
        from .nn import multiplex
        from . import tensor as T

        for t_out, f_out in zip(self.output_table[0], self.output_table[1]):
            idx = T.cast(self.cond, "int32")
            rlist.append(multiplex([f_out, t_out], idx))
        return rlist


class StaticRNN:
    """reference control_flow.py:383 — fixed-length RNN over time steps.

    Built on a sub-block executed by the `recurrent` op, which lowers to
    lax.scan (see ops/recurrent_op in control-flow kernels)."""

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.memories = {}  # mem var name -> (init var, pre_mem var, mem var)
        self.inputs = []
        self.outputs = []
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.seq_len = None
        self._sub_block = None

    @contextlib.contextmanager
    def step(self):
        self.status = StaticRNN.IN_RNN_BLOCK
        self.helper.main_program.create_block()
        yield
        self._sub_block = self.helper.main_program.current_block()
        self.helper.main_program.rollback()
        self.status = StaticRNN.AFTER_RNN_BLOCK
        self._complete_op()

    def _assert_in_rnn_block_(self, method):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError(f"You must invoke {method} in rnn block")

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        self._assert_in_rnn_block_("memory")
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("if init is None, memory at least need shape and batch_ref")
            parent_block = self._parent_block()
            var_name = unique_name.generate("@".join([self.helper.name, "memory_boot"]))
            boot_var = parent_block.create_var(
                name=var_name, shape=shape, dtype=batch_ref.dtype, persistable=False
            )
            parent_block.append_op(
                "fill_constant_batch_size_like",
                {"Input": [batch_ref]},
                {"Out": [boot_var]},
                {
                    "value": init_value,
                    "shape": [1 if i == init_batch_dim_idx else s for i, s in enumerate(boot_var.shape)],
                    "dtype": boot_var.dtype,
                    "input_dim_idx": ref_batch_dim_idx,
                    "output_dim_idx": init_batch_dim_idx,
                },
            )
            return self.memory(init=boot_var)
        pre_mem = self.helper.create_variable(
            name=unique_name.generate("@".join([self.helper.name, "mem"])),
            dtype=init.dtype,
            shape=init.shape,
        )
        self.memories[pre_mem.name] = [init, pre_mem, None]
        return pre_mem

    def step_input(self, x):
        self._assert_in_rnn_block_("step_input")
        if self.seq_len is None:
            self.seq_len = x.shape[0]
        ipt = self.helper.create_variable(
            name=unique_name.generate("@".join([self.helper.name, "step_in"])),
            dtype=x.dtype,
            shape=tuple(x.shape[1:]) if x.shape else None,
        )
        self.inputs.append((x, ipt))
        return ipt

    def step_output(self, o):
        self._assert_in_rnn_block_("step_output")
        self.outputs.append(o)

    def output(self, *outputs):
        for each in outputs:
            self.step_output(each)

    def update_memory(self, mem, var):
        if not isinstance(mem, Variable) or not isinstance(var, Variable):
            raise TypeError("update memory should take variables")
        self.memories[mem.name][2] = var

    def _parent_block(self):
        prog = self.helper.main_program
        return prog.block(self._sub_block.parent_idx) if self._sub_block else prog.current_block()

    def _complete_op(self):
        sub_block = self._sub_block
        parent_block = self._parent_block()
        step_inputs = [x for x, _ in self.inputs]
        inner_inputs = [i for _, i in self.inputs]
        boots = [self.memories[k][0] for k in self.memories]
        pre_mems = [self.memories[k][1] for k in self.memories]
        new_mems = [self.memories[k][2] for k in self.memories]
        if any(m is None for m in new_mems):
            raise ValueError("every memory needs update_memory")
        step_outs = [
            self.helper.create_variable(
                name=unique_name.generate("@".join([self.helper.name, "out"])),
                dtype=o.dtype,
            )
            for o in self.outputs
        ]
        self._outputs_vars = step_outs
        # boots stay ELIGIBLE: a boot var read directly inside the step
        # (beyond its carry role) needs the closure path for that read's
        # gradient; double declaration sums via the multi-slot machinery
        closure = _sub_block_closure(
            parent_block, sub_block,
            exclude=set([v.name for v in inner_inputs]
                        + [v.name for v in pre_mems]))
        parent_block.append_op(
            "recurrent",
            {
                "inputs": step_inputs,
                "initial_states": boots,
                "Closure": closure,
            },
            {"outputs": step_outs, "step_scopes": []},
            {
                "sub_block": sub_block,
                "ex_states": [v.name for v in pre_mems],
                "states": [v.name for v in new_mems],
                "step_input_names": [v.name for v in inner_inputs],
                "step_output_names": [o.name for o in self.outputs],
                "closure_names": closure,
            },
        )

    def __call__(self, *args, **kwargs):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise ValueError("RNN output can only be retrieved after rnn block")
        if not self.outputs:
            raise ValueError("RNN has no output")
        elif len(self.outputs) == 1:
            return self._outputs_vars[0]
        return self._outputs_vars


class DynamicRNN:
    """reference control_flow.py:1317 — variable-length RNN.

    TPU-native lowering: instead of the reference's rank-table bucketing and
    per-step shrinking batches, steps run over the padded [B,T,*] view with
    per-step masks inside one lax.scan (`dynamic_recurrent` op); results are
    re-raggedified. Same semantics, static shapes."""

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self.inputs = []  # (outer ragged var, inner step var)
        self.static_inputs = []
        self.memories = []  # (init or None, shape, value, pre_mem, new_mem)
        self.outputs = []
        self._sub_block = None
        self._first_input = None

    def step_input(self, x):
        self._assert_in_rnn_block_("step_input")
        if self._first_input is None:
            self._first_input = x
        ipt = self.helper.create_variable(
            name=unique_name.generate("@".join([self.helper.name, "step_in"])),
            dtype=x.dtype,
        )
        self.inputs.append((x, ipt))
        return ipt

    def static_input(self, x):
        self._assert_in_rnn_block_("static_input")
        self.static_inputs.append(x)
        return x

    @contextlib.contextmanager
    def block(self):
        if self.status != DynamicRNN.BEFORE_RNN:
            raise ValueError("rnn.block() can only be invoked once")
        self.status = DynamicRNN.IN_RNN
        self.helper.main_program.create_block()
        yield
        self._sub_block = self.helper.main_program.current_block()
        self.helper.main_program.rollback()
        self.status = DynamicRNN.AFTER_RNN
        self._complete_op()

    def memory(self, init=None, shape=None, value=0.0, dtype="float32"):
        self._assert_in_rnn_block_("memory")
        pre_mem = self.helper.create_variable(
            name=unique_name.generate("@".join([self.helper.name, "mem"])),
            dtype=init.dtype if init is not None else dtype,
            shape=init.shape if init is not None else tuple([None] + list(shape or [])),
        )
        self.memories.append([init, shape, value, pre_mem, None])
        return pre_mem

    def update_memory(self, ex_mem, new_mem):
        self._assert_in_rnn_block_("update_memory")
        for m in self.memories:
            if m[3] is ex_mem:
                m[4] = new_mem
                return
        raise ValueError("unknown memory")

    def output(self, *outputs):
        self._assert_in_rnn_block_("output")
        for o in outputs:
            self.outputs.append(o)

    def _assert_in_rnn_block_(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError(f"{method} can only be invoked inside rnn block")

    def _complete_op(self):
        sub_block = self._sub_block
        parent_block = self.helper.main_program.block(sub_block.parent_idx)
        outs = [
            self.helper.create_variable(
                name=unique_name.generate("@".join([self.helper.name, "out"])),
                dtype=o.dtype,
                lod_level=1,
            )
            for o in self.outputs
        ]
        self._outputs_vars = outs
        closure = _sub_block_closure(
            parent_block, sub_block,
            exclude=set([i.name for _, i in self.inputs]
                        + [m[3].name for m in self.memories]
                        + [v.name for v in self.static_inputs]))
        parent_block.append_op(
            "dynamic_recurrent",
            {
                "inputs": [x for x, _ in self.inputs],
                "static_inputs": self.static_inputs,
                "initial_states": [m[0] for m in self.memories if m[0] is not None],
                "Closure": closure,
            },
            {"outputs": outs},
            {
                "sub_block": sub_block,
                "closure_names": closure,
                "static_input_names": [v.name for v in self.static_inputs],
                "step_input_names": [i.name for _, i in self.inputs],
                "mem_init_names": [m[0].name if m[0] is not None else "" for m in self.memories],
                "mem_shapes": [list(m[1]) if m[1] else [] for m in self.memories],
                "mem_values": [float(m[2]) for m in self.memories],
                "pre_mem_names": [m[3].name for m in self.memories],
                "new_mem_names": [m[4].name if m[4] is not None else "" for m in self.memories],
                "step_output_names": [o.name for o in self.outputs],
            },
        )

    def __call__(self, *args, **kwargs):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError("Dynamic RNN outputs can only be retrieved after rnn.block()")
        if len(self._outputs_vars) == 1:
            return self._outputs_vars[0]
        return self._outputs_vars
