"""ResNet for ImageNet-class (resnet_imagenet, depths 18-152) and CIFAR-10
(resnet_cifar10).

Reference parity: benchmark/fluid/models/resnet.py:40-116 (conv_bn blocks,
basic/bottleneck residuals, stage widths 64/128/256/512). TPU-first notes:
NCHW API surface is preserved (reference data_format), while conv kernels
lower to XLA convolutions that the TPU compiler lays out for the MXU;
batch-norm folds into the conv epilogue under XLA fusion.

Provenance: this module is a BENCHMARK WORKLOAD DEFINITION — the
layer sequence, filter counts, and depth configs intentionally match
the reference benchmark model so perf/convergence comparisons are
apples-to-apples; the implementation is written against this
framework's own API.
"""

import numpy as np

import paddle_tpu as fluid


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  layout="NCHW"):
    conv1 = fluid.layers.conv2d(
        input=input, filter_size=filter_size, num_filters=ch_out,
        stride=stride, padding=padding, act=None, bias_attr=False,
        data_format=layout)
    return fluid.layers.batch_norm(input=conv1, act=act, data_layout=layout)


def shortcut(input, ch_out, stride, layout="NCHW"):
    ch_in = input.shape[-1 if layout == "NHWC" else 1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None,
                             layout=layout)
    return input


def basicblock(input, ch_out, stride, layout="NCHW"):
    short = shortcut(input, ch_out, stride, layout)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, layout=layout)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, layout=layout)
    return fluid.layers.elementwise_add(x=short, y=conv2, act="relu")


def bottleneck(input, ch_out, stride, layout="NCHW"):
    short = shortcut(input, ch_out * 4, stride, layout)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, layout=layout)
    conv2 = conv_bn_layer(conv1, ch_out, 3, stride=1, padding=1,
                          layout=layout)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          layout=layout)
    return fluid.layers.elementwise_add(x=short, y=conv3, act="relu")


def layer_warp(block_func, input, ch_out, count, stride, layout="NCHW"):
    res_out = block_func(input, ch_out, stride, layout)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out, 1, layout)
    return res_out


def resnet_imagenet(input, class_dim, depth=50, layout="NCHW"):
    """layout="NHWC" (TPU extension): channels-last activations end to end
    — input must then be [N, H, W, C]; parameters are layout-independent
    (filters stay OIHW), so checkpoints transfer between layouts."""
    cfg = {
        18: ([2, 2, 2, 1], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, layout=layout)
    pool1 = fluid.layers.pool2d(
        input=conv1, pool_type="avg", pool_size=3, pool_stride=2,
        data_format=layout)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1, layout)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2, layout)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2, layout)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2, layout)
    pool2 = fluid.layers.pool2d(
        input=res4, pool_size=7, pool_type="avg", pool_stride=1,
        global_pooling=True, data_format=layout)
    out = fluid.layers.fc(input=pool2, size=class_dim, act="softmax")
    return out


def resnet_cifar10(input, class_dim, depth=32):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(
        input=input, ch_out=16, filter_size=3, stride=1, padding=1)
    res1 = layer_warp(basicblock, conv1, 16, n, 1)
    res2 = layer_warp(basicblock, res1, 32, n, 2)
    res3 = layer_warp(basicblock, res2, 64, n, 2)
    pool = fluid.layers.pool2d(
        input=res3, pool_size=8, pool_type="avg", pool_stride=1)
    out = fluid.layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def get_model(args):
    """benchmark/fluid model contract: returns
    (avg_cost, inference_program, optimizer, train_reader, test_reader,
     batch_acc)."""
    if args.data_set == "cifar10":
        class_dim, dshape, model = 10, [3, 32, 32], resnet_cifar10
        train_r, test_r = fluid.dataset.cifar.train10(), \
            fluid.dataset.cifar.test10()
    else:
        class_dim, dshape, model = 102, [3, 224, 224], resnet_imagenet
        train_r, test_r = fluid.dataset.flowers.train(), \
            fluid.dataset.flowers.test()

    input = fluid.layers.data(name="data", shape=dshape, dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    predict = model(input, class_dim)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)

    inference_program = fluid.default_main_program().clone(for_test=True)
    optimizer = fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9)

    train_reader = fluid.batch(
        fluid.reader.shuffle(train_r, buf_size=5120),
        batch_size=args.batch_size)
    test_reader = fluid.batch(test_r, batch_size=args.batch_size)
    return avg_cost, inference_program, optimizer, train_reader, \
        test_reader, batch_acc
