"""Benchmark model zoo (reference benchmark/fluid/models/__init__.py).

Each module exposes get_model(args) -> (avg_cost, inference_program,
optimizer, train_reader, test_reader, batch_acc). args needs .batch_size and
.data_set ("cifar10" | "flowers" | ...).
"""

from . import mnist
from . import resnet
from . import vgg
from . import se_resnext
from . import stacked_dynamic_lstm
from . import machine_translation

__all__ = ["mnist", "resnet", "vgg", "se_resnext", "stacked_dynamic_lstm",
           "machine_translation"]


def get_model(name):
    import importlib
    return importlib.import_module(f"paddle_tpu.models.{name}").get_model
