"""MNIST conv net (reference benchmark/fluid/models/mnist.py:35-94).

Provenance: this module is a BENCHMARK WORKLOAD DEFINITION — the
layer sequence, filter counts, and depth configs intentionally match
the reference benchmark model so perf/convergence comparisons are
apples-to-apples; the implementation is written against this
framework's own API.
"""

import paddle_tpu as fluid


def cnn_model(data):
    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    import numpy as np
    input_shape = conv_pool_2.shape
    param_shape = [int(np.prod(input_shape[1:]))] + [10]
    scale = (2.0 / (param_shape[0] ** 2 * 10)) ** 0.5
    predict = fluid.layers.fc(
        input=conv_pool_2, size=10, act="softmax",
        param_attr=fluid.ParamAttr(
            initializer=fluid.initializer.NormalInitializer(
                loc=0.0, scale=scale)))
    return predict


def get_model(args):
    images = fluid.layers.data(name="pixel", shape=[1, 28, 28],
                               dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    predict = cnn_model(images)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)

    inference_program = fluid.default_main_program().clone(for_test=True)
    opt = fluid.optimizer.AdamOptimizer(
        learning_rate=0.001, beta1=0.9, beta2=0.999)

    def _wrap(r):
        def wrapped():
            for img, lbl in r():
                yield img.reshape(1, 28, 28), lbl
        return wrapped

    train_reader = fluid.batch(_wrap(fluid.dataset.mnist.train()),
                               batch_size=args.batch_size)
    test_reader = fluid.batch(_wrap(fluid.dataset.mnist.test()),
                              batch_size=args.batch_size)
    return avg_cost, inference_program, opt, train_reader, test_reader, \
        batch_acc
