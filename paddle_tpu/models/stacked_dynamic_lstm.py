"""Stacked LSTM for IMDB sentiment (reference
benchmark/fluid/models/stacked_dynamic_lstm.py:46-120).

The reference hand-builds LSTM gates inside a DynamicRNN (one fc per gate per
step). TPU-first: the same computation is expressed with the fused
dynamic_lstm layer — a projection fc + one lax.scan over time with all four
gates in a single MXU matmul per step — which is the layout the reference's
own cudnn path (dynamic_lstm op) uses. words/sec metric is identical.
"""

import numpy as np

import paddle_tpu as fluid


def get_model(args):
    lstm_size = 512
    emb_dim = 512
    crop_size = 1500

    word_dict = fluid.dataset.imdb.word_dict()

    data = fluid.layers.data(
        name="words", shape=[1], lod_level=1, dtype="int64")
    sentence = fluid.layers.embedding(
        input=data, size=[len(word_dict), emb_dim])
    sentence = fluid.layers.fc(input=sentence, size=lstm_size, act="tanh")

    proj = fluid.layers.fc(input=sentence, size=lstm_size * 4,
                           bias_attr=False)
    hidden, _cell = fluid.layers.dynamic_lstm(
        input=proj, size=lstm_size * 4, use_peepholes=False,
        # static scan bound: without it the scan trip count defaults to
        # the batch's FLAT token total — fine for eager shapes, 10-20x
        # wasteful under bucketed feeding (benchmark --max_seq_len)
        max_len=getattr(args, "max_seq_len", None))

    last = fluid.layers.sequence_pool(hidden, "last")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    logit = fluid.layers.fc(input=last, size=2, act="softmax")
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=logit, label=label))
    batch_acc = fluid.layers.accuracy(input=logit, label=label)

    inference_program = fluid.default_main_program().clone(for_test=True)
    adam = fluid.optimizer.Adam()

    def crop_sentence(reader, crop_size):
        unk_value = word_dict["<unk>"]

        def __impl__():
            for item in reader():
                if len([x for x in item[0] if x != unk_value]) < crop_size:
                    yield item

        return __impl__

    train_reader = fluid.batch(
        fluid.reader.shuffle(
            crop_sentence(fluid.dataset.imdb.train(word_dict), crop_size),
            buf_size=25000),
        batch_size=args.batch_size)
    test_reader = fluid.batch(
        crop_sentence(fluid.dataset.imdb.test(word_dict), crop_size),
        batch_size=args.batch_size)

    return loss, inference_program, adam, train_reader, test_reader, batch_acc
