"""SE-ResNeXt-50/101/152 (reference
python/paddle/fluid/tests/unittests/test_parallel_executor.py SE-ResNeXt
definition + BASELINE.json north star).

Squeeze-and-excitation over grouped bottleneck blocks. Cardinality is
expressed with grouped conv2d; the SE gate is a global-pool -> fc -> fc
-> channel scale, which XLA fuses into the surrounding convolutions.
"""

import paddle_tpu as fluid


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None):
    conv = fluid.layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv, act=act)


def squeeze_excitation(input, num_channels, reduction_ratio):
    pool = fluid.layers.pool2d(
        input=input, pool_type="avg", global_pooling=True)
    squeeze = fluid.layers.fc(
        input=pool, size=num_channels // reduction_ratio, act="relu")
    excitation = fluid.layers.fc(
        input=squeeze, size=num_channels, act="sigmoid")
    return fluid.layers.elementwise_mul(x=input, y=excitation, axis=0)


def shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride)
    return input


def bottleneck_block(input, num_filters, stride, cardinality,
                     reduction_ratio):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu")
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act="relu")
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, num_filters * 2, stride)
    return fluid.layers.elementwise_add(x=short, y=scale, act="relu")


def se_resnext(input, class_dim, depth=50):
    cfg = {
        50: [3, 4, 6, 3],
        101: [3, 4, 23, 3],
        152: [3, 8, 36, 3],
    }
    depth_cfg = cfg[depth]
    cardinality = 32
    reduction_ratio = 16
    num_filters = [128, 256, 512, 1024]

    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu")
    conv = fluid.layers.pool2d(
        input=conv, pool_size=3, pool_stride=2, pool_padding=1,
        pool_type="max")
    for block in range(len(depth_cfg)):
        for i in range(depth_cfg[block]):
            conv = bottleneck_block(
                conv, num_filters[block],
                2 if i == 0 and block != 0 else 1,
                cardinality, reduction_ratio)
    pool = fluid.layers.pool2d(
        input=conv, pool_type="avg", global_pooling=True)
    drop = fluid.layers.dropout(x=pool, dropout_prob=0.2)
    return fluid.layers.fc(input=drop, size=class_dim, act="softmax")


def get_model(args):
    class_dim = 102 if args.data_set != "cifar10" else 10
    dshape = [3, 224, 224] if args.data_set != "cifar10" else [3, 32, 32]
    input = fluid.layers.data(name="data", shape=dshape, dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    predict = se_resnext(input, class_dim)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)

    inference_program = fluid.default_main_program().clone(for_test=True)
    optimizer = fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9)

    if args.data_set == "cifar10":
        train_r, test_r = fluid.dataset.cifar.train10(), \
            fluid.dataset.cifar.test10()
    else:
        train_r, test_r = fluid.dataset.flowers.train(), \
            fluid.dataset.flowers.test()
    train_reader = fluid.batch(
        fluid.reader.shuffle(train_r, buf_size=5120),
        batch_size=args.batch_size)
    test_reader = fluid.batch(test_r, batch_size=args.batch_size)
    return avg_cost, inference_program, optimizer, train_reader, \
        test_reader, batch_acc
