"""Seq2seq NMT with bidirectional LSTM encoder + attention decoder
(reference benchmark/fluid/models/machine_translation.py:30-180).

TPU-first: encoder uses the fused dynamic_lstm (lax.scan) forward+reverse;
the decoder's per-step attention (the reference's DynamicRNN +
sequence_expand/sequence_softmax dance) is expressed with the same sequence
ops — LoD ragged batches are packed into SeqTensor (data + lengths) so the
whole graph stays statically shaped for XLA.
"""

import numpy as np

import paddle_tpu as fluid


def bi_lstm_encoder(input_seq, gate_size):
    input_forward_proj = fluid.layers.fc(
        input=input_seq, size=gate_size * 4, act=None, bias_attr=False)
    forward, _ = fluid.layers.dynamic_lstm(
        input=input_forward_proj, size=gate_size * 4, use_peepholes=False)
    input_reversed_proj = fluid.layers.fc(
        input=input_seq, size=gate_size * 4, act=None, bias_attr=False)
    reversed_, _ = fluid.layers.dynamic_lstm(
        input=input_reversed_proj, size=gate_size * 4, is_reverse=True,
        use_peepholes=False)
    return forward, reversed_


def seq_to_seq_net(embedding_dim, encoder_size, decoder_size,
                   source_dict_dim, target_dict_dim,
                   max_source_len=32, max_target_len=32):
    """max_{source,target}_len are STATIC scan bounds for the decoder (they
    size the padded [B,T,*] buffers XLA compiles). They are enforced, not
    advisory: attention_lstm_decoder raises on any batch whose sequences
    exceed the cap (ops/rnn_ops.py _check_cap), so real data longer than
    the default 32 must pass larger caps here rather than being silently
    truncated."""
    src_word_idx = fluid.layers.data(
        name="source_sequence", shape=[1], dtype="int64", lod_level=1)
    src_embedding = fluid.layers.embedding(
        input=src_word_idx, size=[source_dict_dim, embedding_dim],
        dtype="float32")

    src_forward, src_reversed = bi_lstm_encoder(
        input_seq=src_embedding, gate_size=encoder_size)
    encoded_vector = fluid.layers.concat(
        input=[src_forward, src_reversed], axis=1)
    encoded_proj = fluid.layers.fc(
        input=encoded_vector, size=decoder_size, bias_attr=False)

    backward_first = fluid.layers.sequence_pool(
        input=src_reversed, pool_type="first")
    decoder_boot = fluid.layers.fc(
        input=backward_first, size=decoder_size, bias_attr=False, act="tanh")

    # decoder: teacher-forced LSTM over the target sequence; per-step
    # content attention over the encoder states
    trg_word_idx = fluid.layers.data(
        name="target_sequence", shape=[1], dtype="int64", lod_level=1)
    trg_embedding = fluid.layers.embedding(
        input=trg_word_idx, size=[target_dict_dim, embedding_dim],
        dtype="float32")

    # static scan bounds: wmt14 sequences are <= ~17 tokens with <s>/<e>;
    # without these the kernel falls back to scanning ntokens (sum over the
    # batch) masked steps — correct but ~batch_size times more work.
    # Over-cap batches raise inside the op (no silent truncation).
    prediction = fluid.layers.attention_lstm_decoder(
        target_embedding=trg_embedding,
        encoder_vec=encoded_vector,
        encoder_proj=encoded_proj,
        decoder_boot=decoder_boot,
        decoder_size=decoder_size,
        target_dict_dim=target_dict_dim,
        max_target_len=max_target_len, max_source_len=max_source_len)

    label = fluid.layers.data(
        name="label_sequence", shape=[1], dtype="int64", lod_level=1)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(cost)
    return avg_cost, prediction


def beam_decode(exe, train_prog, src_lod_tensor, beam_size=4, max_len=16,
                start_id=0, end_id=1, scope=None):
    """Beam-search inference over a trained seq_to_seq_net
    (reference python/paddle/fluid/tests/book/test_machine_translation.py:1
    decode(): a While loop of DynamicRNN step + beam_search ops; here the
    host drives the loop — each step is one XLA computation of
    attention_lstm_step + beam_search on dense [B*beam_size] rows).

    Returns (sentences, scores): lists of per-beam token-id lists /
    accumulated log-prob floats, src-major beam-minor."""
    import paddle_tpu as fluid
    from paddle_tpu.core.lod_tensor import LoDTensor

    # -- locate decoder wiring in the train program (robust to layer
    #    auto-naming: the op's own input names are the source of truth)
    dec_op = next(op for b in train_prog.blocks for op in b.ops
                  if op.type == "attention_lstm_decoder")
    evec_n = dec_op.input("EncoderVec")[0]
    eproj_n = dec_op.input("EncoderProj")[0]
    boot_n = dec_op.input("DecoderBoot")[0]
    weight_slots = ["WAttState", "WAttScore", "WStep", "BStep", "WOut",
                    "BOut"]
    weight_names = {s: dec_op.input(s)[0] for s in weight_slots}
    table_n = next(
        op for op in train_prog.global_block().ops
        if op.type == "lookup_table"
        and op.input("Ids")[0] == "target_sequence").input("W")[0]

    # -- run the encoder once (test-mode clone, DCE keeps only the encoder)
    infer_prog = train_prog.clone(for_test=True)
    evec, eproj, boot = exe.run(
        infer_prog, feed={"source_sequence": src_lod_tensor},
        fetch_list=[evec_n, eproj_n, boot_n], return_numpy=False,
        scope=scope)

    def to_padded(lt):
        data = np.asarray(lt.numpy())
        offs = lt.last_level_offsets()
        lens = [b - a for a, b in zip(offs, offs[1:])]
        Ts = max(lens)
        B = len(lens)
        o = np.zeros((B, Ts) + data.shape[1:], data.dtype)
        m = np.zeros((B, Ts), "float32")
        for i, (a, b) in enumerate(zip(offs, offs[1:])):
            o[i, : b - a] = data[a:b]
            m[i, : b - a] = 1.0
        return o, m

    evec_p, src_mask = to_padded(evec)
    eproj_p, _ = to_padded(eproj)
    boot = np.asarray(boot.numpy() if hasattr(boot, "numpy") else boot)
    B, Ts, He = evec_p.shape
    D = boot.shape[-1]
    K = beam_size
    rep = lambda a: np.repeat(a, K, axis=0)
    evec_b, eproj_b, mask_b = rep(evec_p), rep(eproj_p), rep(src_mask)

    table = np.asarray(fluid.fetch_var(table_n, scope=scope))
    E, V = table.shape[1], table.shape[0]

    # -- one-step program (weights pulled from the shared scope by name)
    step_prog = fluid.Program()
    with fluid.program_guard(step_prog, fluid.Program()):
        pe = fluid.layers.data(name="prev_emb", shape=[E], dtype="float32")
        ph = fluid.layers.data(name="prev_h", shape=[D], dtype="float32")
        pc = fluid.layers.data(name="prev_c", shape=[D], dtype="float32")
        blk = step_prog.global_block()
        # encoder tensors are loop-invariant: persistable scope vars, set
        # once below — NOT per-step feeds (host->device rides a slow tunnel)
        ev = blk.create_var(name="beam_evec", shape=[-1, Ts, He],
                            dtype="float32", persistable=True)
        ej = blk.create_var(name="beam_eproj", shape=[-1, Ts, D],
                            dtype="float32", persistable=True)
        sm = blk.create_var(name="beam_smask", shape=[-1, Ts],
                            dtype="float32", persistable=True)
        for s, n in weight_names.items():
            v = train_prog.global_block().vars[n]
            blk.create_var(name=n, shape=v.shape, dtype=v.dtype,
                           persistable=True)
        h_o = blk.create_var(name="step_h", dtype="float32")
        c_o = blk.create_var(name="step_c", dtype="float32")
        lp_o = blk.create_var(name="step_logprobs", dtype="float32")
        blk.append_op(
            type="attention_lstm_step",
            inputs={"PrevEmb": [pe.name], "PrevH": [ph.name],
                    "PrevC": [pc.name], "EncoderVec": [ev.name],
                    "EncoderProj": [ej.name], "SrcMask": [sm.name],
                    **{s: [n] for s, n in weight_names.items()}},
            outputs={"H": [h_o.name], "C": [c_o.name],
                     "LogProbs": [lp_o.name]},
            attrs={})

    # -- beam-step program (ids omitted: candidate id = vocab column)
    beam_prog = fluid.Program()
    with fluid.program_guard(beam_prog, fluid.Program()):
        pi = fluid.layers.data(name="pre_ids", shape=[1], dtype="int64")
        ps = fluid.layers.data(name="pre_scores", shape=[1],
                               dtype="float32")
        cs = fluid.layers.data(name="cand_scores", shape=[V],
                               dtype="float32")
        si, ss, par = fluid.layers.beam_search(
            pi, None, cs, beam_size=K, end_id=end_id, pre_scores=ps,
            return_parents=True)

    pre_ids = np.full((B * K, 1), -1, dtype="int64")
    pre_ids[::K, 0] = start_id
    pre_scores = np.zeros((B * K, 1), dtype="float32")
    h = rep(boot).astype("float32")
    c = np.zeros((B * K, D), dtype="float32")

    # device-resident loop invariants (fed once, read as state every step)
    sc_obj = scope or fluid.global_scope()
    for n, v in (("beam_evec", evec_b), ("beam_eproj", eproj_b),
                 ("beam_smask", mask_b)):
        sc_obj.var(n)
        sc_obj.set_var(n, v.astype("float32"))

    step_ids, step_scores, step_parents = [], [], []
    for _ in range(max_len):
        emb = table[np.clip(pre_ids[:, 0], 0, V - 1)].astype("float32")
        lp, h_new, c_new = exe.run(
            step_prog,
            feed={"prev_emb": emb, "prev_h": h, "prev_c": c},
            fetch_list=["step_logprobs", "step_h", "step_c"], scope=scope)
        cand_scores = pre_scores + np.asarray(lp, "float32")
        sel, sc, par_i = exe.run(
            beam_prog,
            feed={"pre_ids": pre_ids, "pre_scores": pre_scores,
                  "cand_scores": cand_scores},
            fetch_list=[si, ss, par], scope=scope)
        sel = np.asarray(sel, "int64")
        par_i = np.asarray(par_i, "int64")
        step_ids.append(sel)
        step_scores.append(np.asarray(sc, "float32"))
        step_parents.append(par_i)
        # beams follow their parents' recurrent state
        h = np.asarray(h_new)[par_i[:, 0]]
        c = np.asarray(c_new)[par_i[:, 0]]
        pre_ids, pre_scores = sel, np.asarray(sc, "float32")
        if (pre_ids[:, 0] == end_id).all():
            break

    decode_prog = fluid.Program()
    T = len(step_ids)
    with fluid.program_guard(decode_prog, fluid.Program()):
        iv = fluid.layers.data(name="ids", shape=[B * K, 1], dtype="int64")
        sv = fluid.layers.data(name="sc", shape=[B * K, 1], dtype="float32")
        pv = fluid.layers.data(name="par", shape=[B * K, 1], dtype="int64")
        si_v, ss_v = fluid.layers.beam_search_decode(
            iv, sv, parents=pv, end_id=end_id)
        ids_lt, sc_lt = exe.run(
            decode_prog,
            feed={"ids": np.stack(step_ids), "sc": np.stack(step_scores),
                  "par": np.stack(step_parents)},
            fetch_list=[si_v, ss_v], return_numpy=False, scope=scope)

    offs = ids_lt.last_level_offsets()
    toks = np.asarray(ids_lt.numpy()).reshape(-1)
    scs = np.asarray(sc_lt.numpy()).reshape(-1)
    sentences, scores = [], []
    for a, b in zip(offs, offs[1:]):
        sentences.append(toks[a:b].tolist())
        scores.append(float(scs[b - 1]) if b > a else 0.0)
    return sentences, scores


def lodtensor_to_ndarray(lod_tensor):
    import numpy as np
    return np.asarray(lod_tensor.numpy()), lod_tensor.lod()


def get_model(args):
    embedding_dim = 512
    encoder_size = 512
    decoder_size = 512
    dict_size = 30000

    avg_cost, feeding_list = seq_to_seq_net(
        embedding_dim, encoder_size, decoder_size, dict_size, dict_size)

    inference_program = fluid.default_main_program().clone(for_test=True)
    optimizer = fluid.optimizer.Adam(
        learning_rate=getattr(args, "learning_rate", 2e-4))

    train_reader = fluid.batch(
        fluid.reader.shuffle(
            fluid.dataset.wmt14.train(dict_size), buf_size=1000),
        batch_size=args.batch_size)
    test_reader = fluid.batch(
        fluid.dataset.wmt14.test(dict_size), batch_size=args.batch_size)

    return avg_cost, inference_program, optimizer, train_reader, \
        test_reader, None
