"""Seq2seq NMT with bidirectional LSTM encoder + attention decoder
(reference benchmark/fluid/models/machine_translation.py:30-180).

TPU-first: encoder uses the fused dynamic_lstm (lax.scan) forward+reverse;
the decoder's per-step attention (the reference's DynamicRNN +
sequence_expand/sequence_softmax dance) is expressed with the same sequence
ops — LoD ragged batches are packed into SeqTensor (data + lengths) so the
whole graph stays statically shaped for XLA.
"""

import paddle_tpu as fluid


def bi_lstm_encoder(input_seq, gate_size):
    input_forward_proj = fluid.layers.fc(
        input=input_seq, size=gate_size * 4, act=None, bias_attr=False)
    forward, _ = fluid.layers.dynamic_lstm(
        input=input_forward_proj, size=gate_size * 4, use_peepholes=False)
    input_reversed_proj = fluid.layers.fc(
        input=input_seq, size=gate_size * 4, act=None, bias_attr=False)
    reversed_, _ = fluid.layers.dynamic_lstm(
        input=input_reversed_proj, size=gate_size * 4, is_reverse=True,
        use_peepholes=False)
    return forward, reversed_


def seq_to_seq_net(embedding_dim, encoder_size, decoder_size,
                   source_dict_dim, target_dict_dim):
    src_word_idx = fluid.layers.data(
        name="source_sequence", shape=[1], dtype="int64", lod_level=1)
    src_embedding = fluid.layers.embedding(
        input=src_word_idx, size=[source_dict_dim, embedding_dim],
        dtype="float32")

    src_forward, src_reversed = bi_lstm_encoder(
        input_seq=src_embedding, gate_size=encoder_size)
    encoded_vector = fluid.layers.concat(
        input=[src_forward, src_reversed], axis=1)
    encoded_proj = fluid.layers.fc(
        input=encoded_vector, size=decoder_size, bias_attr=False)

    backward_first = fluid.layers.sequence_pool(
        input=src_reversed, pool_type="first")
    decoder_boot = fluid.layers.fc(
        input=backward_first, size=decoder_size, bias_attr=False, act="tanh")

    # decoder: teacher-forced LSTM over the target sequence; per-step
    # content attention over the encoder states
    trg_word_idx = fluid.layers.data(
        name="target_sequence", shape=[1], dtype="int64", lod_level=1)
    trg_embedding = fluid.layers.embedding(
        input=trg_word_idx, size=[target_dict_dim, embedding_dim],
        dtype="float32")

    # static scan bounds: wmt14 sequences are <= ~17 tokens with <s>/<e>;
    # without these the kernel falls back to scanning ntokens (sum over the
    # batch) masked steps — correct but ~batch_size times more work
    prediction = fluid.layers.attention_lstm_decoder(
        target_embedding=trg_embedding,
        encoder_vec=encoded_vector,
        encoder_proj=encoded_proj,
        decoder_boot=decoder_boot,
        decoder_size=decoder_size,
        target_dict_dim=target_dict_dim,
        max_target_len=32, max_source_len=32)

    label = fluid.layers.data(
        name="label_sequence", shape=[1], dtype="int64", lod_level=1)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(cost)
    return avg_cost, prediction


def lodtensor_to_ndarray(lod_tensor):
    import numpy as np
    return np.asarray(lod_tensor.numpy()), lod_tensor.lod()


def get_model(args):
    embedding_dim = 512
    encoder_size = 512
    decoder_size = 512
    dict_size = 30000

    avg_cost, feeding_list = seq_to_seq_net(
        embedding_dim, encoder_size, decoder_size, dict_size, dict_size)

    inference_program = fluid.default_main_program().clone(for_test=True)
    optimizer = fluid.optimizer.Adam(
        learning_rate=getattr(args, "learning_rate", 2e-4))

    train_reader = fluid.batch(
        fluid.reader.shuffle(
            fluid.dataset.wmt14.train(dict_size), buf_size=1000),
        batch_size=args.batch_size)
    test_reader = fluid.batch(
        fluid.dataset.wmt14.test(dict_size), batch_size=args.batch_size)

    return avg_cost, inference_program, optimizer, train_reader, \
        test_reader, None
