"""DataFeeder (reference python/paddle/fluid/data_feeder.py:70): converts
minibatch rows (numpy/lists) into feed dicts of arrays / LoDTensors.

TPU specifics: ragged (lod_level>0) slots are flattened and their token
capacity padded up to a power-of-two bucket so XLA sees a small set of static
shapes (recompiles are bounded), mirroring the role of the reference's
LoD while keeping shapes static.
"""

import numpy as np

from .core.framework import Variable, default_main_program
from .core import dtypes
from .core.lod_tensor import LoDTensor

__all__ = ["DataFeeder"]


def _bucket(n):
    """Round token count up to a power-of-two-ish bucket (1.5x steps)."""
    if n <= 16:
        return 16
    b = 16
    while b < n:
        b = b * 2 if b * 1.5 < n else int(b * 1.5)
    return b


class DataToLoDTensorConverter:
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = [s for s in shape]
        self.dtype = dtypes.to_np(dtype)
        self.data = []
        self.lod = [[] for _ in range(lod_level)]

    def feed(self, data):
        self._feed_impl_(data, self.lod, self.lod_level)

    def _feed_impl_(self, data, lod, lod_level):
        if lod_level == 0:
            self.data.append(data)
        else:
            lod[0].append(len(data))
            for each_data in data:
                self._feed_impl_(each_data, lod[1:], lod_level - 1)

    def done(self, pad_tokens=True):
        if self.lod_level == 0:
            arr = np.array(self.data, dtype=self.dtype)
            shape = [s for s in self.shape if s != -1 and s is not None]
            if arr.shape[1:] != tuple(shape) and int(np.prod(arr.shape[1:])) == int(np.prod(shape)):
                arr = arr.reshape([arr.shape[0]] + shape)
            return LoDTensor(arr)
        flat = []

        def _flatten(d, level):
            if level == 0:
                flat.append(d)
            else:
                for x in d:
                    _flatten(x, level - 1)

        for d in self.data:
            pass
        # self.data holds leaf rows already (appended at level 0)
        arr = np.array(self.data, dtype=self.dtype)
        if arr.ndim == 1:
            arr = arr.reshape(-1, 1)
        if pad_tokens:
            target = _bucket(arr.shape[0])
            if target > arr.shape[0]:
                pad = np.zeros((target - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype)
                arr = np.concatenate([arr, pad], axis=0)
        # offsets from lengths, innermost level last
        lod_offsets = []
        for lengths in self.lod:
            offs = [0]
            for l in lengths:
                offs.append(offs[-1] + l)
            lod_offsets.append(offs)
        t = LoDTensor(arr, lod_offsets)
        return t


class DataFeeder:
    def __init__(self, feed_list, place, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("Feed list should contain a list of variable")
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            shape = each_var.shape or ()
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(shape)
        self.place = place

    def feed(self, iterable):
        converter = []
        for lod_level, shape, dtype in zip(
            self.feed_lod_level, self.feed_shapes, self.feed_dtypes
        ):
            converter.append(
                DataToLoDTensorConverter(
                    place=self.place, lod_level=lod_level, shape=shape, dtype=dtype
                )
            )
        for each_sample in iterable:
            assert len(each_sample) == len(converter), (
                "The number of fields in data (%s) does not match len(feed_list) (%s)"
                % (len(each_sample), len(converter))
            )
            for each_converter, each_slot in zip(converter, each_sample):
                each_converter.feed(each_slot)
        ret_dict = {}
        for each_name, each_converter in zip(self.feed_names, converter):
            ret_dict[each_name] = each_converter.done()
        return ret_dict

    def feed_parallel(self, iterable, num_places=None):
        """Split one batch across devices (reference data_feeder.py:121).

        With the mesh-based ParallelExecutor the split is done by sharding,
        so this simply yields per-device sub-batches for API parity."""
        if num_places is None:
            num_places = 1
        rows = list(iterable)
        chunk = (len(rows) + num_places - 1) // num_places
        for i in range(num_places):
            part = rows[i * chunk : (i + 1) * chunk]
            if part:
                yield self.feed(part)
