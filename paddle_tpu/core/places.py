"""Device places.

Reference parity: paddle/fluid/platform/place.h:25-49 (CPUPlace / CUDAPlace /
CUDAPinnedPlace). The TPU build's first-class accelerator place is TPUPlace;
CUDAPlace is accepted as an alias for the accelerator place so reference-style
scripts run unmodified (they do `fluid.CUDAPlace(0)`).
"""

import jax


class Place:
    def __eq__(self, other):
        return type(self) is type(other) and getattr(self, "device_id", 0) == getattr(
            other, "device_id", 0
        )

    def __hash__(self):
        return hash((type(self).__name__, getattr(self, "device_id", 0)))

    def __repr__(self):
        return type(self).__name__ + "()"


class CPUPlace(Place):
    """Host CPU."""

    platform = "cpu"


class TPUPlace(Place):
    """A single TPU chip (by local device index)."""

    platform = "tpu"

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


# API-parity alias: reference scripts say CUDAPlace(0); here it means
# "the accelerator" (TPU when present, else CPU backend device 0).
class CUDAPlace(TPUPlace):
    def __repr__(self):
        return f"CUDAPlace({self.device_id})"


class CUDAPinnedPlace(CPUPlace):
    """Pinned host memory place (host staging buffers). On TPU, host->device

    transfer staging is managed by PjRt; this exists for API parity."""


def is_compiled_with_tpu():
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except RuntimeError:
        return False


# reference API parity (`core.is_compiled_with_cuda`, pybind.cc)
def is_compiled_with_cuda():
    return is_compiled_with_tpu()


def accelerator_count():
    """Number of local accelerator devices (get_cuda_device_count parity)."""
    return len([d for d in jax.devices() if d.platform != "cpu"]) or 0


def place_to_str(place):
    """Serialize a Place for op attrs / JSON IR ('cpu', 'tpu:0', ...)."""
    if isinstance(place, TPUPlace):
        return f"tpu:{place.device_id}"
    return "cpu"


def place_from_str(s):
    if s == "cpu" or not s:
        return CPUPlace()
    kind, _, idx = s.partition(":")
    if kind not in ("tpu", "cuda", "gpu"):
        raise ValueError(f"unknown place string {s!r}")
    return TPUPlace(int(idx or 0))


def jax_device_for(place):
    """Map a Place to a concrete jax.Device (place.h:25-49 semantics).

    CPUPlace resolves via the host platform directly (``jax.devices("cpu")``),
    NOT by scanning the default backend's device list: when an accelerator
    plugin owns the default backend, ``jax.devices()`` holds no cpu device
    and a scan would silently route CPUPlace to the accelerator (the r2
    MULTICHIP failure mode).

    Places address LOCAL devices (reference place.h: CUDAPlace(i) is the
    i-th local GPU): under jax.distributed the global device list starts
    with process 0's devices, so indexing jax.devices() would hand every
    other process a non-addressable device it cannot execute on."""
    if isinstance(place, CPUPlace) and not isinstance(place, TPUPlace):
        try:
            return jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            # no host platform registered at all; fall back to the default
            return jax.local_devices()[0]
    devs = jax.local_devices()
    accel = [d for d in devs if d.platform != "cpu"] or devs
    return accel[getattr(place, "device_id", 0) % len(accel)]
