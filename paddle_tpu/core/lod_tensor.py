"""LoDTensor: dense data + level-of-detail sequence offsets.

Reference parity: paddle/fluid/framework/lod_tensor.h:58,110 — `LoD` is a
list of offset vectors describing nested variable-length sequences laid out
flat along dim 0.

TPU-native representation: the flat data lives as a jax.Array with a
STATIC dim-0 size (batches are padded/bucketed by DataFeeder so XLA sees
static shapes); the lod offsets ride along as host numpy. In traced programs
sequence ops consume a derived `segment_ids`/`lengths` int array (see
ops/sequence_ops.py) so compute stays on-device with static shapes — this is
the XLA answer to the reference's dynamic LoD kernels.
"""

import numpy as np


def _offsets_to_lengths(level):
    return [level[i + 1] - level[i] for i in range(len(level) - 1)]


def _lengths_to_offsets(lengths):
    out = [0]
    for l in lengths:
        out.append(out[-1] + l)
    return out


class LoDTensor:
    def __init__(self, data=None, lod=None):
        self._data = data  # np.ndarray or jax.Array
        self._lod = [list(map(int, lv)) for lv in (lod or [])]

    # -- reference API ------------------------------------------------------
    def set(self, array, place=None):
        self._data = np.asarray(array)

    def set_lod(self, lod):
        self._lod = [list(map(int, lv)) for lv in lod]

    def lod(self):
        return [list(lv) for lv in self._lod]

    def set_recursive_sequence_lengths(self, lengths):
        self._lod = [_lengths_to_offsets(lv) for lv in lengths]

    def recursive_sequence_lengths(self):
        return [_offsets_to_lengths(lv) for lv in self._lod]

    def has_valid_recursive_sequence_lengths(self):
        if not self._lod:
            return True
        n = self._data.shape[0] if self._data is not None else 0
        prev_len = None
        for i, level in enumerate(self._lod):
            if not level or level[0] != 0:
                return False
            if any(level[j] > level[j + 1] for j in range(len(level) - 1)):
                return False
            if prev_len is not None and level[-1] != prev_len:
                return False
            prev_len = len(level) - 1 if i + 1 < len(self._lod) else None
        return self._lod[-1][-1] == n if self._lod else True

    def shape(self):
        return tuple(self._data.shape)

    @property
    def data(self):
        return self._data

    def __array__(self, dtype=None):
        arr = np.asarray(self._data)
        return arr.astype(dtype) if dtype is not None else arr

    def numpy(self):
        return np.asarray(self._data)

    # -- sequence helpers ---------------------------------------------------
    def last_level_offsets(self):
        """Offsets of the finest level, or trivial [0, N] when lod is empty."""
        if self._lod:
            return list(self._lod[-1])
        n = self._data.shape[0] if self._data is not None else 0
        return [0, n]

    def num_sequences(self):
        return len(self.last_level_offsets()) - 1

    def __repr__(self):
        shp = None if self._data is None else tuple(self._data.shape)
        return f"LoDTensor(shape={shp}, lod={self._lod})"


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """reference python/paddle/fluid/lod_tensor.py create_lod_tensor."""
    if isinstance(data, list):
        # list of lists -> flatten; infer lengths
        flattened = [item for seq in data for item in seq]
        lengths = [len(seq) for seq in data]
        arr = np.asarray(flattened)
        if arr.ndim == 1:
            arr = arr.reshape(-1, 1)
        t = LoDTensor(arr)
        t.set_recursive_sequence_lengths([lengths])
        return t
    t = LoDTensor(np.asarray(data))
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    assert t.has_valid_recursive_sequence_lengths(), "invalid lod lengths for data shape"
    return t


def create_bucketed_seq_tensor(seqs, bucket, place=None, dtype="int64",
                               pad_value=0):
    """LoD -> dense bridge for compile-stable sequence feeding (r4 VERDICT
    task 3): concatenate variable-length sequences and TAIL-PAD the flat
    data up to the next multiple of `bucket` tokens. The result is a
    SeqTensor whose data shape is a bucket multiple — batches padded to the
    same bucket compile ONCE and can ride Executor.run(iters=K) — while
    lengths stay exact: every lod_aware kernel masks via
    SeqTensor.segment_ids()/token_mask(), which classify the tail rows as
    padding, so the math matches the unpadded feed.

    seqs: list of per-sequence 1-D/2-D arrays (a batch). bucket: pad total
    tokens up to a multiple of this. Returns a SeqTensor feedable wherever
    a LoDTensor feed is accepted.
    """
    import jax.numpy as jnp

    from .registry import SeqTensor

    arrs = [np.asarray(s, dtype=dtype) for s in seqs]
    arrs = [a.reshape(-1, 1) if a.ndim == 1 else a for a in arrs]
    lengths = np.asarray([a.shape[0] for a in arrs], np.int32)
    flat = np.concatenate(arrs, axis=0) if arrs else \
        np.zeros((0, 1), dtype=dtype)
    total = flat.shape[0]
    bucket = max(1, int(bucket))
    padded_total = -(-total // bucket) * bucket
    if padded_total > total:
        pad = np.full((padded_total - total,) + flat.shape[1:], pad_value,
                      dtype=flat.dtype)
        flat = np.concatenate([flat, pad], axis=0)
    return SeqTensor(jnp.asarray(flat), jnp.asarray(lengths))


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place, low, high):
    total = sum(recursive_seq_lens[-1])
    shape = [total] + list(base_shape)
    data = np.random.randint(low, high + 1, size=shape).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
