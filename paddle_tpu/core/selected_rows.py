"""SelectedRows: the sparse row-set tensor type.

Reference parity: paddle/fluid/framework/selected_rows.h:26 (rows_ +
value_ + height_) and its kernels (operators/math/selected_rows_functor.cc:
merge_add, scatter update paths).

Two representations:

- `SelectedRows` — (rows, values, height). `rows` may contain duplicates
  (like the reference); consumers merge. Registered as a jax pytree so a
  sparse gradient can flow THROUGH a jit trace as a pair of static-shape
  arrays (ids + grad rows) — the TPU-idiomatic form of a sparse update:
  the optimizer does one scatter-add instead of materializing a dense
  [vocab, dim] gradient.

- `SparseTable` — the parameter-server side auto-growing hash table
  (reference lookup_sparse_table_op.cc AutoGrownIndex + framework
  selected_rows.h Get/Set). Host-only, numpy-backed, keyed by raw id so a
  mod-sharded pserver never rebases indices. Rows are initialized on first
  touch with a deterministic per-id uniform draw, so recovery/re-shard
  reproduces the same init.
"""

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["SelectedRows", "SparseTable", "merge_selected_rows"]


@jax.tree_util.register_pytree_node_class
class SelectedRows:
    """rows: int [R]; values: [R, ...dim]; height: logical dim-0 size."""

    def __init__(self, rows, values, height):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        rows, values = children
        return cls(rows, values, height)

    @property
    def dtype(self):
        return self.values.dtype

    def to_dense(self):
        """Scatter-add into a dense [height, ...dim] array (duplicates sum)."""
        v = jnp.asarray(self.values)
        dense = jnp.zeros((self.height,) + v.shape[1:], v.dtype)
        return dense.at[jnp.asarray(self.rows)].add(v)

    def __repr__(self):
        return (f"SelectedRows(rows={np.shape(self.rows)}, "
                f"values={np.shape(self.values)}, height={self.height})")


def merge_selected_rows(sr):
    """Host-side duplicate-row merge (reference
    math::scatter::MergeAdd) -> SelectedRows with unique, sorted rows."""
    rows = np.asarray(sr.rows).reshape(-1)
    values = np.asarray(sr.values).reshape(rows.shape[0], -1)
    uniq, inv = np.unique(rows, return_inverse=True)
    merged = np.zeros((uniq.shape[0], values.shape[1]), values.dtype)
    np.add.at(merged, inv, values)
    merged = merged.reshape((uniq.shape[0],) + tuple(np.shape(sr.values)[1:]))
    return SelectedRows(uniq, merged, sr.height)


class SparseTable:
    """Auto-growing embedding table for the pserver path.

    reference lookup_sparse_table_op.cc (auto_grown gather with uniform
    init between min/max) + the distributed table's sgd update
    (distribute_transpiler.py _create_table_optimize_block).
    """

    def __init__(self, value_dim, height=None, dtype="float32",
                 init_low=-0.05, init_high=0.05, seed=0):
        self.value_dim = int(value_dim)
        self.height = height  # logical vocab size (None = unbounded)
        self.dtype = np.dtype(dtype)
        self.init_low = float(init_low)
        self.init_high = float(init_high)
        self.seed = int(seed)
        self._index = {}           # id -> row in _data[:_size]
        self._data = np.zeros((0, self.value_dim), self.dtype)
        self._size = 0             # rows in use (capacity grows geometrically)

    def __len__(self):
        return len(self._index)

    def rows(self):
        """Known ids, in insertion order."""
        return np.fromiter(self._index.keys(), dtype=np.int64,
                           count=len(self._index))

    def _init_row(self, id_):
        rng = np.random.RandomState((self.seed * 0x9E3779B1 + int(id_))
                                    & 0x7FFFFFFF)
        return rng.uniform(self.init_low, self.init_high,
                           self.value_dim).astype(self.dtype)

    def _grow(self, ids):
        # dedupe while preserving order: a repeated unseen id must claim
        # exactly one row (duplicates would orphan rows forever)
        new = list(dict.fromkeys(i for i in ids if i not in self._index))
        if not new:
            return
        need = self._size + len(new)
        if need > self._data.shape[0]:
            # geometric growth: amortized O(rows) total copy instead of the
            # O(rows^2) a concatenate-per-miss would cost on the prefetch
            # hot path (held under the server lock)
            cap = max(need, 2 * self._data.shape[0], 64)
            grown = np.zeros((cap, self.value_dim), self.dtype)
            grown[:self._size] = self._data[:self._size]
            self._data = grown
        for i in new:
            self._data[self._size] = self._init_row(i)
            self._index[int(i)] = self._size
            self._size += 1

    def gather(self, ids, auto_grow=True):
        """rows for `ids` [N] -> [N, value_dim]; unknown ids are initialized
        (auto_grow) or returned as zeros."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        if self.height is not None and ids.size and \
                (ids.min() < 0 or ids.max() >= self.height):
            raise IndexError(
                f"sparse-table id out of range [0, {self.height}): "
                f"{ids.min()}..{ids.max()}")
        if auto_grow:
            self._grow(ids.tolist())
            idx = np.fromiter((self._index[int(i)] for i in ids),
                              dtype=np.int64, count=ids.size)
            return self._data[idx]
        outv = np.zeros((ids.size, self.value_dim), self.dtype)
        for k, i in enumerate(ids):
            j = self._index.get(int(i))
            if j is not None:
                outv[k] = self._data[j]
        return outv

    def scatter_sub(self, rows, deltas):
        """param[rows] -= deltas (rows must be unique; grow-on-miss)."""
        rows = np.asarray(rows).reshape(-1).astype(np.int64)
        deltas = np.asarray(deltas, self.dtype).reshape(rows.size,
                                                        self.value_dim)
        self._grow(rows.tolist())
        idx = np.fromiter((self._index[int(i)] for i in rows),
                          dtype=np.int64, count=rows.size)
        np.subtract.at(self._data, idx, deltas)

    def sgd_update(self, grad, lr):
        """Apply one SGD step from a SelectedRows gradient."""
        m = merge_selected_rows(grad)
        self.scatter_sub(m.rows, np.asarray(m.values) * float(lr))

    def to_dense(self, height=None):
        """Dense [height, value_dim] snapshot. Rows never touched by a
        lookup/update are ZERO here — not the deterministic first-touch
        init. A consumer that needs dense/sparse parity on never-seen ids
        must trigger the init by looking the id up (auto-grow) first."""
        height = height if height is not None else self.height
        if height is None:
            height = (max(self._index) + 1) if self._index else 0
        dense = np.zeros((int(height), self.value_dim), self.dtype)
        for i, j in self._index.items():
            if 0 <= i < height:
                dense[i] = self._data[j]
        return dense
