"""Compile-time per-op shape contracts (r2 VERDICT missing #5).

Reference parity: every reference op declares an InferShape checked when the
OpDesc is built (framework/shape_inference.h:1, op_desc.cc InferShape call),
so a malformed program fails at append_op with op context — not deep inside
a jax trace. Same contract here: `infer(op, block)` runs from
Block.append_op for every op type with a registered contract.

Conventions:
- a Variable's shape may be None (unknown) — contracts skip checks that
  need it rather than failing;
- -1 is the dynamic (batch) dim and matches anything;
- contracts VALIDATE input consistency and SET output var shapes
  (authoritative: they overwrite layer-side ad-hoc shape math so the two
  can never drift).

Kept free of jax imports so framework.py can use it without pulling the
backend in at program-build time.
"""

import math

_contracts = {}


class ShapeError(ValueError):
    pass


def register_infer_shape(*types):
    def deco(fn):
        for t in types:
            _contracts[t] = fn
        return fn
    return deco


def has_contract(type):
    return type in _contracts


class InferShapeContext:
    """Mirrors the reference InferShapeContext surface
    (shape_inference.h:28-60): typed access to input dims + output dim
    setting, by slot name."""

    def __init__(self, op, block):
        self.op = op
        self.block = block

    # -- vars -----------------------------------------------------------
    def _var(self, name):
        b = self.block
        while b is not None:
            v = b.vars.get(name)
            if v is not None:
                return v
            b = b.parent_block
        return None

    def has_input(self, slot):
        return bool(self.op.inputs.get(slot))

    def has_output(self, slot):
        return bool(self.op.outputs.get(slot))

    def input_dim(self, slot, i=0):
        names = self.op.inputs.get(slot) or []
        if i >= len(names):
            return None
        v = self._var(names[i])
        return tuple(v.shape) if v is not None and v.shape is not None \
            else None

    def input_dims(self, slot):
        return [self.input_dim(slot, i)
                for i in range(len(self.op.inputs.get(slot) or []))]

    def set_output_dim(self, slot, dim, i=0):
        names = self.op.outputs.get(slot) or []
        if i >= len(names):
            return
        v = self._var(names[i])
        if v is not None and dim is not None:
            v.shape = tuple(int(d) for d in dim)

    def attr(self, name, default=None):
        return self.op.attrs.get(name, default)

    def enforce(self, cond, msg):
        if not cond:
            raise ShapeError(msg)


def infer(op, block):
    """Run the contract for op.type, if any, with op context on failure."""
    fn = _contracts.get(op.type)
    if fn is None:
        return
    ctx = InferShapeContext(op, block)
    try:
        fn(ctx)
    except ShapeError as e:
        raise ShapeError(
            f"InferShape failed for op '{op.type}' "
            f"(inputs={dict(op.inputs)}, attrs="
            f"{ {k: v for k, v in op.attrs.items() if not k.startswith('op_')} }): {e}"
        ) from None


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------
def _dim_match(a, b):
    return a == b or a == -1 or b == -1


def _shapes_match(a, b):
    return len(a) == len(b) and all(_dim_match(x, y) for x, y in zip(a, b))


def _numel(shape):
    n = 1
    for d in shape:
        if d == -1:
            return None
        n *= d
    return n


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def _conv_out(in_size, k, pad, stride, dilation):
    if in_size in (-1, None):
        return -1
    return (in_size + 2 * pad - (dilation * (k - 1) + 1)) // stride + 1


def _pool_out(in_size, k, pad, stride, ceil_mode):
    if in_size in (-1, None):
        return -1
    num = in_size - k + 2 * pad
    return (math.ceil(num / stride) if ceil_mode else num // stride) + 1


# ---------------------------------------------------------------------------
# contracts — the high-traffic families (conv/pool/matmul/elementwise/
# reductions/reshape and friends)
# ---------------------------------------------------------------------------
@register_infer_shape("conv2d", "depthwise_conv2d")
def _conv2d(ctx):
    x = ctx.input_dim("Input")
    w = ctx.input_dim("Filter")
    if x is None or w is None:
        return
    ctx.enforce(len(x) == 4, f"Input must be NCHW 4-D, got {x}")
    ctx.enforce(len(w) == 4, f"Filter must be [M, C/g, kh, kw], got {w}")
    groups = ctx.attr("groups", 1) or 1
    ctx.enforce(_dim_match(x[1], w[1] * groups),
                f"in_channels {x[1]} != filter_channels {w[1]} * groups "
                f"{groups}")
    ctx.enforce(w[0] % groups == 0,
                f"num_filters {w[0]} not divisible by groups {groups}")
    s = _pair(ctx.attr("strides", [1, 1]))
    p = _pair(ctx.attr("paddings", [0, 0]))
    d = _pair(ctx.attr("dilations", [1, 1]))
    oh = _conv_out(x[2], w[2], p[0], s[0], d[0])
    ow = _conv_out(x[3], w[3], p[1], s[1], d[1])
    ctx.enforce(oh != 0 and ow != 0 and (oh > 0 or oh == -1)
                and (ow > 0 or ow == -1),
                f"empty conv output {oh}x{ow} for input {x[2:]}, filter "
                f"{w[2:]}, stride {s}, padding {p}, dilation {d}")
    ctx.set_output_dim("Output", (x[0], w[0], oh, ow))


@register_infer_shape("pool2d")
def _pool2d(ctx):
    x = ctx.input_dim("X")
    if x is None:
        return
    ctx.enforce(len(x) == 4, f"X must be NCHW 4-D, got {x}")
    if ctx.attr("global_pooling", False):
        ctx.set_output_dim("Out", (x[0], x[1], 1, 1))
        return
    k = _pair(ctx.attr("ksize", [1, 1]))
    s = _pair(ctx.attr("strides", [1, 1]))
    p = _pair(ctx.attr("paddings", [0, 0]))
    ceil_mode = ctx.attr("ceil_mode", False)
    oh = _pool_out(x[2], k[0], p[0], s[0], ceil_mode)
    ow = _pool_out(x[3], k[1], p[1], s[1], ceil_mode)
    ctx.enforce((oh > 0 or oh == -1) and (ow > 0 or ow == -1),
                f"empty pool output {oh}x{ow} for input {x[2:]}, ksize {k}, "
                f"stride {s}, padding {p}")
    ctx.set_output_dim("Out", (x[0], x[1], oh, ow))


@register_infer_shape("mul")
def _mul(ctx):
    x = ctx.input_dim("X")
    y = ctx.input_dim("Y")
    if x is None or y is None:
        return
    xnc = ctx.attr("x_num_col_dims", 1)
    ync = ctx.attr("y_num_col_dims", 1)
    ctx.enforce(len(x) > xnc, f"X rank {len(x)} <= x_num_col_dims {xnc}")
    # reference mul_op InferShape: Y rank strictly greater than
    # y_num_col_dims, else y[ync:] is empty and Out silently loses cols
    ctx.enforce(len(y) > ync, f"Y rank {len(y)} <= y_num_col_dims {ync}")
    kx = _numel(x[xnc:])
    ky = _numel(y[:ync])
    if kx is not None and ky is not None:
        ctx.enforce(kx == ky,
                    f"flattened inner dims mismatch: X{x} cols {kx} vs "
                    f"Y{y} rows {ky}")
    ctx.set_output_dim("Out", tuple(x[:xnc]) + tuple(y[ync:]))


@register_infer_shape("matmul")
def _matmul(ctx):
    x = ctx.input_dim("X")
    y = ctx.input_dim("Y")
    if x is None or y is None:
        return
    tx, ty = ctx.attr("transpose_X", False), ctx.attr("transpose_Y", False)
    xs, ys = list(x), list(y)
    if len(xs) == 1:
        xs = [1, xs[0]]
    if len(ys) == 1:
        ys = [ys[0], 1]
    if tx:
        xs[-2], xs[-1] = xs[-1], xs[-2]
    if ty:
        ys[-2], ys[-1] = ys[-1], ys[-2]
    ctx.enforce(_dim_match(xs[-1], ys[-2]),
                f"contraction mismatch: X{x} (tx={tx}) K={xs[-1]} vs "
                f"Y{y} (ty={ty}) K={ys[-2]}")
    batch = xs[:-2] if len(xs) >= len(ys) else ys[:-2]
    # mirror the kernel (math_ops.py matmul_op) and reference
    # matmul_op.cc:306-317: the dim inserted to pad a 1-D operand is
    # squeezed back out of Out (-2 slot for X, -1 slot for Y)
    tail = [xs[-2], ys[-1]]
    if len(y) == 1:
        tail.pop(1)
    if len(x) == 1:
        tail.pop(0)
    out = list(batch) + tail
    ctx.set_output_dim("Out", tuple(out) if out else (1,))


@register_infer_shape(
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow")
def _elementwise(ctx):
    x = ctx.input_dim("X")
    y = ctx.input_dim("Y")
    if x is not None and y is not None:
        axis = ctx.attr("axis", -1)
        if axis is None:
            axis = -1
        ctx.enforce(len(y) <= len(x),
                    f"Y rank {len(y)} > X rank {len(x)}")
        # Reference broadcast rule (elementwise_op_function.h): Y is aligned
        # at `axis` (default: trailing); trailing size-1 dims of Y are
        # trimmed before alignment, and any size-1 Y dim broadcasts against
        # the corresponding X dim — a scalar/all-ones Y matches any X.
        # The runtime kernel (util.bcast_y_to_x + numpy broadcasting) accepts
        # exactly this, so the contract must too.
        if len(y) == len(x):
            for i in range(len(x)):
                ctx.enforce(_dim_match(x[i], y[i]) or y[i] == 1,
                            f"same-rank elementwise shape mismatch: X{x} vs "
                            f"Y{y}")
        else:
            # default axis aligns the UNtrimmed Y rank (reference computes
            # axis before trim_trailing_singular_dims)
            a = axis if axis >= 0 else len(x) - len(y)
            yr = len(y)
            while yr > 1 and y[yr - 1] == 1:
                yr -= 1
            ctx.enforce(0 <= a <= len(x) - yr,
                        f"axis {axis} out of range for X{x} vs Y{y}")
            for i in range(yr):
                ctx.enforce(_dim_match(x[a + i], y[i]) or y[i] == 1,
                            f"dim {a + i}: X{x} vs Y{y} (axis={axis})")
    if x is not None:
        ctx.set_output_dim("Out", x)


@register_infer_shape(
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod")
def _reduce(ctx):
    x = ctx.input_dim("X")
    if x is None:
        return
    if ctx.attr("reduce_all", False):
        ctx.set_output_dim("Out", (1,))
        return
    dim = ctx.attr("dim", 0)
    dims = [dim] if isinstance(dim, int) else list(dim)
    for d in dims:
        ctx.enforce(-len(x) <= d < len(x),
                    f"reduce dim {d} out of range for shape {x}")
    dims = [d % len(x) for d in dims]
    keep = ctx.attr("keep_dim", False)
    out = []
    for i, s in enumerate(x):
        if i in dims:
            if keep:
                out.append(1)
        else:
            out.append(s)
    ctx.set_output_dim("Out", tuple(out) if out else (1,))


@register_infer_shape("reshape")
def _reshape(ctx):
    x = ctx.input_dim("X")
    tgt = list(ctx.attr("shape", []))
    ctx.enforce(tgt.count(-1) <= 1, f"more than one -1 in shape {tgt}")
    if x is None:
        return
    out = []
    for i, d in enumerate(tgt):
        if d == 0:
            ctx.enforce(i < len(x),
                        f"shape[{i}]=0 but X rank is only {len(x)}")
            out.append(x[i])
        else:
            out.append(d)
    nx = _numel(x)
    if nx is not None:
        known = _numel([d for d in out if d != -1])
        if -1 in out:
            if known not in (None, 0):
                ctx.enforce(nx % known == 0,
                            f"cannot infer -1: numel {nx} not divisible by "
                            f"{known} (shape {tgt}, X{x})")
                out[out.index(-1)] = nx // known
        elif known is not None:
            ctx.enforce(known == nx,
                        f"reshape numel mismatch: X{x} has {nx}, shape "
                        f"{tgt} wants {known}")
    ctx.set_output_dim("Out", tuple(out))


@register_infer_shape("transpose")
def _transpose(ctx):
    x = ctx.input_dim("X")
    perm = list(ctx.attr("axis", []))
    if x is None:
        return
    ctx.enforce(sorted(perm) == list(range(len(x))),
                f"perm {perm} is not a permutation of rank {len(x)}")
    ctx.set_output_dim("Out", tuple(x[p] for p in perm))


@register_infer_shape("concat")
def _concat(ctx):
    xs = [s for s in ctx.input_dims("X") if s is not None]
    if not xs:
        return
    axis = ctx.attr("axis", 0)
    r = len(xs[0])
    ctx.enforce(-r <= axis < r, f"concat axis {axis} out of range ({r}-D)")
    axis %= r
    total = 0
    for s in xs:
        ctx.enforce(len(s) == r, f"rank mismatch among inputs: {xs}")
        for i in range(r):
            if i != axis:
                ctx.enforce(_dim_match(s[i], xs[0][i]),
                            f"dim {i} mismatch among concat inputs: {xs}")
        total = -1 if (total == -1 or s[axis] == -1) else total + s[axis]
    out = list(xs[0])
    out[axis] = total
    ctx.set_output_dim("Out", tuple(out))


@register_infer_shape("softmax")
def _softmax(ctx):
    x = ctx.input_dim("X")
    if x is not None:
        ctx.set_output_dim("Out", x)


@register_infer_shape("cross_entropy")
def _cross_entropy(ctx):
    x = ctx.input_dim("X")
    lab = ctx.input_dim("Label")
    if x is None:
        return
    ctx.enforce(len(x) >= 2, f"X must be at least 2-D [N, C], got {x}")
    if lab is not None:
        ctx.enforce(len(lab) == len(x),
                    f"Label rank {len(lab)} != X rank {len(x)}")
        for i in range(len(x) - 1):
            ctx.enforce(_dim_match(x[i], lab[i]),
                        f"batch dims mismatch: X{x} vs Label{lab}")
        if ctx.attr("soft_label", False):
            ctx.enforce(_dim_match(lab[-1], x[-1]),
                        f"soft_label needs Label{lab} last dim == C {x[-1]}")
        else:
            ctx.enforce(lab[-1] == 1,
                        f"hard-label Label{lab} last dim must be 1")
    ctx.set_output_dim("Y", tuple(x[:-1]) + (1,))


@register_infer_shape("softmax_with_cross_entropy")
def _softmax_xent(ctx):
    x = ctx.input_dim("Logits")
    lab = ctx.input_dim("Label")
    if x is None:
        return
    if lab is not None and not ctx.attr("soft_label", False):
        ctx.enforce(lab[-1] == 1,
                    f"hard-label Label{lab} last dim must be 1")
    ctx.set_output_dim("Softmax", x)
    ctx.set_output_dim("Loss", tuple(x[:-1]) + (1,))


@register_infer_shape("batch_norm")
def _batch_norm(ctx):
    x = ctx.input_dim("X")
    if x is None:
        return
    ctx.enforce(2 <= len(x) <= 5, f"X rank must be 2..5, got {x}")
    c = x[1]
    for slot in ("Scale", "Bias", "Mean", "Variance"):
        s = ctx.input_dim(slot)
        if s is not None and c != -1:
            ctx.enforce(len(s) == 1 and _dim_match(s[0], c),
                        f"{slot}{s} must be [{c}]")
    ctx.set_output_dim("Y", x)


@register_infer_shape("lookup_table")
def _lookup_table(ctx):
    w = ctx.input_dim("W")
    ids = ctx.input_dim("Ids")
    if w is None:
        return
    ctx.enforce(len(w) == 2, f"W must be 2-D [V, D], got {w}")
    if ids is not None:
        ctx.enforce(_dim_match(ids[-1], 1), f"Ids{ids} last dim must be 1")
        ctx.set_output_dim("Out", tuple(ids[:-1]) + (w[1],))


@register_infer_shape("mean")
def _mean(ctx):
    ctx.set_output_dim("Out", (1,))


@register_infer_shape("sum")
def _sum(ctx):
    xs = [s for s in ctx.input_dims("X") if s is not None]
    for s in xs[1:]:
        ctx.enforce(_shapes_match(s, xs[0]),
                    f"sum inputs must agree in shape: {xs}")
    if xs:
        ctx.set_output_dim("Out", xs[0])


@register_infer_shape("scale", "cast", "relu", "sigmoid", "tanh", "abs",
                      "exp", "sqrt", "square", "softsign", "softplus",
                      "ceil", "floor", "round", "reciprocal", "log",
                      "leaky_relu", "elu", "relu6", "hard_sigmoid",
                      "swish", "clip", "dropout")
def _same_shape(ctx):
    x = ctx.input_dim("X")
    if x is not None:
        ctx.set_output_dim("Out", x)
        if ctx.has_output("Mask"):  # dropout
            ctx.set_output_dim("Mask", x)


@register_infer_shape("top_k")
def _top_k(ctx):
    x = ctx.input_dim("X")
    if x is None:
        return
    k = ctx.attr("k", 1)
    if x[-1] != -1:
        ctx.enforce(k <= x[-1], f"k={k} > last dim of X{x}")
    out = tuple(x[:-1]) + (k,)
    ctx.set_output_dim("Out", out)
    ctx.set_output_dim("Indices", out)


@register_infer_shape("fill_constant")
def _fill_constant(ctx):
    shape = ctx.attr("shape")
    if shape is not None:
        ctx.set_output_dim("Out", tuple(int(s) for s in shape))


@register_infer_shape("split")
def _split(ctx):
    x = ctx.input_dim("X")
    if x is None:
        return
    axis = ctx.attr("axis", 0)
    ctx.enforce(-len(x) <= axis < len(x),
                f"split axis {axis} out of range for {x}")
    axis %= len(x)
    sections = ctx.attr("sections") or []
    num = ctx.attr("num", 0)
    n_out = len(ctx.op.outputs.get("Out") or [])
    if sections:
        ctx.enforce(len(sections) == n_out,
                    f"{len(sections)} sections vs {n_out} outputs")
        if x[axis] != -1:
            ctx.enforce(sum(sections) == x[axis],
                        f"sections {sections} don't sum to dim {x[axis]}")
        for i, s in enumerate(sections):
            out = list(x)
            out[axis] = s
            ctx.set_output_dim("Out", tuple(out), i)
    elif num:
        if x[axis] != -1:
            ctx.enforce(x[axis] % num == 0,
                        f"dim {x[axis]} not divisible by num {num}")
        for i in range(n_out):
            out = list(x)
            out[axis] = -1 if x[axis] == -1 else x[axis] // num
            ctx.set_output_dim("Out", tuple(out), i)
